#!/usr/bin/env python
"""Static lint gate — the ``.golangci.yml`` analog (VERDICT r3 #4).

The image ships no third-party linter (no ruff/flake8/pylint and installs
are off-limits), and ``compileall`` catches syntax only. This is a small
AST/text linter over the checks that pay for themselves in review:

  F401  unused import
  F403  ``from x import *``
  E501  line longer than the limit (default 88; noqa'able)
  E722  bare ``except:``
  W191  tab indentation
  W291  trailing whitespace
  W605  invalid escape sequence (via compile() in default warnings mode)

``# noqa`` (whole line) or ``# noqa: CODE`` suppress per line, same
convention as flake8. Exit 1 on any finding; prints ``path:line: CODE
message`` so editors can jump.

Usage: python hack/lint.py [paths...]   (default: the package, tests,
bench.py, __graft_entry__.py, hack/)
"""

from __future__ import annotations

import ast
import re
import sys
from pathlib import Path

MAX_LINE = 88
DEFAULT_PATHS = [
    "cron_operator_tpu", "tests", "hack",
    "bench.py", "__graft_entry__.py",
]
_NOQA = re.compile(r"#\s*noqa(?::\s*(?P<codes>[A-Z0-9, ]+))?", re.I)


def _noqa_codes(line: str):
    """None = no noqa; set() = blanket noqa; {codes} = specific."""
    m = _NOQA.search(line)
    if not m:
        return None
    codes = m.group("codes")
    if not codes:
        return set()
    return {c.strip().upper() for c in codes.split(",") if c.strip()}


class _ImportTracker(ast.NodeVisitor):
    """Collect imported names and every name usage; unused = F401."""

    def __init__(self) -> None:
        self.imports: dict[str, int] = {}  # bound name -> lineno
        self.star_imports: list[int] = []
        self.used: set[str] = set()

    def visit_Import(self, node: ast.Import) -> None:
        for alias in node.names:
            bound = alias.asname or alias.name.split(".")[0]
            self.imports[bound] = node.lineno
        self.generic_visit(node)

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.module == "__future__":  # compiler directive, always "used"
            return
        for alias in node.names:
            if alias.name == "*":
                self.star_imports.append(node.lineno)
                continue
            bound = alias.asname or alias.name
            self.imports[bound] = node.lineno
        self.generic_visit(node)

    def visit_Name(self, node: ast.Name) -> None:
        if isinstance(node.ctx, ast.Load):
            self.used.add(node.id)
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        # a.b.c marks `a` used; visit_Name on the root handles it.
        self.generic_visit(node)


def _string_referenced(name: str, tree: ast.Module) -> bool:
    """Names referenced in __all__ or string annotations count as used."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            if name in re.findall(r"[A-Za-z_][A-Za-z0-9_]*", node.value):
                return True
    return False


def lint_file(path: Path) -> list[tuple[int, str, str]]:
    findings: list[tuple[int, str, str]] = []
    text = path.read_text(encoding="utf-8")
    lines = text.splitlines()

    try:
        tree = ast.parse(text, filename=str(path))
    except SyntaxError as exc:
        return [(exc.lineno or 0, "E999", f"syntax error: {exc.msg}")]

    tracker = _ImportTracker()
    tracker.visit(tree)
    for name, lineno in sorted(tracker.imports.items(), key=lambda kv: kv[1]):
        if name == "_" or name.startswith("__"):
            continue
        if name not in tracker.used and not _string_referenced(name, tree):
            findings.append((lineno, "F401", f"{name!r} imported but unused"))
    for lineno in tracker.star_imports:
        findings.append((lineno, "F403", "star import"))

    for node in ast.walk(tree):
        if isinstance(node, ast.ExceptHandler) and node.type is None:
            findings.append((node.lineno, "E722", "bare except"))

    for i, line in enumerate(lines, 1):
        if len(line) > MAX_LINE:
            findings.append((i, "E501",
                             f"line too long ({len(line)} > {MAX_LINE})"))
        if line != line.rstrip():
            findings.append((i, "W291", "trailing whitespace"))
        if line.startswith("\t") or re.match(r" *\t", line):
            findings.append((i, "W191", "tab indentation"))

    # Apply noqa suppression.
    out = []
    for lineno, code, msg in findings:
        line = lines[lineno - 1] if 0 < lineno <= len(lines) else ""
        codes = _noqa_codes(line)
        if codes is not None and (not codes or code in codes):
            continue
        out.append((lineno, code, msg))
    return out


def main(argv: list[str]) -> int:
    root = Path(__file__).resolve().parent.parent
    targets = argv or DEFAULT_PATHS
    files: list[Path] = []
    for t in targets:
        p = (root / t) if not Path(t).is_absolute() else Path(t)
        if p.is_dir():
            files.extend(sorted(p.rglob("*.py")))
        elif p.suffix == ".py":
            files.append(p)
    total = 0
    for f in files:
        for lineno, code, msg in lint_file(f):
            print(f"{f.relative_to(root)}:{lineno}: {code} {msg}")
            total += 1
    if total:
        print(f"lint: {total} finding(s)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
