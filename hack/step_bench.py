"""Step-speed benchmark for the overlap-aware training executor (PR 12).

Measures what the executor rework actually bought, on real Trainer runs
(not synthetic kernels), and gates it:

- ``external_ab`` — THE acceptance gate. The same MLP training run on
  host-generated MNIST batches, two ways: **A** = the seed synchronous
  path (``steps_per_call=1``, ``stage_async=False``: one dispatch per
  step, batch staged inline on the step's critical path) vs **B** = the
  new default mode (``steps_per_call="auto"`` scan-chained chunks,
  double-buffered background staging). Verdict is OK iff B ≥
  ``--min-speedup`` (default 1.3×) samples/s over A AND the final
  params of a fresh A/B pair trained on identical streams are
  bit-exact (same math, fewer dispatches — the whole point).
- ``fused_vs_external`` — fused in-step data generation (the r5 zero
  host-traffic mode) vs the new external chunked+staged path: how close
  external data now gets to the fused ceiling.
- ``chain_floor`` — ops.microbench.timed_chain (the span-differenced
  primitive hack/mfu_probe.py and hack/mfu_attrib.py wrap) on a
  hand-built fused step: the pure device-compute floor per step.
  ``overlap_headroom_ms`` = A's per-step wall minus this floor — the
  host+dispatch slice the overlap machinery exists to hide.
- ``transformer`` — Bert-tiny MLM leg: flash-attention impl vs XLA
  attention through the full train step (flash runs interpret=True off
  TPU — correctness-checked, meaningless for speed; the JSON says which
  mode ran). The XLA side's tokens/s is the ``train-large`` rate.

Writes BENCH_STEP.json (one verdict over every leg). ``--check`` is the
CI-gate smoke: small sizes, transformer leg skipped, asserts bit-exact
parity and NONZERO OVERLAP (B's per-step host wait strictly below A's
inline staging cost) — not the 1.3× gate, which stays a full-run claim.
``--emit-matrix-seed PATH`` additionally writes the measured rates as a
fleet ``ThroughputMatrix`` sidecar (``{"alpha":…, "rates": {"<class>/
<slice>": rate}}`` — the format ``ThroughputMatrix.load_seed`` reads),
so a fresh operator's placement scorer starts from measured throughput
instead of the chips-proportional prior.

Run: ``make bench-step`` (full), ``make bench-step CHECK=1`` (smoke).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 ".jax_cache"),
)
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _r(x, nd=2):
    return None if x is None else round(x, nd)


def write_matrix_seed(path, slice_type, rates_by_class):
    """Write measured rates as a fleet ``ThroughputMatrix`` seed sidecar
    — the exact shape :meth:`ThroughputMatrix.load_seed` reads
    (``rates`` keyed ``"<workload-class>/<slice-type>"``; ``"*"`` is the
    scorer's any-class fallback row). ``rates_by_class`` maps workload
    class → measured rate; falsy rates are dropped, not zero-seeded.
    Returns the rates dict written."""
    rates = {
        f"{wclass}/{slice_type}": round(float(rate), 1)
        for wclass, rate in rates_by_class.items() if rate
    }
    doc = {"alpha": 0.3, "rates": rates, "source": "hack/step_bench.py"}
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
    os.replace(tmp, path)
    return rates


def _measure_run(make_trainer, make_batches, warm, steps, batch):
    """Wall-clock a Trainer over ``steps`` steady-state steps (compile +
    ``warm`` steps excluded via a first run() call on the same trainer;
    run()'s target is cumulative, so the second call runs exactly
    ``steps`` more). Returns (samples_per_s, per_step_ms, host_wait_ms)
    where host_wait_ms is the mean per-step data_s — inline staging cost
    on the synchronous path, residual stager wait on the async one."""
    tr = make_trainer()
    it = make_batches()
    waits = []

    def on_step(s):
        if tr.steps_done > warm or s.step > warm:
            waits.append(s.data_s)

    tr.run(it, warm, on_step=lambda s: None)
    t0 = time.perf_counter()
    tr.run(it, warm + steps, on_step=on_step)
    dt = time.perf_counter() - t0
    host_wait = sum(waits) / len(waits) if waits else 0.0
    return batch * steps / dt, dt / steps * 1e3, host_wait * 1e3


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default=None,
                    help="JSON artifact path (default BENCH_STEP.json; "
                         "never written in --check unless given)")
    ap.add_argument("--stdout", action="store_true",
                    help="print the JSON to stdout too")
    ap.add_argument("--check", action="store_true",
                    help="CI smoke: small sizes, no transformer leg; "
                         "fails on parity break or zero overlap")
    ap.add_argument("--steps", type=int, default=None,
                    help="timed steady-state steps per side")
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--min-speedup", type=float, default=1.3,
                    help="external_ab gate: B over A samples/s")
    ap.add_argument("--emit-matrix-seed", default=None, metavar="PATH",
                    help="write measured rates as a fleet "
                         "ThroughputMatrix seed sidecar")
    ap.add_argument("--skip-transformer", action="store_true")
    args = ap.parse_args()

    # Warmup and timed steps are multiples of the auto chunk (8): the
    # warm run must compile the SAME chunk length the timed segment uses
    # — a warm count below one chunk compiles a short program, then the
    # full-length chunk compiles inside the timed window and the "B"
    # number measures XLA, not the executor.
    _CHUNK = 8
    steps = args.steps or (48 if args.check else 96)
    steps = max(_CHUNK, (steps // _CHUNK) * _CHUNK)
    warm = 2 * _CHUNK
    batch = args.batch

    import jax
    import jax.numpy as jnp
    import numpy as np

    from cron_operator_tpu.models import MLP
    from cron_operator_tpu.ops.microbench import timed_chain
    from cron_operator_tpu.parallel import mesh_for_devices
    from cron_operator_tpu.workloads import data as datasets
    from cron_operator_tpu.workloads.train import TrainConfig, Trainer

    backend = jax.default_backend()
    on_tpu = backend not in ("cpu", "gpu")
    kind = jax.devices()[0].device_kind
    slice_type = backend if not on_tpu else kind.split()[0].lower()
    mesh = mesh_for_devices(jax.devices())

    model = MLP(features=(64,))
    init_params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 28, 28, 1))
    )["params"]
    apply_fn = lambda p, x: model.apply({"params": p}, x)  # noqa: E731

    def trainer(**cfg_kw):
        # Fresh trainer from the SAME init params each call — A and B
        # must start from identical weights for parity to mean anything.
        return Trainer(
            apply_fn,
            jax.tree_util.tree_map(jnp.copy, init_params),
            mesh,
            TrainConfig(optimizer="sgd", **cfg_kw),
        )

    cfg_a = dict(steps_per_call=1, stage_async=False)  # seed sync path
    cfg_b = dict(steps_per_call="auto", stage_async=True)  # new default

    # --- external_ab: the gate ------------------------------------------
    a_rate, a_ms, a_wait = _measure_run(
        lambda: trainer(**cfg_a),
        lambda: datasets.mnist_batches(batch, seed=5), warm, steps, batch,
    )
    b_rate, b_ms, b_wait = _measure_run(
        lambda: trainer(**cfg_b),
        lambda: datasets.mnist_batches(batch, seed=5), warm, steps, batch,
    )
    speedup = b_rate / a_rate if a_rate else None

    # Overlap proof, apples-to-apples: the SAME chunked path with the
    # stager forced synchronous pays the full stack+device_put inline;
    # the async wait must sit strictly below it (what the background
    # thread hid). Structural, not a cross-config timing race — this is
    # the --check assertion, robust on a loaded CI host.
    _, _, bs_wait = _measure_run(
        lambda: trainer(steps_per_call="auto", stage_async=False),
        lambda: datasets.mnist_batches(batch, seed=5), warm, steps, batch,
    )
    overlap_ms = bs_wait - b_wait

    # Bit-exact parity: fresh pair, identical streams, a step count that
    # straddles the auto chunk (8) with a non-divisible tail.
    psteps = 13
    tr_a, tr_b = trainer(**cfg_a), trainer(**cfg_b)
    tr_a.run(datasets.mnist_batches(batch, seed=9), psteps)
    tr_b.run(datasets.mnist_batches(batch, seed=9), psteps)
    la = jax.tree_util.tree_leaves(tr_a.state.params)
    lb = jax.tree_util.tree_leaves(tr_b.state.params)
    parity = all(
        np.array_equal(np.asarray(x), np.asarray(y)) for x, y in zip(la, lb)
    )
    auto_chunk = tr_b.resolved_steps_per_call

    external_ab = {
        "model": "mlp(64) mnist", "batch": batch, "steps": steps,
        "a_samples_per_s": _r(a_rate, 1), "b_samples_per_s": _r(b_rate, 1),
        "a_step_ms": _r(a_ms), "b_step_ms": _r(b_ms),
        "a_host_wait_ms": _r(a_wait, 3), "b_host_wait_ms": _r(b_wait, 3),
        "b_sync_stage_wait_ms": _r(bs_wait, 3),
        "overlap_hidden_ms_per_step": _r(overlap_ms, 3),
        "auto_steps_per_call": auto_chunk,
        "speedup_b_over_a": _r(speedup, 3),
        "min_speedup": args.min_speedup,
        "params_bit_exact": parity,
        "ok": bool(parity and speedup and speedup >= args.min_speedup),
    }

    # --- fused_vs_external ----------------------------------------------
    import itertools

    f_rate, f_ms, _ = _measure_run(
        lambda: Trainer(
            apply_fn, jax.tree_util.tree_map(jnp.copy, init_params), mesh,
            TrainConfig(optimizer="sgd", steps_per_call=8),
            sample_fn=datasets.mnist_sample(batch),
        ),
        lambda: itertools.repeat({}), warm, steps, batch,
    )
    fused_vs_external = {
        "fused_samples_per_s": _r(f_rate, 1), "fused_step_ms": _r(f_ms),
        "external_b_samples_per_s": _r(b_rate, 1),
        "external_over_fused": _r(b_rate / f_rate, 3) if f_rate else None,
    }

    # --- chain_floor (shared timed_chain primitive) ---------------------
    import optax

    tx = optax.sgd(1e-3)
    sample = datasets.mnist_sample(batch)
    from cron_operator_tpu.workloads.train import cross_entropy_loss

    def floor_step(carry):
        p, o, key = carry
        key, kb = jax.random.split(key)
        b = sample(kb)

        def loss(pp):
            return cross_entropy_loss(apply_fn(pp, b["x"]), b["y"])

        g = jax.grad(loss)(p)
        u, o = tx.update(g, o, p)
        return (optax.apply_updates(p, u), o, key)

    p0 = jax.tree_util.tree_map(jnp.copy, init_params)
    floor_t, _ = timed_chain(
        floor_step, (p0, tx.init(p0), jax.random.PRNGKey(2)), iters=8
    )
    chain_floor = {
        "floor_step_ms": _r(floor_t * 1e3 if floor_t else None, 3),
        "overlap_headroom_ms": _r(
            a_ms - floor_t * 1e3 if floor_t else None, 3
        ),
    }

    # --- transformer (flash vs xla through the full step) ---------------
    transformer = None
    if not (args.check or args.skip_transformer):
        from cron_operator_tpu.models import Bert, BertConfig

        # seq 128: the flash kernel's block size — smaller sequences
        # reject the Pallas path outright.
        tseq, tbatch, tsteps, twarm = 128, 4, 12, 4

        def bert_rate(impl):
            cfg = BertConfig.tiny(
                max_len=tseq, attention_impl=impl,
                attention_interpret=not on_tpu and impl == "flash",
            )
            m = Bert(cfg, mesh=mesh)
            params = m.init(
                jax.random.PRNGKey(0), jnp.zeros((1, tseq), jnp.int32)
            )["params"]
            tr = Trainer(
                lambda p, x: m.apply({"params": p}, x), params, mesh,
                TrainConfig(optimizer="sgd", seq_dim_in_batch=1,
                            labels_follow_seq=True, steps_per_call=4),
                sample_fn=datasets.token_sample(tbatch, tseq,
                                                cfg.vocab_size),
            )
            it = itertools.repeat({})
            tr.run(it, twarm)
            t0 = time.perf_counter()
            tr.run(it, twarm + tsteps)
            dt = time.perf_counter() - t0
            return tbatch * tseq * tsteps / dt

        xla_tps = bert_rate("xla")
        try:
            flash_tps = bert_rate("flash")
        except Exception as exc:  # noqa: BLE001 — interpret-mode flash
            flash_tps = None      # must not kill the artifact
            transformer_err = str(exc)[-300:]
        else:
            transformer_err = None
        transformer = {
            "model": "bert-tiny mlm", "seq": tseq, "batch": tbatch,
            "flash_mode": "mosaic" if on_tpu else "interpret",
            "xla_tokens_per_s": _r(xla_tps, 1),
            "flash_tokens_per_s": _r(flash_tps, 1),
            "flash_over_xla": (
                _r(flash_tps / xla_tps, 3) if flash_tps else None
            ),
            "error": transformer_err,
        }

    verdict = "OK" if external_ab["ok"] else "REGRESSION"
    report = {
        "backend": backend, "device_kind": kind,
        "slice_type": slice_type,
        "mode": "check" if args.check else "full",
        "timing": "steady-state Trainer wall clock, compile+warmup "
                  "excluded; chain floor via ops.microbench.timed_chain",
        "external_ab": external_ab,
        "fused_vs_external": fused_vs_external,
        "chain_floor": chain_floor,
        "transformer": transformer,
        "verdict": verdict,
    }

    if args.emit_matrix_seed:
        # train-small rides the measured MLP rate (and seeds the "*"
        # fallback row), train-large the transformer tokens/s when the
        # full run measured it.
        by_class = {"train-small": b_rate, "*": b_rate}
        if transformer and transformer.get("xla_tokens_per_s"):
            by_class["train-large"] = transformer["xla_tokens_per_s"]
        report["matrix_seed_rates"] = write_matrix_seed(
            args.emit_matrix_seed, slice_type, by_class
        )
        report["matrix_seed"] = args.emit_matrix_seed

    out_path = args.out or (None if args.check else "BENCH_STEP.json")
    if out_path and out_path != "/dev/null":
        tmp = out_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(report, f, indent=2)
            f.write("\n")
        os.replace(tmp, out_path)
    if args.stdout or not out_path:
        print(json.dumps(report))

    if args.check:
        # Smoke gate: the math must be identical and the overlap real.
        # The 1.3x throughput claim stays a full-run gate — a loaded CI
        # host must not flake the commit gate on a timing ratio.
        assert parity, "scan-chained params diverged from per-step path"
        assert overlap_ms > 0, (
            "async staging hid no host time (sync stage wait %.3f ms <= "
            "async wait %.3f ms)" % (bs_wait, b_wait)
        )
        return 0
    return 0 if verdict == "OK" else 1


if __name__ == "__main__":
    sys.exit(main())
