"""Standalone TPU device probe with hang diagnostics.

Round-4 answer to VERDICT.md weak #1 / next-round #1: three rounds of
bench runs fell back to CPU because ``jax.devices()`` on the tunneled
'axon' backend hung past the probe deadline, and the artifact recorded
*that* it hung but never *where*. This probe:

- arms ``faulthandler.dump_traceback_later`` (the same trick
  ``__graft_entry__.py`` uses) so every 60 s of hang dumps the blocking
  Python frame to stderr — a timeout now produces a stack, not silence;
- on success prints a JSON line with backend/devices and exits 0, so a
  parent (bench.py) can keep this process's warm compilation cache
  directory for the measured run.

The probe itself runs unbounded — the DEADLINE is the parent's job
(bench.py ``communicate(timeout=...)``, one long attempt instead of
round-3's 2x150 s that both failed), which kills this child and keeps
the last dump as the hang evidence.

Usage:  python hack/tpu_probe.py
Exit codes: 0 = device up, 2 = init raised, (killed by parent on hang).
"""

from __future__ import annotations

import faulthandler
import json
import os
import sys
import time


def main() -> int:
    # Dump the blocking stack every 60 s while init is in flight; a parent
    # that kills us on timeout still has the last dump on stderr.
    faulthandler.enable()
    faulthandler.dump_traceback_later(60, repeat=True, exit=False)

    t0 = time.time()
    try:
        import jax

        devices = jax.devices()
    except Exception as exc:  # deterministic failure, not a hang
        # Env/plugin mismatch self-heal: JAX_PLATFORMS pins a platform
        # name the installed plugin set doesn't register under (observed
        # r5: env said "axon" while the plugin registered as plain "tpu"
        # when the sitecustomize path was missing — and vice versa). One
        # re-exec with the pin cleared lets JAX auto-pick whatever
        # accelerator actually registered; the re-exec'd run's JSON
        # carries ``cleared_jax_platforms`` so the parent (bench.py) can
        # strip the pin from every LATER child too — healing only the
        # probe would leave prewarm/runner children failing identically.
        if (
            "not in the list of known backends" in str(exc)
            and os.environ.get("JAX_PLATFORMS")
            and os.environ.get("TPU_PROBE_REEXEC") != "1"
        ):
            faulthandler.cancel_dump_traceback_later()
            env = dict(os.environ)
            env.pop("JAX_PLATFORMS", None)
            env["TPU_PROBE_REEXEC"] = "1"
            os.execve(sys.executable, [sys.executable, __file__], env)
        print(
            json.dumps(
                {
                    "ok": False,
                    "error": f"{type(exc).__name__}: {exc}",
                    "elapsed_s": round(time.time() - t0, 1),
                }
            )
        )
        return 2
    finally:
        faulthandler.cancel_dump_traceback_later()

    print(
        json.dumps(
            {
                "ok": True,
                "backend": jax.default_backend(),
                "n": len(devices),
                "kind": devices[0].device_kind,
                "platform_version": getattr(
                    devices[0].client, "platform_version", ""
                ),
                "init_s": round(time.time() - t0, 1),
                # True when this is the self-healed re-exec (the original
                # JAX_PLATFORMS pin named an unregistered platform) — the
                # parent must clear the pin for its other children.
                "cleared_jax_platforms": (
                    os.environ.get("TPU_PROBE_REEXEC") == "1"
                ),
            }
        )
    )
    sys.stdout.flush()

    # Optionally hold the initialized client alive so a parent can reuse
    # this process as the prewarm worker (it signals us via stdin close).
    if os.environ.get("TPU_PROBE_HOLD") == "1":
        sys.stdin.read()
    return 0


if __name__ == "__main__":
    sys.exit(main())
