"""Observability verdict layer (``make obs-report`` → ``BENCH_OBS.json``).

Drives the REAL stack — ``APIServer`` + ``Persistence`` + the flight
recorder (``telemetry/audit.py``) + ``CronReconciler`` +
``LocalExecutor`` — and computes the goodput/SLO verdicts the
observability layer exists to answer:

- **flight_recorder** — audit ≡ WAL record for record
  (:meth:`AuditJournal.wal_check`), every fired tick present as a
  ``decision`` record matching ``cron_ticks_fired_total``, and the
  ``/debug/audit`` / ``/debug/traces`` bodies parse as bounded JSON.
- **scheduling_slo** — tick fired (the ``tick_fired`` audit record's
  wall-clock ``ts``) → the workload's first training step
  (``trainingProgress.first_step_at``, same clock domain): p95 must be
  under ``SCHED_SLO_P95_S``.
- **timeline** — the observatory's history layer: every fired tick is
  mirrored into the bounded ``TimeSeriesStore``, the stored maxima
  match the live counters, and one append costs ≤ the 5µs gate
  (``TIMESERIES_APPEND_GATE_US``).
- **deadline_slo** — per-Cron deadline accounting folded from audit
  records: every fired tick a hit, a synthetic fleet-shed a charged
  miss, hit-rate ≥ ``DEADLINE_HIT_RATE_FLOOR`` — and the whole
  observatory pass (report + rollup + /debug bodies) rv-bracketed to
  prove ZERO store/WAL writes.
- **utilization** — busy-chip-seconds ÷ capacity-chip-seconds per
  slice type, integrated from fleet samples on a simulated pool under
  a place/release schedule.
- **mfu_timeline** — a real (CPU) training run publishes the bounded
  per-step phase timeline (data/dispatch/device/ckpt) and a positive
  rolling-MFU estimate into ``trainingProgress``.
- **goodput** (full mode only) — the chaos soak's preempt-storm leg:
  real CPU-mesh training under preemption storms, productive ÷ total
  steps across every attempt chain, must clear
  ``chaos_soak.GOODPUT_FLOOR``.

``--check`` runs the fast legs only (simulated workloads, no real
multi-round training) — the CI smoke ``hack/ci_gate.sh`` runs on every
gate.

Verdict: ``OK`` iff every leg passes, else ``REGRESSION`` (exit 1).
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import time
from datetime import timedelta

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

CRON_API_VERSION = "apps.kubedl.io/v1alpha1"
WORKLOAD_API_VERSION = "kubeflow.org/v1"
WORKLOAD_KIND = "JAXJob"
NAMESPACE = "default"

#: Scheduling-latency SLO: p95 of (tick fired → first training step).
#: Simulated workloads complete their first "step" at executor pickup,
#: so this bounds the control plane + executor dispatch path itself.
SCHED_SLO_P95_S = 2.0

#: Sizes of the fast scenario (kept small: the CI gate runs --check).
OBS_CRONS = 6
OBS_ROUNDS = 4

#: Deadline-SLO verdict floor: fired-in-deadline ticks ÷ all accounted
#: ticks (the fast scenario fires every tick promptly; the one
#: synthetic shed keeps the rate just under 1.0).
DEADLINE_HIT_RATE_FLOOR = 0.9


def _cron(i: int) -> dict:
    return {
        "apiVersion": CRON_API_VERSION,
        "kind": "Cron",
        "metadata": {"name": f"obs-{i}", "namespace": NAMESPACE},
        "spec": {
            "schedule": "*/1 * * * *",
            "concurrencyPolicy": "Allow",
            "historyLimit": 2,
            "template": {"workload": {
                "apiVersion": WORKLOAD_API_VERSION,
                "kind": WORKLOAD_KIND,
                "metadata": {"annotations": {
                    # Simulated 10ms run: reports started_at/first_step_at
                    # like a real trainer, without JAX in the loop.
                    "tpu.kubedl.io/simulate-duration": "10ms",
                }},
                "spec": {"replicaSpecs": {"Worker": {"replicas": 1}}},
            }},
        },
    }


def _is_terminal(obj: dict) -> bool:
    for c in ((obj.get("status") or {}).get("conditions") or []):
        if c.get("type") in ("Succeeded", "Failed") and \
                c.get("status") == "True":
            return True
    return False


def _percentile(sorted_vals: list, q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
    return sorted_vals[idx]


def _time_calls(fn, repeat: int) -> float:
    """Mean µs per call."""
    t0 = time.perf_counter()
    for _ in range(repeat):
        fn()
    return (time.perf_counter() - t0) / repeat * 1e6


def run_fast_legs(rounds: int = OBS_ROUNDS, crons: int = OBS_CRONS) -> dict:
    """The flight-recorder + scheduling-SLO legs: fake-clock ticks over
    simulated workloads, real wall-clock dispatch underneath."""
    from cron_operator_tpu.backends.local import LocalExecutor
    from cron_operator_tpu.controller.cron_controller import CronReconciler
    from cron_operator_tpu.runtime.kube import APIServer
    from cron_operator_tpu.runtime.manager import Metrics
    from cron_operator_tpu.runtime.persistence import Persistence
    from cron_operator_tpu.telemetry import (
        DEFAULT_HISTORY_FAMILIES,
        TIMESERIES_APPEND_GATE_US,
        AuditJournal,
        FleetObservatory,
        TimeSeriesStore,
        Tracer,
    )
    from cron_operator_tpu.utils.clock import FakeClock

    tmp = tempfile.mkdtemp(prefix="obs-report-")
    clock = FakeClock()
    store = APIServer(clock=clock)
    metrics = Metrics()
    journal = AuditJournal()
    tracer = Tracer()
    journal.instrument(metrics)
    tracer.instrument(metrics)
    # The observatory layers under test: the history mirror on the live
    # registry, and the audit-record fold — exactly the cmd_start wiring.
    history = TimeSeriesStore()
    metrics.instrument(history, families=DEFAULT_HISTORY_FAMILIES)
    observatory = FleetObservatory(
        metrics=metrics, tracer=tracer, data_dir=tmp
    )
    journal.attach_observer(observatory.on_record)
    pers = Persistence(tmp, flush_interval_s=0)
    pers.instrument(metrics)
    pers.attach_audit(journal)
    pers.start(store)
    store.instrument(metrics)
    store.attach_audit(journal)
    ex = LocalExecutor(store, metrics=metrics, tracer=tracer, audit=journal)
    ex.start()
    rec = CronReconciler(store, metrics=metrics, tracer=tracer,
                         audit=journal)

    for i in range(crons):
        store.create(_cron(i))

    first_step_at: dict = {}

    def _sweep() -> None:
        for i in range(crons):
            rec.reconcile(NAMESPACE, f"obs-{i}")

    def _wait_terminal(deadline_s: float = 30.0) -> None:
        deadline = time.time() + deadline_s
        while time.time() < deadline:
            workloads = store.list(
                WORKLOAD_API_VERSION, WORKLOAD_KIND, namespace=NAMESPACE
            )
            for w in workloads:
                meta = w.get("metadata") or {}
                prog = (w.get("status") or {}).get("trainingProgress") or {}
                if prog.get("first_step_at") is not None:
                    first_step_at.setdefault(
                        meta.get("name", ""),
                        float(prog["first_step_at"]),
                    )
            if all(_is_terminal(w) for w in workloads):
                return
            time.sleep(0.02)

    for _ in range(rounds):
        clock.advance(timedelta(seconds=61))
        _sweep()
        _wait_terminal()
        _sweep()  # fold the settled round into history / GC
        pers.flush()

    # ---- flight recorder leg ---------------------------------------------
    wal = journal.wal_check(pers.records_appended)
    ticks_fired = int(metrics.get("cron_ticks_fired_total") or 0)
    tick_records = journal.records(kind="decision", event="tick_fired")
    audit_body = json.loads(
        journal.render_json({"kind": ["decision"], "limit": ["10"]})
    )
    traces_body = json.loads(tracer.render_json())
    endpoint_ok = (
        audit_body["matched"] <= 10
        and all(r["kind"] == "decision" for r in audit_body["records"])
        and isinstance(traces_body.get("traces"), list)
    )
    recorder = {
        "wal_check": wal,
        "ticks_fired_metric": ticks_fired,
        "tick_fired_audit_records": len(tick_records),
        "kind_totals": journal.kind_totals(),
        "audit_total": journal.total,
        "audit_dropped": journal.records_dropped,
        "debug_endpoints_ok": endpoint_ok,
        "ok": (
            wal["ok"]
            and ticks_fired > 0
            and len(tick_records) == ticks_fired
            and endpoint_ok
        ),
    }

    # ---- scheduling-latency SLO leg --------------------------------------
    lat = []
    for r in tick_records:
        name = r["key"].rsplit("/", 1)[-1]
        fs = first_step_at.get(name)
        if fs is not None:
            lat.append(max(0.0, fs - r["ts"]))
    lat.sort()
    slo = {
        "samples": len(lat),
        "p50_s": round(_percentile(lat, 0.50), 4),
        "p95_s": round(_percentile(lat, 0.95), 4),
        "max_s": round(lat[-1], 4) if lat else 0.0,
        "slo_p95_s": SCHED_SLO_P95_S,
        "ok": bool(lat) and _percentile(lat, 0.95) <= SCHED_SLO_P95_S,
    }

    # ---- timeline (history) leg ------------------------------------------
    # The mirrored counter history must agree with the live registry
    # (counters record their cumulative total, so the newest bucket max
    # IS the counter), and one append must clear the 5µs hot-path gate.
    bench_store = TimeSeriesStore()
    tick = [0.0]

    def _append_once():
        tick[0] += 0.01
        bench_store.append("bench_series", 1.0, ts=tick[0])

    append_us = min(_time_calls(_append_once, 500) for _ in range(3))
    fired_pts = history.snapshot("cron_ticks_fired_total")
    fired_max = max((p["max"] for p in fired_pts), default=0.0)
    timeline_body = json.loads(history.render_json(
        {"family": ["cron_ticks_fired_total"], "res": ["10s"]}
    ))
    timeline = {
        "append_us": round(append_us, 2),
        "append_gate_us": TIMESERIES_APPEND_GATE_US,
        "series_count": len(history.series_names()),
        "points_total": history.points_total,
        "fired_history_max": fired_max,
        "ok": (
            append_us <= TIMESERIES_APPEND_GATE_US
            and ticks_fired > 0
            and fired_max == float(ticks_fired)
            and len(timeline_body["series"]) == 1
            and timeline_body["series"]["cron_ticks_fired_total"]
        ),
    }
    timeline["ok"] = bool(timeline["ok"])

    # ---- deadline-SLO + zero-store-write leg ------------------------------
    # Every fired tick is a deadline hit (no startingDeadlineSeconds in
    # the scenario, and tick_fired lateness attrs flow through the audit
    # observer); one synthetic fleet-shed record proves sheds are
    # charged as misses. The whole observatory read side — report,
    # JSONL rollup, both /debug bodies — runs inside an rv + WAL
    # bracket: the accounting layer must add ZERO store writes.
    journal.record(
        "decision", "tick_shed", reason="FleetQueueFull",
        key=f"{WORKLOAD_API_VERSION}/{WORKLOAD_KIND}/{NAMESPACE}/obs-shed",
        cron=f"{NAMESPACE}/obs-0", tick="synthetic",
        lateness_s=1.0, deadline_s=30.0,
    )
    rv_before = int(getattr(store, "_rv", 0))
    wal_before = pers.records_appended
    obs_body = observatory.report()
    rollup_path = observatory.rollup()
    fleet_body = json.loads(observatory.render_json())
    json.loads(history.render_json({}))
    rv_after = int(getattr(store, "_rv", 0))
    wal_after = pers.records_appended
    rollup_lines = 0
    if rollup_path and os.path.exists(rollup_path):
        with open(rollup_path) as f:
            rollup_lines = sum(1 for ln in f if ln.strip())
    slo_body = obs_body["deadline_slo"]
    deadline = {
        "hits": slo_body["hits"],
        "misses": slo_body["misses"],
        "hit_rate": slo_body["hit_rate"],
        "hit_rate_floor": DEADLINE_HIT_RATE_FLOOR,
        "crons_tracked": len(slo_body["per_cron"]),
        "rollup_lines": rollup_lines,
        "store_writes_during_observatory": rv_after - rv_before,
        "wal_appends_during_observatory": wal_after - wal_before,
        "ok": (
            slo_body["hits"] == ticks_fired
            and slo_body["misses"] == 1
            and slo_body["hit_rate"] >= DEADLINE_HIT_RATE_FLOOR
            and rollup_lines >= 1
            and rv_after == rv_before
            and wal_after == wal_before
            and isinstance(fleet_body.get("observatory"), dict)
        ),
    }

    ex.stop()
    store.close()
    pers.close()
    journal.close()
    shutil.rmtree(tmp, ignore_errors=True)
    return {
        "flight_recorder": recorder,
        "scheduling_slo": slo,
        "timeline": timeline,
        "deadline_slo": deadline,
    }


def run_utilization_leg() -> dict:
    """Busy ÷ capacity chip-seconds per slice type, integrated by the
    observatory from fleet samples on a simulated heterogeneous pool
    (place 3 gangs → full, release 1 → partial), with a capacity flap
    shrinking the denominator for the flapped window."""
    from cron_operator_tpu.backends.tpu import slice_for
    from cron_operator_tpu.runtime.fleet import FleetScheduler, SliceType
    from cron_operator_tpu.runtime.manager import Metrics
    from cron_operator_tpu.telemetry import FleetObservatory

    metrics = Metrics()
    fleet = FleetScheduler(
        [
            SliceType("v5e-16", 2, slice_for("v5e", "4x4")),
            SliceType("cpu", 2, None),
        ],
        api=None, on_create=lambda w, t: None, metrics=metrics,
    )
    obs = FleetObservatory(metrics=metrics)
    obs.attach_fleet(fleet)

    def _wl(i: int) -> dict:
        return {
            "apiVersion": WORKLOAD_API_VERSION, "kind": WORKLOAD_KIND,
            "metadata": {"namespace": NAMESPACE, "name": f"util-{i}",
                         "annotations": {}},
            "spec": {"replicaSpecs": {"Worker": {"replicas": 1}}},
        }

    t = 0.0
    obs.sample_fleet(now_mono=t)  # baseline anchor (no dt yet)
    for i in range(3):  # 2 land on v5e-16 (higher prior rate), 1 on cpu
        fleet.submit(_wl(i))
    t += 10.0
    obs.sample_fleet(now_mono=t)
    fleet.release(NAMESPACE, "util-0")
    t += 10.0
    obs.sample_fleet(now_mono=t)
    util = obs.report()["utilization"]
    gauge = metrics.gauge('fleet_utilization{slice_type="v5e-16"}')
    return {
        "per_slice_type": util,
        "utilization_gauge_v5e": gauge,
        "ok": (
            bool(util)
            and any(row["utilization"] > 0 for row in util.values())
            and all(
                0.0 <= row["utilization"] <= 1.0 for row in util.values()
            )
            and all(
                row["busy_chip_s"] <= row["capacity_chip_s"] + 1e-9
                for row in util.values()
            )
            and gauge is not None
        ),
    }


def run_elasticity_leg() -> dict:
    """Bidirectional-elasticity observability: the REAL GrowPlanner over
    a simulated two-tier pool with the audit journal attached. The
    observatory must count the grow and the shrink-back decision
    (``fleet_grow``/``fleet_shrink`` audit kinds), integrate the
    reclaimed idle chip-seconds while the grown gang holds the loaned
    width, and both counters must land in metrics."""
    from cron_operator_tpu.runtime.fleet import FleetScheduler, parse_pool
    from cron_operator_tpu.runtime.manager import Metrics
    from cron_operator_tpu.telemetry import AuditJournal, FleetObservatory

    metrics = Metrics()
    journal = AuditJournal()
    recon = []

    class _Recorder:
        def reconfigure(self, ns, name, kind, api_version,
                        target_devices, reason):
            recon.append((name, int(target_devices), reason))
            return True

    fleet = FleetScheduler(
        parse_pool("narrow=1@2,wide=1@8"),
        backend=_Recorder(), metrics=metrics, audit=journal,
        on_create=lambda w, t: None,
        grow_enabled=True, grow_idle_pumps=2,
    )
    obs = FleetObservatory(metrics=metrics)
    obs.attach_fleet(fleet)
    journal.attach_observer(obs.on_record)

    def _wl(name: str, ann: dict) -> dict:
        return {
            "apiVersion": WORKLOAD_API_VERSION, "kind": WORKLOAD_KIND,
            "metadata": {"namespace": NAMESPACE, "name": name,
                         "annotations": ann},
            "spec": {},
        }

    # Blocker seizes the wide slice; the elastic job lands narrow.
    fleet.submit(_wl("blocker", {"tpu.kubedl.io/priority": "high"}))
    fleet.submit(_wl("growme", {
        "tpu.kubedl.io/elastic-resume": "true",
        "tpu.kubedl.io/param.devices": "2",
    }))
    obs.sample_fleet(now_mono=0.0)
    fleet.release(NAMESPACE, "blocker")
    for _ in range(2):  # hysteresis window, then the grow fires
        fleet.pump()
    grew = bool(recon) and recon[-1] == ("growme", 8, "FleetGrow")
    # Controller-side resume: the regrown attempt at the loaned width.
    fleet.submit(_wl("growme-r1", {
        "tpu.kubedl.io/elastic-resume": "true",
        "tpu.kubedl.io/param.devices": "8",
        "tpu.kubedl.io/resume-of": "growme",
        "tpu.kubedl.io/resume-cause": "grow",
        "tpu.kubedl.io/original-devices": "2",
    }))
    obs.sample_fleet(now_mono=10.0)  # 10s holding +6 loaned chips
    # Priority pressure pinned to the wide slice → planned shrink-back.
    fleet.submit(_wl("aggressor", {
        "tpu.kubedl.io/priority": "high",
        "tpu.kubedl.io/fleet-slice-type": "wide",
    }))
    shrank = any(r == ("growme-r1", 2, "FleetShrink") for r in recon)
    rep = obs.report()["elasticity"]
    return {
        "reconfigures": recon,
        "observatory": rep,
        "fleet_grows_total": metrics.get("fleet_grows_total"),
        "fleet_shrinks_total": metrics.get("fleet_shrinks_total"),
        "ok": (
            grew and shrank
            and rep["grows"] >= 1
            and rep["shrinks"] >= 1
            and rep["reclaimed_idle_chip_s"] > 0
            and (metrics.get("fleet_grows_total") or 0) >= 1
            and (metrics.get("fleet_shrinks_total") or 0) >= 1
        ),
    }


def run_mfu_leg() -> dict:
    """Step-profiler timeline + MFU estimator on ONE real (CPU) training
    run: the mnist entrypoint must publish a bounded per-step phase
    timeline and a positive rolling-MFU estimate into its progress."""
    from cron_operator_tpu.backends.registry import JobContext
    from cron_operator_tpu.workloads.entrypoints import mnist

    ctx = JobContext(
        name="obs-mfu", namespace=NAMESPACE,
        job={"metadata": {"annotations": {}}},
        params={
            "steps": "6", "batch_size": "32", "platform": "cpu",
            # One dispatch per step: the leg asserts per-step compile
            # flags (first step compiles, the rest reuse), which the
            # default scan-chained mode folds into one fused dispatch.
            "steps_per_call": "1", "stage_async": "0",
            # Synthetic per-chip peak: on host CPU no TPU family applies,
            # so the estimator's denominator comes from the override —
            # the verdict is presence + positivity, not an MFU range.
            "mfu": "1", "peak_flops_per_chip": "1e9",
        },
    )
    mnist(ctx)
    timeline = ctx.progress.get("step_timeline") or []
    phase_keys = {"step", "t", "step_s", "data_s", "dispatch_s",
                  "device_s", "ckpt_s", "compile"}
    mfu = ctx.progress.get("mfu")
    return {
        "timeline_entries": len(timeline),
        "first_entry": timeline[0] if timeline else None,
        "mfu": mfu,
        "steps_done": ctx.progress.get("steps_done"),
        "ok": (
            len(timeline) >= 6
            and all(phase_keys <= set(e) for e in timeline)
            and bool(timeline[0]["compile"])
            and not any(e["compile"] for e in timeline[1:])
            and mfu is not None and mfu > 0
        ),
    }


#: Distributed-trace leg: hard deadline for the whole topology round
#: trip (boot → traced POST → tick → runner → trace assembled).
DIST_DEADLINE_S = 60.0

#: Slack allowed between the trace's own wall time and the driver's
#: measured POST→assembled latency (the trace is a strict sub-interval
#: of the measurement, so this only absorbs clock skew between the
#: driver's reads and the spans' wall-clock stamps).
DIST_WALL_SLACK_S = 0.25


def _http_json(url: str, timeout: float = 5.0):
    import urllib.request

    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read().decode())


def _probe_port_base(tries: int = 40) -> int:
    """A port base where the supervisor's deterministic layout (router
    on base, shard api on base+1, WAL ship on base+51) is free."""
    import random
    import socket

    for _ in range(tries):
        base = random.randrange(20000, 55000)
        ok = True
        for port in (base, base + 1, base + 51):
            s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            try:
                s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
                s.bind(("127.0.0.1", port))
            except OSError:
                ok = False
                break
            finally:
                s.close()
        if ok:
            return base
    raise RuntimeError("no free port window found for the topology")


def run_distributed_leg() -> dict:
    """ONE trace across the real multi-process topology.

    Spawns the supervisor (router + shard leader + standby, real OS
    processes), POSTs a Cron through the router's front door with a
    driver-minted ``traceparent``, and asserts that a single cron tick
    produced a single trace whose spans come from >= 3 distinct
    processes (router, shard leader, runner subprocess), whose
    critical-path decomposition (route → admit → commit → fsync →
    submit → first_step) reconciles against the trace's wall time and
    stays inside the driver's measured end-to-end latency; that the
    cluster event timeline fanned in at the router saw the shard's
    lease acquisition; that I9 (audit ≡ WAL) holds on the serving
    shard; that the debug read path adds ZERO store/WAL writes; and
    that per-frame trace-context propagation clears its µs gate."""
    import signal
    import subprocess

    from cron_operator_tpu.api.scheme import default_scheme
    from cron_operator_tpu.runtime.cluster import (
        ClusterAPIServer,
        ClusterConfig,
    )
    from cron_operator_tpu.telemetry.trace import (
        TraceContext,
        new_span_id,
        new_trace_id,
        reset_current_trace,
        set_current_trace,
    )

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from controlplane_bench import _trace_ctx_microbench

    tmp = tempfile.mkdtemp(prefix="obs-dist-")
    base = _probe_port_base()
    router_url = f"http://127.0.0.1:{base}"
    shard_url = f"http://127.0.0.1:{base + 1}"
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env.setdefault("JAX_PLATFORMS", "cpu")
    sup = subprocess.Popen(
        [sys.executable, "-m", "cron_operator_tpu.cli.main", "start",
         "--shard-role", "supervisor", "--shards", "1",
         "--data-dir", tmp, "--port-base", str(base),
         "--zap-log-level", "warn",
         "--health-probe-bind-address", "0",
         "--metrics-bind-address", "0"],
        env=env, cwd=REPO_ROOT,
    )
    deadline = time.time() + DIST_DEADLINE_S
    leg: dict = {"port_base": base, "ok": False}
    api = ClusterAPIServer(
        config=ClusterConfig(server=router_url, qps=0),
        scheme=default_scheme(),
    )
    try:
        # ---- wait for the router (and behind it, the shard) ---------------
        ready = False
        while time.time() < deadline:
            try:
                api.list(CRON_API_VERSION, "Cron", namespace=NAMESPACE)
                ready = True
                break
            except Exception:
                time.sleep(0.2)
        leg["topology_ready"] = ready
        if not ready:
            return leg

        # ---- the traced write: one Cron through the front door ------------
        trace_id, root_span = new_trace_id(), new_span_id()
        leg["trace_id"] = trace_id
        cron = {
            "apiVersion": CRON_API_VERSION,
            "kind": "Cron",
            "metadata": {"name": "dist-0", "namespace": NAMESPACE},
            "spec": {
                "schedule": "@every 1s",
                "concurrencyPolicy": "Forbid",
                "historyLimit": 1,
                "template": {"workload": {
                    "apiVersion": WORKLOAD_API_VERSION,
                    "kind": WORKLOAD_KIND,
                    "metadata": {"annotations": {
                        # Pre-stamping the tick's trace id joins the
                        # scheduled tick to THIS traced request (the
                        # controller adopts it instead of minting).
                        "tpu.kubedl.io/trace-id": trace_id,
                        # Real subprocess isolation: the runner is the
                        # third OS process on the trace.
                        "tpu.kubedl.io/isolation": "subprocess",
                        "tpu.kubedl.io/entrypoint":
                            "cron_operator_tpu.workloads.smoke:run",
                        "tpu.kubedl.io/param.steps": "2",
                    }},
                    "spec": {"replicaSpecs": {"Worker": {"replicas": 1}}},
                }},
            },
        }
        t_post = time.time()
        token = set_current_trace(TraceContext(trace_id, root_span))
        try:
            api.create(cron)
        finally:
            reset_current_trace(token)

        # ---- poll the router's cluster trace assembly ---------------------
        trace_doc: dict = {}
        assembled = False
        while time.time() < deadline:
            try:
                trace_doc = _http_json(
                    f"{router_url}/debug/trace/{trace_id}"
                )
            except Exception:
                trace_doc = {}
            cp = trace_doc.get("critical_path") or {}
            pids = {
                p.get("pid") for p in trace_doc.get("processes") or []
                if p.get("pid") is not None
            }
            if cp.get("reconciles") and len(pids) >= 3:
                assembled = True
                break
            time.sleep(0.25)
        t_done = time.time()
        cp = trace_doc.get("critical_path") or {}
        pids = {
            p.get("pid") for p in trace_doc.get("processes") or []
            if p.get("pid") is not None
        }
        measured_e2e_s = t_done - t_post
        leg.update({
            "assembled": assembled,
            "span_count": len(trace_doc.get("spans") or []),
            "processes": trace_doc.get("processes"),
            "distinct_pids": len(pids),
            "orphan_spans": len(trace_doc.get("orphans") or []),
            "critical_path": cp,
            "measured_e2e_s": round(measured_e2e_s, 4),
        })
        wall_ok = (
            assembled
            and 0.0 < cp.get("wall_s", 0.0)
            <= measured_e2e_s + DIST_WALL_SLACK_S
        )
        leg["wall_within_measured"] = wall_ok

        # ---- cluster event timeline fan-in --------------------------------
        events_doc = {}
        try:
            events_doc = _http_json(f"{router_url}/debug/events")
        except Exception:
            pass
        events = events_doc.get("events") or []
        lease_seen = any(
            e.get("event") == "lease_acquired"
            and str(e.get("source", "")).startswith("shard-")
            for e in events
        )
        leg["events_total"] = len(events)
        leg["lease_acquired_seen"] = lease_seen

        # ---- standby liveness on the router's shard doc -------------------
        standby_attached = False
        try:
            shards_doc = _http_json(f"{router_url}/debug/shards")
            for doc in shards_doc.get("shards") or []:
                standby = (doc or {}).get("standby") or {}
                standby_attached = bool(standby.get("attached"))
        except Exception:
            pass
        leg["standby_attached"] = standby_attached

        # ---- quiesce: stop the ticking cron, wait for rv to settle --------
        try:
            api.delete(CRON_API_VERSION, "Cron", NAMESPACE, "dist-0")
        except Exception:
            pass

        def _shard_rv_wal() -> tuple:
            doc = _http_json(f"{shard_url}/debug/shards")
            sd = (doc.get("shards") or [{}])[0]
            return (
                int(sd.get("rv") or 0),
                int((sd.get("wal") or {}).get("records_appended") or 0),
            )

        stable_since = None
        last = None
        while time.time() < deadline:
            try:
                cur = _shard_rv_wal()
            except Exception:
                time.sleep(0.2)
                continue
            if cur != last:
                last, stable_since = cur, time.time()
            elif time.time() - stable_since >= 1.0:
                break
            time.sleep(0.2)

        # ---- zero-write read path: rv + WAL bracket the debug sweep -------
        rv_before = wal_before = rv_after = wal_after = None
        try:
            rv_before, wal_before = _shard_rv_wal()
            _http_json(f"{router_url}/debug/trace/{trace_id}")
            _http_json(f"{router_url}/debug/traces")
            _http_json(f"{router_url}/debug/events")
            _http_json(f"{router_url}/debug/shards")
            _http_json(f"{shard_url}/debug/events")
            rv_after, wal_after = _shard_rv_wal()
        except Exception:
            pass
        zero_write = (
            rv_before is not None
            and (rv_before, wal_before) == (rv_after, wal_after)
        )
        leg["store_writes_during_debug"] = (
            None if rv_after is None else rv_after - rv_before
        )
        leg["wal_appends_during_debug"] = (
            None if wal_after is None else wal_after - wal_before
        )
        leg["zero_write_read_path"] = zero_write

        # ---- I9 on the serving shard --------------------------------------
        audit_check = {}
        try:
            audit_check = _http_json(f"{shard_url}/debug/audit")
        except Exception:
            pass
        leg["audit_check"] = audit_check

        # ---- propagation overhead gate ------------------------------------
        try:
            bench = _trace_ctx_microbench()
        except AssertionError as err:
            bench = {"error": str(err)}
        leg["propagation"] = bench
        bench_ok = bool(bench) and "error" not in bench

        leg["ok"] = bool(
            assembled
            and not cp.get("missing")
            and wall_ok
            and lease_seen
            and standby_attached
            and zero_write
            and audit_check.get("ok")
            and bench_ok
        )
        return leg
    finally:
        if sup.poll() is None:
            sup.send_signal(signal.SIGTERM)
            try:
                sup.wait(timeout=15)
            except subprocess.TimeoutExpired:
                sup.kill()
                sup.wait(timeout=5)
        shutil.rmtree(tmp, ignore_errors=True)


def run_goodput_leg(seed: int, jobs: int, rounds: int) -> dict:
    """Real CPU-mesh training under preemption storms (the chaos soak's
    elastic leg), reduced to the goodput verdict."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        # Must be set before ANY jax import in this process.
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import chaos_soak

    ev = chaos_soak.run_preempt_soak(seed, jobs, rounds, elastic=True)
    goodput = chaos_soak.compute_goodput(ev)
    goodput["preempt_events"] = len(ev["preempt_events"])
    goodput["resumes"] = int(ev["metrics"]["resumes"])
    return goodput


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--check", action="store_true", default=False,
                    help="fast legs only (no real training) — the CI "
                         "smoke; verdict still OK/REGRESSION")
    ap.add_argument("--distributed", action="store_true", default=False,
                    help="cross-process tracing leg only: spawn the real "
                         "supervisor topology (router + shard + standby), "
                         "fire one traced cron tick through the router, "
                         "assert a single trace spanning >=3 processes "
                         "with a reconciling critical path "
                         "(make obs-report-dist)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--goodput-jobs", type=int, default=2,
                    help="logical training runs in the goodput leg")
    ap.add_argument("--goodput-rounds", type=int, default=2,
                    help="preemption-storm rounds in the goodput leg")
    ap.add_argument("--out", default=os.path.join(REPO_ROOT,
                                                  "BENCH_OBS.json"))
    args = ap.parse_args(argv)

    t0 = time.time()
    if args.distributed:
        print("obs report (distributed): supervisor topology, one traced "
              "tick through the router", flush=True)
        report = {"mode": "distributed",
                  "distributed": run_distributed_leg()}
        legs = [("distributed", report["distributed"])]
        ok = all(leg["ok"] for _, leg in legs)
        report["verdict"] = "OK" if ok else "REGRESSION"
        report["elapsed_s"] = round(time.time() - t0, 2)
        with open(args.out, "w") as f:
            json.dump(report, f, indent=2, default=str)
            f.write("\n")
        leg = report["distributed"]
        cp = leg.get("critical_path") or {}
        hops = " + ".join(
            f"{h['hop']}={h['seconds'] * 1e3:.1f}ms"
            for h in cp.get("hops") or []
        )
        mark = "PASS" if leg["ok"] else "FAIL"
        print(
            f"  [{mark}] distributed: {leg.get('distinct_pids', 0)} "
            f"process(es) on trace {leg.get('trace_id')}, "
            f"{hops or 'no hops'} "
            f"(wall {cp.get('wall_s', 0):.3f}s, reconciles="
            f"{cp.get('reconciles')}), measured e2e "
            f"{leg.get('measured_e2e_s')}s, "
            f"I9={((leg.get('audit_check') or {}).get('ok'))}, "
            f"debug store_writes={leg.get('store_writes_during_debug')}, "
            f"propagation "
            f"{(leg.get('propagation') or {}).get('trace_ctx_frame_us')}"
            f"µs/frame"
        )
        print(f"wrote {args.out} (verdict={report['verdict']})")
        return 0 if ok else 1

    mode = "check" if args.check else "full"
    print(f"obs report ({mode}): crons={OBS_CRONS} rounds={OBS_ROUNDS}",
          flush=True)
    report = {"mode": mode, **run_fast_legs()}
    report["utilization"] = run_utilization_leg()
    report["elasticity"] = run_elasticity_leg()
    report["mfu_timeline"] = run_mfu_leg()

    if not args.check:
        print(
            f"  goodput leg: jobs={args.goodput_jobs} "
            f"rounds={args.goodput_rounds} (real CPU-mesh training)",
            flush=True,
        )
        report["goodput"] = run_goodput_leg(
            args.seed, args.goodput_jobs, args.goodput_rounds
        )

    legs = [("flight_recorder", report["flight_recorder"]),
            ("scheduling_slo", report["scheduling_slo"]),
            ("timeline", report["timeline"]),
            ("deadline_slo", report["deadline_slo"]),
            ("utilization", report["utilization"]),
            ("elasticity", report["elasticity"]),
            ("mfu_timeline", report["mfu_timeline"])]
    if "goodput" in report:
        legs.append(("goodput", report["goodput"]))
    ok = all(leg["ok"] for _, leg in legs)
    report["verdict"] = "OK" if ok else "REGRESSION"
    report["elapsed_s"] = round(time.time() - t0, 2)

    with open(args.out, "w") as f:
        json.dump(report, f, indent=2, default=str)
        f.write("\n")

    for name, leg in legs:
        mark = "PASS" if leg["ok"] else "FAIL"
        if name == "flight_recorder":
            detail = (
                f"audit≡WAL={leg['wal_check']['ok']} "
                f"({leg['wal_check']['audited_records']} records), "
                f"tick_fired audit {leg['tick_fired_audit_records']} == "
                f"metric {leg['ticks_fired_metric']}, "
                f"endpoints_ok={leg['debug_endpoints_ok']}"
            )
        elif name == "scheduling_slo":
            detail = (
                f"p95={leg['p95_s']}s <= {leg['slo_p95_s']}s "
                f"over {leg['samples']} tick(s)"
            )
        elif name == "timeline":
            detail = (
                f"append {leg['append_us']}µs <= {leg['append_gate_us']}µs "
                f"gate, {leg['series_count']} series / "
                f"{leg['points_total']} points, counter history "
                f"max={leg['fired_history_max']}"
            )
        elif name == "deadline_slo":
            detail = (
                f"hit_rate={leg['hit_rate']} >= {leg['hit_rate_floor']} "
                f"({leg['hits']} hit(s), {leg['misses']} miss(es)), "
                f"store_writes={leg['store_writes_during_observatory']}, "
                f"wal_appends={leg['wal_appends_during_observatory']}"
            )
        elif name == "utilization":
            util_s = ", ".join(
                f"{t}={row['utilization']}"
                for t, row in leg["per_slice_type"].items()
            )
            detail = f"busy/capacity chip-s: {util_s}"
        elif name == "elasticity":
            rep = leg["observatory"]
            detail = (
                f"{rep['grows']} grow(s) / {rep['shrinks']} shrink(s) "
                f"observed, reclaimed {rep['reclaimed_idle_chip_s']} "
                f"idle chip-s, counters grows="
                f"{leg['fleet_grows_total']} "
                f"shrinks={leg['fleet_shrinks_total']}"
            )
        elif name == "mfu_timeline":
            detail = (
                f"{leg['timeline_entries']} timeline entries over "
                f"{leg['steps_done']} step(s), mfu={leg['mfu']}"
            )
        else:
            detail = (
                f"goodput {leg['overall']} >= floor {leg['floor']} "
                f"({leg['preempt_events']} preempt(s), "
                f"{leg['resumes']} resume(s))"
            )
        print(f"  [{mark}] {name}: {detail}")
    print(f"wrote {args.out} (verdict={report['verdict']})")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
