"""Observability verdict layer (``make obs-report`` → ``BENCH_OBS.json``).

Drives the REAL stack — ``APIServer`` + ``Persistence`` + the flight
recorder (``telemetry/audit.py``) + ``CronReconciler`` +
``LocalExecutor`` — and computes the goodput/SLO verdicts the
observability layer exists to answer:

- **flight_recorder** — audit ≡ WAL record for record
  (:meth:`AuditJournal.wal_check`), every fired tick present as a
  ``decision`` record matching ``cron_ticks_fired_total``, and the
  ``/debug/audit`` / ``/debug/traces`` bodies parse as bounded JSON.
- **scheduling_slo** — tick fired (the ``tick_fired`` audit record's
  wall-clock ``ts``) → the workload's first training step
  (``trainingProgress.first_step_at``, same clock domain): p95 must be
  under ``SCHED_SLO_P95_S``.
- **goodput** (full mode only) — the chaos soak's preempt-storm leg:
  real CPU-mesh training under preemption storms, productive ÷ total
  steps across every attempt chain, must clear
  ``chaos_soak.GOODPUT_FLOOR``.

``--check`` runs the fast legs only (simulated workloads, no real
training) — the CI smoke ``hack/ci_gate.sh`` runs on every gate.

Verdict: ``OK`` iff every leg passes, else ``REGRESSION`` (exit 1).
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import time
from datetime import timedelta

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

CRON_API_VERSION = "apps.kubedl.io/v1alpha1"
WORKLOAD_API_VERSION = "kubeflow.org/v1"
WORKLOAD_KIND = "JAXJob"
NAMESPACE = "default"

#: Scheduling-latency SLO: p95 of (tick fired → first training step).
#: Simulated workloads complete their first "step" at executor pickup,
#: so this bounds the control plane + executor dispatch path itself.
SCHED_SLO_P95_S = 2.0

#: Sizes of the fast scenario (kept small: the CI gate runs --check).
OBS_CRONS = 6
OBS_ROUNDS = 4


def _cron(i: int) -> dict:
    return {
        "apiVersion": CRON_API_VERSION,
        "kind": "Cron",
        "metadata": {"name": f"obs-{i}", "namespace": NAMESPACE},
        "spec": {
            "schedule": "*/1 * * * *",
            "concurrencyPolicy": "Allow",
            "historyLimit": 2,
            "template": {"workload": {
                "apiVersion": WORKLOAD_API_VERSION,
                "kind": WORKLOAD_KIND,
                "metadata": {"annotations": {
                    # Simulated 10ms run: reports started_at/first_step_at
                    # like a real trainer, without JAX in the loop.
                    "tpu.kubedl.io/simulate-duration": "10ms",
                }},
                "spec": {"replicaSpecs": {"Worker": {"replicas": 1}}},
            }},
        },
    }


def _is_terminal(obj: dict) -> bool:
    for c in ((obj.get("status") or {}).get("conditions") or []):
        if c.get("type") in ("Succeeded", "Failed") and \
                c.get("status") == "True":
            return True
    return False


def _percentile(sorted_vals: list, q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
    return sorted_vals[idx]


def run_fast_legs(rounds: int = OBS_ROUNDS, crons: int = OBS_CRONS) -> dict:
    """The flight-recorder + scheduling-SLO legs: fake-clock ticks over
    simulated workloads, real wall-clock dispatch underneath."""
    from cron_operator_tpu.backends.local import LocalExecutor
    from cron_operator_tpu.controller.cron_controller import CronReconciler
    from cron_operator_tpu.runtime.kube import APIServer
    from cron_operator_tpu.runtime.manager import Metrics
    from cron_operator_tpu.runtime.persistence import Persistence
    from cron_operator_tpu.telemetry import AuditJournal, Tracer
    from cron_operator_tpu.utils.clock import FakeClock

    tmp = tempfile.mkdtemp(prefix="obs-report-")
    clock = FakeClock()
    store = APIServer(clock=clock)
    metrics = Metrics()
    journal = AuditJournal()
    tracer = Tracer()
    journal.instrument(metrics)
    tracer.instrument(metrics)
    pers = Persistence(tmp, flush_interval_s=0)
    pers.instrument(metrics)
    pers.attach_audit(journal)
    pers.start(store)
    store.instrument(metrics)
    store.attach_audit(journal)
    ex = LocalExecutor(store, metrics=metrics, tracer=tracer, audit=journal)
    ex.start()
    rec = CronReconciler(store, metrics=metrics, tracer=tracer,
                         audit=journal)

    for i in range(crons):
        store.create(_cron(i))

    first_step_at: dict = {}

    def _sweep() -> None:
        for i in range(crons):
            rec.reconcile(NAMESPACE, f"obs-{i}")

    def _wait_terminal(deadline_s: float = 30.0) -> None:
        deadline = time.time() + deadline_s
        while time.time() < deadline:
            workloads = store.list(
                WORKLOAD_API_VERSION, WORKLOAD_KIND, namespace=NAMESPACE
            )
            for w in workloads:
                meta = w.get("metadata") or {}
                prog = (w.get("status") or {}).get("trainingProgress") or {}
                if prog.get("first_step_at") is not None:
                    first_step_at.setdefault(
                        meta.get("name", ""),
                        float(prog["first_step_at"]),
                    )
            if all(_is_terminal(w) for w in workloads):
                return
            time.sleep(0.02)

    for _ in range(rounds):
        clock.advance(timedelta(seconds=61))
        _sweep()
        _wait_terminal()
        _sweep()  # fold the settled round into history / GC
        pers.flush()

    # ---- flight recorder leg ---------------------------------------------
    wal = journal.wal_check(pers.records_appended)
    ticks_fired = int(metrics.get("cron_ticks_fired_total") or 0)
    tick_records = journal.records(kind="decision", event="tick_fired")
    audit_body = json.loads(
        journal.render_json({"kind": ["decision"], "limit": ["10"]})
    )
    traces_body = json.loads(tracer.render_json())
    endpoint_ok = (
        audit_body["matched"] <= 10
        and all(r["kind"] == "decision" for r in audit_body["records"])
        and isinstance(traces_body.get("traces"), list)
    )
    recorder = {
        "wal_check": wal,
        "ticks_fired_metric": ticks_fired,
        "tick_fired_audit_records": len(tick_records),
        "kind_totals": journal.kind_totals(),
        "audit_total": journal.total,
        "audit_dropped": journal.records_dropped,
        "debug_endpoints_ok": endpoint_ok,
        "ok": (
            wal["ok"]
            and ticks_fired > 0
            and len(tick_records) == ticks_fired
            and endpoint_ok
        ),
    }

    # ---- scheduling-latency SLO leg --------------------------------------
    lat = []
    for r in tick_records:
        name = r["key"].rsplit("/", 1)[-1]
        fs = first_step_at.get(name)
        if fs is not None:
            lat.append(max(0.0, fs - r["ts"]))
    lat.sort()
    slo = {
        "samples": len(lat),
        "p50_s": round(_percentile(lat, 0.50), 4),
        "p95_s": round(_percentile(lat, 0.95), 4),
        "max_s": round(lat[-1], 4) if lat else 0.0,
        "slo_p95_s": SCHED_SLO_P95_S,
        "ok": bool(lat) and _percentile(lat, 0.95) <= SCHED_SLO_P95_S,
    }

    ex.stop()
    store.close()
    pers.close()
    journal.close()
    shutil.rmtree(tmp, ignore_errors=True)
    return {"flight_recorder": recorder, "scheduling_slo": slo}


def run_goodput_leg(seed: int, jobs: int, rounds: int) -> dict:
    """Real CPU-mesh training under preemption storms (the chaos soak's
    elastic leg), reduced to the goodput verdict."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        # Must be set before ANY jax import in this process.
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import chaos_soak

    ev = chaos_soak.run_preempt_soak(seed, jobs, rounds, elastic=True)
    goodput = chaos_soak.compute_goodput(ev)
    goodput["preempt_events"] = len(ev["preempt_events"])
    goodput["resumes"] = int(ev["metrics"]["resumes"])
    return goodput


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--check", action="store_true", default=False,
                    help="fast legs only (no real training) — the CI "
                         "smoke; verdict still OK/REGRESSION")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--goodput-jobs", type=int, default=2,
                    help="logical training runs in the goodput leg")
    ap.add_argument("--goodput-rounds", type=int, default=2,
                    help="preemption-storm rounds in the goodput leg")
    ap.add_argument("--out", default=os.path.join(REPO_ROOT,
                                                  "BENCH_OBS.json"))
    args = ap.parse_args(argv)

    t0 = time.time()
    mode = "check" if args.check else "full"
    print(f"obs report ({mode}): crons={OBS_CRONS} rounds={OBS_ROUNDS}",
          flush=True)
    report = {"mode": mode, **run_fast_legs()}

    if not args.check:
        print(
            f"  goodput leg: jobs={args.goodput_jobs} "
            f"rounds={args.goodput_rounds} (real CPU-mesh training)",
            flush=True,
        )
        report["goodput"] = run_goodput_leg(
            args.seed, args.goodput_jobs, args.goodput_rounds
        )

    legs = [("flight_recorder", report["flight_recorder"]),
            ("scheduling_slo", report["scheduling_slo"])]
    if "goodput" in report:
        legs.append(("goodput", report["goodput"]))
    ok = all(leg["ok"] for _, leg in legs)
    report["verdict"] = "OK" if ok else "REGRESSION"
    report["elapsed_s"] = round(time.time() - t0, 2)

    with open(args.out, "w") as f:
        json.dump(report, f, indent=2, default=str)
        f.write("\n")

    for name, leg in legs:
        mark = "PASS" if leg["ok"] else "FAIL"
        if name == "flight_recorder":
            detail = (
                f"audit≡WAL={leg['wal_check']['ok']} "
                f"({leg['wal_check']['audited_records']} records), "
                f"tick_fired audit {leg['tick_fired_audit_records']} == "
                f"metric {leg['ticks_fired_metric']}, "
                f"endpoints_ok={leg['debug_endpoints_ok']}"
            )
        elif name == "scheduling_slo":
            detail = (
                f"p95={leg['p95_s']}s <= {leg['slo_p95_s']}s "
                f"over {leg['samples']} tick(s)"
            )
        else:
            detail = (
                f"goodput {leg['overall']} >= floor {leg['floor']} "
                f"({leg['preempt_events']} preempt(s), "
                f"{leg['resumes']} resume(s))"
            )
        print(f"  [{mark}] {name}: {detail}")
    print(f"wrote {args.out} (verdict={report['verdict']})")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
