#!/usr/bin/env bash
# Version-consistency gate: VERSION is the single source of truth; the
# Python package, pyproject, and Helm chart must all agree (the reference
# release workflow enforces the same for its chart —
# /root/reference/.github/workflows/release.yaml "Check whether chart
# version and appVersion matches version").
set -euo pipefail
cd "$(dirname "$0")/.."

VERSION=$(cat VERSION)
RAW=${VERSION#v}

fail=0

check() { # name actual
    if [[ "$2" != "$RAW" ]]; then
        echo "ERROR: $1 is '$2', expected '$RAW' (from VERSION)" >&2
        fail=1
    fi
}

check "pyproject.toml version" \
    "$(grep -E '^version *= *' pyproject.toml | head -1 | sed -E 's/.*"(.*)".*/\1/')"
check "package __version__" \
    "$(python -c 'import cron_operator_tpu as m; print(m.__version__)')"
check "chart version" \
    "$(grep '^version:' charts/cron-operator-tpu/Chart.yaml | awk '{print $2}')"
check "chart appVersion" \
    "$(grep '^appVersion:' charts/cron-operator-tpu/Chart.yaml | awk '{print $2}' | tr -d '\"')"

if [[ $fail -ne 0 ]]; then
    exit 1
fi
echo "version consistency: all at ${RAW}"
