"""Semantic diff of two rendered-manifest streams (helm vs helmtmpl).

Closes the round-4 golden circularity (VERDICT r4 missing #2): the chart
goldens were produced by the same in-repo renderer the tests exercise, so
a helmtmpl↔helm divergence shipped a broken install with everything
green. CI now renders the chart BOTH ways — real ``helm template`` and
``python -m cron_operator_tpu.utils.helmtmpl`` — and this script asserts
the outputs are semantically identical: same set of (kind, name,
namespace) documents, each structurally equal after YAML parsing.

Byte-level comparison would be meaninglessly strict (helm and helmtmpl
order map keys and wrap strings differently — both render the same
Kubernetes objects); parsing to object form and re-dumping with sorted
keys compares what the apiserver would actually see.

Usage: ``python hack/helm_diff.py A.yaml B.yaml [--label-a helm]
[--label-b helmtmpl]``. Exit 0 = equivalent, 1 = divergent (unified diff
of the canonical forms on stderr).
"""

from __future__ import annotations

import argparse
import difflib
import sys

import yaml


def _key(doc) -> tuple:
    if not isinstance(doc, dict):
        # A renderer emitting a bare string/list is itself a divergence
        # worth surfacing, not a crash: key it by its repr.
        return ("<non-mapping>", repr(doc), "", "")
    meta = doc.get("metadata") or {}
    return (
        doc.get("apiVersion", ""),
        doc.get("kind", ""),
        meta.get("namespace", ""),
        meta.get("name", ""),
    )


def load_docs(path: str):
    """{identity key: [docs]} — ALL documents per identity are kept and
    compared element-wise (keeping only a count, or only the last doc,
    would pass a [corrupted, good] vs [good, good] divergence — the
    exact breakage this script exists to catch)."""
    with open(path) as f:
        docs = [d for d in yaml.safe_load_all(f) if d]
    out = {}
    for d in docs:
        out.setdefault(_key(d), []).append(d)
    return out


def canonical(doc) -> str:
    return yaml.safe_dump(doc, sort_keys=True, default_flow_style=False)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("a")
    p.add_argument("b")
    p.add_argument("--label-a", default="a")
    p.add_argument("--label-b", default="b")
    args = p.parse_args(argv)

    a, b = load_docs(args.a), load_docs(args.b)
    rc = 0
    for key in sorted(set(a) | set(b)):
        ident = "/".join(str(k) for k in key)
        if key not in a:
            print(f"DIVERGENT: {ident} only in {args.label_b}",
                  file=sys.stderr)
            rc = 1
            continue
        if key not in b:
            print(f"DIVERGENT: {ident} only in {args.label_a}",
                  file=sys.stderr)
            rc = 1
            continue
        la, lb = a[key], b[key]
        if len(la) != len(lb):
            print(f"DIVERGENT: {ident} emitted {len(la)}x by "
                  f"{args.label_a} but {len(lb)}x by {args.label_b}",
                  file=sys.stderr)
            rc = 1
        for i, (da, db) in enumerate(zip(la, lb)):
            if da == db:
                continue
            n = f"#{i}" if max(len(la), len(lb)) > 1 else ""
            print(f"DIVERGENT: {ident}{n}", file=sys.stderr)
            sys.stderr.writelines(difflib.unified_diff(
                canonical(da).splitlines(keepends=True),
                canonical(db).splitlines(keepends=True),
                fromfile=f"{args.label_a}:{ident}{n}",
                tofile=f"{args.label_b}:{ident}{n}",
            ))
            rc = 1
    if rc == 0:
        n_docs = sum(len(v) for v in a.values())
        print(f"EQUIVALENT: {n_docs} documents match "
              f"({args.label_a} == {args.label_b})")
    return rc


if __name__ == "__main__":
    sys.exit(main())
