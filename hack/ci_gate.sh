#!/usr/bin/env bash
# The commit gate (VERDICT r2 #5) — the reference runs fmt/vet/lint/codegen-
# drift + unit tests in .github/workflows/integration.yaml; this is the same
# pyramid for this repo, runnable locally (`make gate`) and in CI. Round 1
# shipped red tests because nothing gated commits; this would have caught it.
set -euo pipefail
cd "$(dirname "$0")/.."

echo "==> syntax (compileall)"
python -m compileall -q cron_operator_tpu tests bench.py __graft_entry__.py

echo "==> codegen drift (CRD manifests)"
python -m cron_operator_tpu.api.crd >/dev/null
if ! git diff --quiet -- deploy/crds charts/cron-operator-tpu/crds; then
    echo "ERROR: generated CRDs drifted from committed copies:" >&2
    git --no-pager diff --stat -- deploy/crds charts/cron-operator-tpu/crds >&2
    exit 1
fi

echo "==> chart renders (default + ci values)"
python -m cron_operator_tpu.utils.helmtmpl charts/cron-operator-tpu >/dev/null
python -m cron_operator_tpu.utils.helmtmpl charts/cron-operator-tpu \
    --values charts/cron-operator-tpu/ci/values.yaml >/dev/null

echo "==> unit + integration tests"
python -m pytest tests/ -q

echo "GATE: all checks passed"
