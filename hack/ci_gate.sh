#!/usr/bin/env bash
# The commit gate (VERDICT r2 #5) — the reference runs fmt/vet/lint/codegen-
# drift + unit tests in .github/workflows/integration.yaml; this is the same
# pyramid for this repo, runnable locally (`make gate`) and in CI. Round 1
# shipped red tests because nothing gated commits; this would have caught it.
set -euo pipefail
cd "$(dirname "$0")/.."

echo "==> syntax (compileall)"
python -m compileall -q cron_operator_tpu tests bench.py __graft_entry__.py

echo "==> lint (hack/lint.py — the .golangci.yml analog)"
python hack/lint.py

echo "==> version consistency (VERSION ↔ pyproject ↔ package ↔ chart)"
bash hack/check_version.sh

echo "==> codegen drift (CRD manifests)"
python -m cron_operator_tpu.api.crd >/dev/null
if ! git diff --quiet -- deploy/crds charts/cron-operator-tpu/crds \
        config/crd/bases; then
    echo "ERROR: generated CRDs drifted from committed copies:" >&2
    git --no-pager diff --stat -- deploy/crds charts/cron-operator-tpu/crds \
        config/crd/bases >&2
    exit 1
fi

echo "==> chart renders match goldens (default + ci + full)"
_golden() { # file renderer-args...
    local golden="charts/cron-operator-tpu/tests/golden/$1"; shift
    { sed -n '/^# GOLDEN RENDER/,/^# and diff against/p' "$golden"
      python -m cron_operator_tpu.utils.helmtmpl charts/cron-operator-tpu \
          "$@"; } | diff -u "$golden" - || {
        echo "ERROR: chart render drifted from $golden — regenerate per" >&2
        echo "       the golden's header and review the diff" >&2
        exit 1
    }
}
_golden default.yaml
_golden ci.yaml --values charts/cron-operator-tpu/ci/values.yaml
_golden full.yaml --set metrics.serviceMonitor.enable=true \
    --set networkPolicy.enable=true

echo "==> chart README in sync (helm-docs analog)"
python hack/chart_docs.py --check

echo "==> control-plane write-path smoke (fire storm + zero-write steady state)"
# Small-N run of the real bench harness: catches a wedged fire storm or a
# reappearing steady-state store write long before the full 1k/5k bench.
python hack/controlplane_bench.py --sizes 200 --sweep-timeout 120 --stdout \
    | python -c '
import json, sys
r = json.loads(sys.stdin.readlines()[-1])["results"][0]
assert not r["fire_storm_timed_out"], r
assert r["fire_storm_workloads_created"] == 200, r
assert r["list_reconcile_store_writes"] == 0, (
    "steady-state sweep wrote to the store: %r" % r)
print("    storm %s Crons/s; steady-state store writes: 0"
      % r["fire_storm_crons_per_s"])
'

echo "==> chaos smoke (fixed-seed fault injection + crash-restart, 7 invariants)"
# Short seeded soak: 40 Crons x 3 rounds under the default chaos plan
# (conflicts, transient errors, watch breaks, leader loss, preemption
# storms) PLUS kill+restart rounds against the WAL/snapshot durability
# layer, then a fault-free replay from the same seed. Exits non-zero if
# any of the seven invariants (Forbid exclusion, bounded history,
# exactly-once ticks, zero-write convergence, replay equivalence,
# recovery==WAL-replay, restart tick integrity) is violated. Full run:
# make chaos-soak (writes CHAOS.json).
python hack/chaos_soak.py --seed 7 --crons 40 --rounds 3 --out /dev/null

echo "==> sharded control-plane smoke (per-shard + aggregate scale-out verdicts)"
# Small-N run of the sharded bench sweep (runtime/shard.py): measures the
# steady-state list+reconcile sweep at 1 and 2 shards, printing one
# OK/REGRESSION verdict per shard (zero steady-state store writes on
# every shard) plus the aggregate scale-up verdict; --check fails the
# gate on any REGRESSION. Full sweep: make bench-shards (updates
# BENCH_CONTROLPLANE.json).
python hack/controlplane_bench.py --shards-sweep --shards-total 2000 \
    --shard-counts 1,2 --shards-min-scaleup 1.5 --stdout --check \
    >/dev/null

echo "==> shard-kill failover smoke (2 shards, WAL-shipping hot standby)"
# Fixed-seed sharded soak: the seed guarantees kill rounds, so every run
# exercises at least one leader kill + follower promotion. I6 is checked
# per shard at promotion time (follower state must equal an independent
# replay of the shipped WAL); all seven invariants must hold across the
# failovers.
python hack/chaos_soak.py --seed 11 --crons 24 --rounds 3 --shards 2 \
    --out /dev/null

echo "==> multi-process kill -9 smoke (2 shard processes, lease failover)"
# Fixed-seed PROCESS-mode soak: spawns the real topology (one leader +
# one standby OS process per shard, socket WAL shipping, on-disk lease
# files, one router process), SIGKILLs a PRF-chosen shard's serving
# process mid-storm, and requires the standby to self-promote within the
# bounded failover window with I6 proven against an independent disk
# replay before serving (promotion-*.json) and I9 (audit ≡ WAL) proven
# by every gracefully-stopped generation (audit-check-*.json). The storm
# book must equal the routed surface exactly, split per shard by the
# consistent hash. Full run: make chaos-soak (processes leg of
# CHAOS.json).
python hack/chaos_soak.py --processes --seed 7 --crons 24 --rounds 1 \
    --out /dev/null

echo "==> preempt-storm smoke (elastic reshard-on-preemption, I8)"
# Fixed-seed storm over REAL CPU-mesh training jobs: two rounds of
# PRF-scheduled slice preemptions against paced mnist runs; the
# reconciler must resume every victim on the shrunken mesh from its
# latest checkpoint, and I8 (finishes at target, loses <= one
# checkpoint interval per preemption, exactly one history entry per
# logical run) must hold. Full run: make chaos-soak-preempt.
python hack/chaos_soak.py --seed 5 --crons 24 --rounds 2 \
    --preempt-storm --elastic-jobs 2 --out /dev/null

echo "==> elastic counter-proof (same storms, no resume -> I8 must break)"
# The same storm schedule against restart-on-preemption jobs with NO
# checkpointing: the restarted runs start over at step 0, so I8's
# "loses at most one interval" must be violated — proves the I8 PASS
# above is not vacuous.
python hack/chaos_soak.py --seed 5 --rounds 2 --no-elastic \
    --elastic-jobs 2 --expect-violation --out /dev/null

echo "==> durability counter-proof (same kills, no durability -> I7 must break)"
# The same fixed-seed kill schedule restarted from an EMPTY data dir
# must lose in-window ticks (permanently_lost non-empty): proves the
# soak genuinely detects the failure mode the WAL exists to prevent,
# i.e. the I7 PASS above is not vacuous.
python hack/chaos_soak.py --seed 7 --crons 40 --rounds 3 \
    --no-durability --expect-violation --out /dev/null

echo "==> observability report smoke (flight recorder + SLO verdict, fast legs)"
# Fast legs of the goodput/SLO report (hack/obs_report.py): a simulated
# fire+resume scenario whose audit journal must reconcile exactly against
# the WAL (I9's audit ≡ WAL check), the scheduling-SLO leg, and the PR 11
# observatory legs — timeline (history append gated <= 5µs, counter
# history == live counter), deadline_slo (hit-rate floor + rv-bracketed
# zero-store-write proof), utilization (busy <= capacity chip-seconds on
# a simulated fleet) and mfu_timeline (step-phase timeline + MFU on a
# real CPU training run); --check skips the real-training goodput leg
# and fails the gate on any REGRESSION verdict. Full report:
# make obs-report (writes BENCH_OBS.json).
python hack/obs_report.py --check --out /dev/null >/dev/null

echo "==> distributed-obs smoke (one trace across router + shard + runner)"
# Cross-process tracing leg: spawns the REAL supervisor topology (router
# + shard leader + standby as separate OS processes), POSTs a Cron
# through the router under a driver-minted traceparent, and requires
# ONE trace with spans from >= 3 distinct processes whose critical-path
# decomposition (route → admit → commit → fsync → submit → first_step)
# reconciles against measured wall latency — plus I9 on the shard, a
# zero-write debug read path, the cluster event fan-in, and the
# per-frame trace-context propagation µs gate. Full artifact:
# make obs-report-dist (writes BENCH_OBS_DIST.json).
python hack/obs_report.py --distributed --out /dev/null

echo "==> HTTP front-door smoke (fan-out encode-once, group-commit, APF fairness)"
# Small-size run of the real front-door bench against the in-process
# HTTPAPIServer: 100 watchers must each receive every event from ONE
# encode per event, durable-write p99 must hold from 1 -> 16 concurrent
# writers with a closed-loop burst sharing fsyncs, a quiet tenant's p99
# must survive a 50x+ noisy flood (vs a single-flow FIFO control), and
# the read-only phase must commit zero store/WAL writes. --check fails
# the gate on any REGRESSION verdict. Full run: make bench-http
# (updates BENCH_HTTP.json; BASELINE=<ref> adds the >= 5x fan-out A/B).
python hack/http_bench.py --check --stdout >/dev/null

echo "==> follower-read smoke (rv barriers, leader fallback, watch across kill -9)"
# Mechanism-only asserts for the follower read plane: a barriered read
# against a lagging replica must block and resume exactly at the
# barrier rv (timeout -> 504 FollowerBehind -> counted leader
# fallback), write-then-list through the router must never observe the
# pre-write state, and a follower-served watch stream must deliver the
# full event sequence across a kill -9 promotion. Capacity RATIOS
# (>= 3x per replica, writes within 5%) stay full-run claims:
# make bench-http (follower_fanout leg of BENCH_HTTP.json).
python -m pytest tests/test_follower_reads.py -q

echo "==> fleet scheduler smoke (makespan A/B, fairness, p50, zero-write)"
# Small-size run of the fleet bench (hack/fleet_bench.py): a 600-job
# storm over the mixed v5e/v4/cpu pool must beat the FIFO/first-fit
# baseline >= 1.5x on makespan at equal-or-better Jain fairness, keep
# the placement decision p50 <= 1 ms, and commit zero store writes
# across repeated steady-state pumps. --check fails the gate on
# REGRESSION. Full run: make bench-fleet (updates BENCH_FLEET.json).
python hack/fleet_bench.py --check --stdout >/dev/null

echo "==> step-speed smoke (scan-chain parity + async staging overlap)"
# Small-size run of the step bench (hack/step_bench.py): the default
# scan-chained + double-buffered executor mode must produce BIT-exact
# params vs the per-step path on the same stream, and the async stager
# must hide host staging time (its per-step wait strictly below the
# synchronous stager's inline cost). The 1.3x throughput gate stays a
# full-run claim (make bench-step) — a loaded CI host must not flake
# the commit gate on a timing ratio.
JAX_PLATFORMS=cpu python hack/step_bench.py --check --stdout >/dev/null

echo "==> fleet capacity-flap soak (quotas, preemption + elastic resume)"
# Fixed-seed flap rounds against the fleet scheduler: the slice pool
# shrinks past its free slices mid-storm (forcing preemptions through
# the real executor) and grows back. No admitted job may be lost,
# tenant quotas must never be exceeded (the high-water mark is checked,
# including joint dispatch batches), and every preempted run must
# resume via the elastic chain into a single history entry.
python hack/chaos_soak.py --seed 13 --crons 18 --rounds 3 --fleet-flap \
    --out /dev/null

echo "==> bidirectional elasticity (grow soak + shrink-only counter-proof)"
# Fixed-seed grow smoke: one real CPU-mesh training job is
# checkpoint-and-regrown 2→4→8 into idle slices by the GrowPlanner,
# shrunk back under pinned pressure, and must beat the shrink-only
# baseline's goodput by >= 1.15x with params bit-exact across every
# width change (F4). Then the same scenario with the planner OFF must
# leave a measurable idle chip-second gap — the counter-proof that the
# grow gate measures reclaimed capacity, not noise.
python hack/chaos_soak.py --seed 17 --crons 12 --rounds 2 --fleet-flap \
    --grow --out /dev/null
python hack/chaos_soak.py --seed 17 --no-grow --expect-violation \
    --out /dev/null

echo "==> gray-failure smoke (lease fencing, hang watchdog, shard breakers)"
# Fixed-seed gray soak: SIGSTOP rounds freeze a live leader mid-lease
# (a zombie, not a corpse) — the standby must promote with a bumped
# generation and the woken zombie must fence itself before any
# stale-epoch write commits; a byte-level scan of every WAL/snapshot
# must find zero stale-generation records (I10). The router leg
# SIGSTOPs one shard of two: its circuit breaker must trip open, the
# healthy shard's p99 must stay bounded, tripped calls must fail fast,
# and the breaker must close after SIGCONT. The hang leg wedges REAL
# CPU-mesh training runs silently; the step watchdog must declare
# HangDetected within its EMA budget and the elastic chain must finish
# every run at target in exactly one history entry (I11). Full run:
# make chaos-soak-gray (folds into CHAOS.json).
python hack/chaos_soak.py --seed 7 --rounds 4 --gray --out /dev/null

echo "==> fencing counter-proof (same SIGSTOPs, fencing off -> I10 must break)"
# The same SIGSTOP/promote/SIGCONT schedule with fencing disabled: the
# woken zombie's poison write must LAND as a stale-generation (or
# zero-fill-corrupted) record in the WAL inode the promoted leader now
# owns — proves the I10 PASS above detects the split-brain that
# fencing exists to prevent, i.e. it is not vacuous.
python hack/chaos_soak.py --seed 7 --rounds 2 --gray --no-fencing \
    --expect-violation --out /dev/null

echo "==> live-split smoke (1->2 split under storm, fencing + crash resolution)"
# Fixed-seed split soak: live 1->N shard splits under a concurrent write
# storm, including a PRF-chosen round that SIGKILLs the parent's
# persistence mid-dark-window and restarts the whole plane. Every split
# must hold I6 (child == filtered replay of the shipped WAL), I9
# (audit == WAL per shard), I10 (zero stale-generation records on
# disk), S1 (every key has exactly one owner after each split AND after
# the crash-restart), and S2 (no acked write lost). Full run:
# make chaos-soak-split (writes CHAOS_SPLIT.json).
python hack/chaos_soak.py --split --seed 3 --crons 40 --rounds 2 \
    --out /dev/null

echo "==> split counter-proof (same storm, fencing off -> acked write must vanish)"
# The same split schedule with range fencing disabled: a poison write
# routed to the demoted parent during the dark window must be ACKED and
# then erased from the routed surface by the cutover — proves the S2
# PASS above detects the lost-ack split-brain that range fencing
# exists to prevent, i.e. it is not vacuous.
python hack/chaos_soak.py --split --no-fencing --seed 3 --crons 40 \
    --rounds 2 --expect-violation --out /dev/null

echo "==> disk-fault smoke (checksummed WAL, quarantine, degraded mode, scrubber)"
# Fixed-seed disk-fault soak: cycles every DiskFaultInjector kind —
# seeded bit-flips and mid-file torn writes against the closed WAL,
# EIO/ENOSPC injected into append/fsync/rename through the syscall seam.
# I12a: no corrupted (or never-acked) record is ever applied — recovery
# always lands on a verifiable prefix of the acked history. I12b: every
# damage round is detected (non-clean verdict, wal.quarantine/ forensics,
# scrubber finding the latent sealed-segment flip). I12c: injected
# errors fail closed (refused write exists NOWHERE, degraded gauge
# visible, probe append heals). Full run: make chaos-soak-disk (folds
# into CHAOS.json).
python hack/chaos_soak.py --disk --seed 42 --rounds 6 --out /dev/null

echo "==> checksum counter-proof (same bit-flip, CRCs off -> I12a must break)"
# The same seeded bit-flip against the LEGACY trailer-less format: the
# flipped record must be applied SILENTLY (verdict "clean", store no
# longer matches the acked ledger) — proves the I12a PASS above detects
# the silent corruption the checksums exist to catch, i.e. not vacuous.
python hack/chaos_soak.py --disk --no-checksums --seed 42 --rounds 6 \
    --expect-violation --out /dev/null

echo "==> partition smoke (lying network: blackholes, dup/reorder, half-open)"
# Fixed-seed partition soak: seeded socket proxies on every transport
# seam inject one-way blackholes, delay, reordering, duplicated frames,
# slow-drip partial frames and mid-stream RSTs. I13a: no acked write
# lost or doubled across dark windows (ship-stream book check). I13b: a
# leader partitioned from the router but lease-fresh never
# false-fails-over (generation pinned, breaker fails fast, zero
# stale-generation bytes). I13c: every partition detected by the
# ping/pong heartbeats and healed within the bound. I13d: a retry storm
# at a dark shard leaves the healthy shard's write p99 within 1.2x
# baseline. Full run: make chaos-soak-partition (folds into CHAOS.json).
python hack/chaos_soak.py --partition --seed 42 --rounds 4 --out /dev/null

echo "==> heartbeat counter-proof (same blackhole, heartbeats off -> wedge)"
# The same seeded one-way blackhole with app-level heartbeats and read
# deadlines OFF: the ship connection must wedge half-open FOREVER (the
# follower never re-dials, its lag grows silently) — proves the I13c
# PASS above detects the gray failure the heartbeat stack exists to
# catch, i.e. not vacuous.
python hack/chaos_soak.py --partition --no-net-heartbeats --seed 42 \
    --rounds 4 --expect-violation --out /dev/null

echo "==> metric registry drift (every emitted family declared + typed)"
# Explicit run of the registry drift guard: scans every metrics.inc/
# observe/set call site AND interned-series assignment in the package,
# and fails if a family is emitted that _FAMILY_META does not declare
# (or vice versa). Runs again inside the full suite below, but a drifted
# registry should name itself, not hide in a wall of test output.
python -m pytest tests/test_registry_drift.py -q

echo "==> unit + integration tests"
# With pytest-cov installed (CI always; optional locally) the suite runs
# under coverage and hack/ci_gate enforces the pyproject fail_under
# threshold — untested seams become visible per PR (VERDICT r4 #7: the
# pre-round-4 runner gap would have been flagged).
if python -c "import pytest_cov" 2>/dev/null; then
    # --cov-fail-under passed explicitly: older pytest-cov releases do
    # not pick fail_under up from [tool.coverage.report]. Keep the two
    # values in sync.
    python -m pytest tests/ -q --cov \
        --cov-report=term-missing:skip-covered --cov-fail-under=70
else
    echo "    (pytest-cov not installed; running without coverage)"
    python -m pytest tests/ -q
fi

echo "GATE: all checks passed"
