#!/usr/bin/env python
"""Fleet scheduler benchmark (ISSUE 10) — BENCH_FLEET.json.

Two legs:

1. **Makespan / fairness A-B** — an event-driven simulation of a fired
   storm: N virtual Crons (default 10k, ``--check`` shrinks) all fire at
   t=0 over a 3-type fleet. The same seeded job mix (5 workload classes
   with strongly type-dependent throughput, 4 tenants) runs under the
   heterogeneity-aware policy and under the naive FIFO/first-fit
   baseline; job physics are identical (duration = work / rate(class,
   placed type)), only placement differs. Gates: hetero makespan beats
   FIFO by ``--min-speedup`` (default 1.5x) at equal-or-better Jain
   fairness over per-tenant goodput, and the placement decision itself
   (the only thing the tick path pays) stays under ``--max-p50-ms``
   (default 1 ms) at p50.

2. **Wired zero-write steady state** — a real APIServer with placed and
   queued workloads: repeated scheduler pumps with no watch events must
   commit zero store writes (resourceVersion frozen). Placement reads
   the fleet's in-memory books, never the store — the control plane's
   steady-state zero-write invariant survives the new subsystem.

3. **Bidirectional-elasticity A-B (grow leg)** — the REAL GrowPlanner
   (``grow_enabled=True``) over a three-tier ``host_chips`` pool on a
   virtual clock: blockers vacate progressively wider slices and the
   planner checkpoint-and-regrows one elastic job into them (each
   reshard pays a fixed virtual penalty); the baseline is the identical
   timeline with the planner off. Job physics are chips-proportional
   (tokens/s = width). Gates: the grown job finishes ``--min-grow-speedup``
   (default 2x) faster, exactly two grow decisions fire, and the
   planner reclaims >= 90% of the idle chip-seconds the baseline
   leaves on the table.

Output: BENCH_FLEET.json with one OK/REGRESSION verdict over all legs.
``--check`` runs small sizes and exits non-zero on REGRESSION (the CI
gate smoke); ``--stdout`` prints the JSON document.
"""

import argparse
import heapq
import json
import os
import random
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from cron_operator_tpu.runtime.fleet import (  # noqa: E402
    FleetScheduler,
    ThroughputMatrix,
    parse_pool,
)

POOL = "v5e-16=8,v4-8=12,cpu=16"

# Seeded "bench history": tokens/s per (workload class, slice type).
# Each class has a strongly preferred type — the structure a mixed
# training/eval/preprocess fleet actually shows (Gavel, arXiv
# 2008.09213, Table 1 measures 10x+ spreads across GPU generations).
RATES = {
    ("train-large", "v5e-16"): 20.0,
    ("train-large", "v4-8"): 4.0,
    ("train-large", "cpu"): 0.5,
    ("train-small", "v5e-16"): 8.0,
    ("train-small", "v4-8"): 6.0,
    ("train-small", "cpu"): 1.0,
    ("eval", "v5e-16"): 6.0,
    ("eval", "v4-8"): 5.0,
    ("eval", "cpu"): 2.0,
    ("preprocess", "v5e-16"): 2.0,
    ("preprocess", "v4-8"): 1.8,
    ("preprocess", "cpu"): 1.5,
    ("export", "v5e-16"): 3.0,
    ("export", "v4-8"): 2.8,
    ("export", "cpu"): 2.5,
}
CLASSES = ["train-large", "train-small", "eval", "preprocess", "export"]
TENANTS = ["team-a", "team-b", "team-c", "team-d"]


def _jain(xs):
    xs = [x for x in xs if x > 0]
    if not xs:
        return 1.0
    return (sum(xs) ** 2) / (len(xs) * sum(x * x for x in xs))


def _job_mix(n_jobs, seed):
    rng = random.Random(seed)
    jobs = []
    for i in range(n_jobs):
        wclass = rng.choice(CLASSES)
        work = {
            "train-large": 200.0, "train-small": 60.0, "eval": 30.0,
            "preprocess": 15.0, "export": 12.0,
        }[wclass] * rng.uniform(0.5, 1.5)
        jobs.append({
            "name": f"job-{i}",
            "wclass": wclass,
            "tenant": TENANTS[i % len(TENANTS)],
            "work": work,
        })
    return jobs


def run_storm(policy, jobs, backfill_window=48):
    """Event-heap simulation: submit everything at t=0, then advance the
    virtual clock finish-by-finish; every release lets the scheduler
    dispatch queued work at the current sim time."""
    now = [0.0]
    finish_at = {}
    heap = []
    by_name = {j["name"]: j for j in jobs}

    def on_create(workload, slice_type):
        name = workload["metadata"]["name"]
        job = by_name[name]
        dur = job["work"] / RATES[(job["wclass"], slice_type)]
        finish_at[name] = now[0] + dur
        heapq.heappush(heap, (finish_at[name], name))

    fs = FleetScheduler(
        parse_pool(POOL),
        policy=policy,
        matrix=ThroughputMatrix(RATES),
        max_queue=len(jobs) + 1,
        backfill_window=backfill_window,
        # Bounded slowdown: waiting for the right slice beats running a
        # train-large gang 40x slower on host CPUs (no-op under fifo —
        # the baseline takes any free slot, as first-fit does).
        min_efficiency=0.25,
        on_create=on_create,
    )
    submit_lat = []
    for j in jobs:
        wl = {
            "apiVersion": "kubeflow.org/v1", "kind": "JAXJob",
            "metadata": {
                "namespace": "bench", "name": j["name"],
                "annotations": {
                    "tpu.kubedl.io/workload-class": j["wclass"],
                    "tpu.kubedl.io/tenant": j["tenant"],
                    "tpu.kubedl.io/estimated-work": str(j["work"]),
                },
            },
            "spec": {},
        }
        t0 = time.perf_counter()
        d = fs.submit(wl)
        submit_lat.append(time.perf_counter() - t0)
        assert d.action != "rejected", d
    while heap:
        t, name = heapq.heappop(heap)
        now[0] = t
        fs.release("bench", name)
    assert len(finish_at) == len(jobs), (
        f"{policy}: {len(jobs) - len(finish_at)} jobs never ran"
    )
    tenant_work = {}
    tenant_turnaround = {}
    for j in jobs:
        tenant_work[j["tenant"]] = (
            tenant_work.get(j["tenant"], 0.0) + j["work"]
        )
        tenant_turnaround[j["tenant"]] = (
            tenant_turnaround.get(j["tenant"], 0.0) + finish_at[j["name"]]
        )
    goodput = [
        tenant_work[t] / tenant_turnaround[t] for t in sorted(tenant_work)
    ]
    lat_ms = sorted(x * 1000 for x in submit_lat)
    return {
        "policy": policy,
        "jobs": len(jobs),
        "makespan_s": round(max(finish_at.values()), 3),
        "jain_fairness": round(_jain(goodput), 4),
        "mean_turnaround_s": round(
            statistics.fmean(finish_at.values()), 3
        ),
        "backfills": fs.backfilled_total,
        "submit_p50_ms": round(lat_ms[len(lat_ms) // 2], 4),
        "submit_p99_ms": round(lat_ms[int(len(lat_ms) * 0.99) - 1], 4),
    }


def run_zero_write_leg(n_jobs=40, pumps=200):
    """Wired leg: fleet + real store. After the storm settles, repeated
    pumps with no watch traffic must not commit a single store write."""
    from cron_operator_tpu.runtime.kube import APIServer

    api = APIServer()
    fs = FleetScheduler(
        parse_pool("v5e-16=2,v4-8=2,cpu=2"),
        api=api,
        matrix=ThroughputMatrix(RATES),
        max_queue=n_jobs + 1,
    )
    api.add_watcher(fs._on_event, coalesce=True)
    rng = random.Random(7)
    for i in range(n_jobs):
        fs.submit({
            "apiVersion": "kubeflow.org/v1", "kind": "JAXJob",
            "metadata": {
                "namespace": "bench", "name": f"zw-{i}",
                "annotations": {
                    "tpu.kubedl.io/workload-class": rng.choice(CLASSES),
                },
            },
            "spec": {},
        })
    api.flush()
    fs.pump()  # drain the create echoes
    rv_before = int(getattr(api, "_rv", 0))
    for _ in range(pumps):
        fs.pump()
    rv_after = int(getattr(api, "_rv", 0))
    stats = fs.stats()
    api.close()
    return {
        "jobs": n_jobs,
        "pumps": pumps,
        "running": stats["running"],
        "queued": stats["queued"],
        "steady_state_store_writes": rv_after - rv_before,
    }


GROW_POOL = "small=1@2,mid=1@4,wide=1@8"
GROW_WORK_TOKENS = 240.0
GROW_RESHARD_PENALTY_S = 0.5
GROW_RELEASES = [(2.0, "block-mid"), (5.0, "block-wide")]


def run_grow_leg(grow_idle_pumps=3):
    """Elasticity A-B on a virtual clock. One elastic job (tokens/s =
    width) launches on the 2-chip tier while blockers hold the 4- and
    8-chip slices; as each blocker finishes, the grow-enabled leg lets
    the REAL GrowPlanner relocate the job (``backend.reconfigure`` is
    recorded, the controller's resume is simulated by resubmitting at
    the target width, and each reshard costs a fixed dead-time
    penalty). The baseline leg runs the same timeline with the planner
    off. Also integrates the idle chip-second gap — wider-slice
    capacity sitting free while the job runs narrower — which the
    planner is supposed to reclaim."""
    elastic_ann = {
        "tpu.kubedl.io/elastic-resume": "true",
        "tpu.kubedl.io/workload-class": "train",
    }

    def wl(name, ann):
        return {
            "apiVersion": "kubeflow.org/v1", "kind": "JAXJob",
            "metadata": {
                "namespace": "bench", "name": name,
                "annotations": dict(ann),
            },
            "spec": {},
        }

    def leg(grow):
        recon = []

        class _Recorder:
            def reconfigure(self, ns, name, kind, api_version,
                            target_devices, reason):
                recon.append((ns, name, int(target_devices), reason))
                return True

        placed = {}
        fs = FleetScheduler(
            parse_pool(GROW_POOL),
            backend=_Recorder(),
            on_create=lambda w, t: placed.__setitem__(
                w["metadata"]["name"], t
            ),
            grow_enabled=grow,
            grow_idle_pumps=grow_idle_pumps,
            max_queue=8,
        )
        # Chips-proportional prior: the first blocker takes the widest
        # free slice, the second the next, the elastic job the 2-chip.
        fs.submit(wl("block-wide", {"tpu.kubedl.io/priority": "high"}))
        fs.submit(wl("block-mid", {"tpu.kubedl.io/priority": "high"}))
        fs.submit(wl("job", {**elastic_ann,
                             "tpu.kubedl.io/param.devices": "2"}))
        assert placed.get("job") == "small", placed

        chips = {t.name: t.chips for t in parse_pool(GROW_POOL)}
        now = 0.0
        tokens = 0.0
        width = 2
        idle_gap = 0.0
        free_wider = []  # chip widths of freed slices wider than `width`
        grows = 0
        jname = "job"

        def advance(to):
            nonlocal now, tokens, idle_gap
            dt = to - now
            tokens += width * dt
            if free_wider:
                idle_gap += (max(free_wider) - width) * dt
            now = to

        for rel_t, rel_name in GROW_RELEASES:
            if tokens + width * (rel_t - now) >= GROW_WORK_TOKENS:
                break  # done before this slice even frees
            advance(rel_t)
            fs.release("bench", rel_name)
            free_wider.append(chips[placed[rel_name]])
            if not grow:
                continue
            for _ in range(grow_idle_pumps):
                fs.pump()
            if recon and recon[-1][1] == jname:
                _ns, _n, target, reason = recon[-1]
                assert reason == "FleetGrow", recon
                # Reshard dead time, then the controller-side resume:
                # the regrown attempt lands on the freed wider slice.
                now += GROW_RESHARD_PENALTY_S
                grows += 1
                jname = f"job-r{grows}"
                fs.submit(wl(jname, {
                    **elastic_ann,
                    "tpu.kubedl.io/param.devices": str(target),
                    "tpu.kubedl.io/resume-of": "job",
                }))
                free_wider = [c for c in free_wider if c > target]
                width = target
        remaining = max(0.0, GROW_WORK_TOKENS - tokens)
        done_at = now + remaining / width
        if free_wider:
            idle_gap += (max(free_wider) - width) * (done_at - now)
        return {
            "completion_s": round(done_at, 3),
            "final_width": width,
            "grows": grows,
            "reconfigures": recon,
            "idle_gap_chip_s": round(idle_gap, 3),
        }

    grown = leg(True)
    base = leg(False)
    speedup = (
        base["completion_s"] / grown["completion_s"]
        if grown["completion_s"] else 0.0
    )
    reclaimed = (
        1.0 - grown["idle_gap_chip_s"] / base["idle_gap_chip_s"]
        if base["idle_gap_chip_s"] else 0.0
    )
    return {
        "pool": GROW_POOL,
        "work_tokens": GROW_WORK_TOKENS,
        "reshard_penalty_s": GROW_RESHARD_PENALTY_S,
        "grow": grown,
        "baseline": base,
        "grow_speedup": round(speedup, 3),
        "idle_reclaimed_frac": round(reclaimed, 4),
    }


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--jobs", type=int, default=10000,
                    help="storm size (default 10000)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--min-speedup", type=float, default=1.5,
                    help="required FIFO/hetero makespan ratio")
    ap.add_argument("--max-p50-ms", type=float, default=1.0,
                    help="placement decision p50 budget on the tick path")
    ap.add_argument("--jain-slack", type=float, default=0.02,
                    help="allowed Jain-fairness deficit vs the baseline")
    ap.add_argument("--min-grow-speedup", type=float, default=2.0,
                    help="required completion speedup of the grow leg "
                         "over the shrink-only baseline")
    ap.add_argument("--check", action="store_true",
                    help="small sizes; exit 1 on REGRESSION (CI smoke)")
    ap.add_argument("--stdout", action="store_true",
                    help="print the JSON document")
    ap.add_argument("--out", default=None,
                    help="output path (default BENCH_FLEET.json, "
                         "/dev/null to skip)")
    args = ap.parse_args(argv)

    n_jobs = 600 if args.check else args.jobs
    jobs = _job_mix(n_jobs, args.seed)
    hetero = run_storm("hetero", jobs)
    fifo = run_storm("fifo", jobs)
    zero_write = run_zero_write_leg()
    grow = run_grow_leg()

    speedup = fifo["makespan_s"] / hetero["makespan_s"]
    jain_ok = (
        hetero["jain_fairness"] >= fifo["jain_fairness"] - args.jain_slack
    )
    p50_ok = hetero["submit_p50_ms"] <= args.max_p50_ms
    zw_ok = zero_write["steady_state_store_writes"] == 0
    grow_ok = (
        grow["grow_speedup"] >= args.min_grow_speedup
        and grow["grow"]["grows"] == 2
        and grow["idle_reclaimed_frac"] >= 0.9
    )
    ok = (speedup >= args.min_speedup and jain_ok and p50_ok and zw_ok
          and grow_ok)

    doc = {
        "bench": "fleet",
        "pool": POOL,
        "seed": args.seed,
        "check_mode": bool(args.check),
        "hetero": hetero,
        "fifo": fifo,
        "makespan_speedup": round(speedup, 3),
        "min_speedup": args.min_speedup,
        "zero_write": zero_write,
        "grow_leg": grow,
        "min_grow_speedup": args.min_grow_speedup,
        "gates": {
            "makespan_speedup_ok": speedup >= args.min_speedup,
            "jain_ok": jain_ok,
            "submit_p50_ok": p50_ok,
            "steady_state_zero_write_ok": zw_ok,
            "grow_speedup_ok": grow_ok,
        },
        "verdict": "OK" if ok else "REGRESSION",
    }

    out = args.out or ("/dev/null" if args.check else "BENCH_FLEET.json")
    if out != "/dev/null":
        with open(out, "w") as f:
            json.dump(doc, f, indent=2, sort_keys=True)
            f.write("\n")
    if args.stdout:
        print(json.dumps(doc, sort_keys=True))
    print(
        f"fleet bench [{doc['verdict']}]: {n_jobs} jobs, makespan "
        f"hetero {hetero['makespan_s']}s vs fifo {fifo['makespan_s']}s "
        f"({speedup:.2f}x, need >= {args.min_speedup}x), Jain "
        f"{hetero['jain_fairness']} vs {fifo['jain_fairness']}, "
        f"submit p50 {hetero['submit_p50_ms']}ms "
        f"(<= {args.max_p50_ms}ms), steady-state writes "
        f"{zero_write['steady_state_store_writes']}, grow leg "
        f"{grow['grow_speedup']}x (need >= {args.min_grow_speedup}x, "
        f"{grow['grow']['grows']} grows, "
        f"{grow['idle_reclaimed_frac']:.0%} idle reclaimed)",
        file=sys.stderr,
    )
    return 0 if (ok or not args.check) else 1


if __name__ == "__main__":
    sys.exit(main())
