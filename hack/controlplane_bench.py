"""Reproducible control-plane benchmark (``make bench-controlplane``).

Measures what the embedded control plane sustains at 1k/5k Crons using
the REAL stack — ``APIServer`` + ``Manager`` worker pool + ``CronReconciler``
on a ``FakeClock`` — not a stripped-down reconcile loop:

- populate: N Cron creates (objects/s),
- ``list()`` latency: the two controller-shaped hot calls, all-Crons and
  label-selector workload listing (mean µs/call),
- fire sweep: advance the fake clock so every Cron has a due tick, start
  the manager (informer seed enqueues all N), and time until every Cron
  has created its workload — creation-bound by design; reconciles/s plus
  p50/p99 reconcile latency read from the live
  ``controller_runtime_reconcile_time_seconds`` histogram,
- list+reconcile sweep: a full no-tick-due reconcile pass over all N
  Crons against the now-populated store (every reconcile lists its
  children, recomputes the schedule, syncs status). This is the
  steady-state hot loop the indexes and schedule cache target, and the
  headline throughput number. The sweep also reports how many store
  commits it performed — with no-op status elision the target is zero,
- fire storm: the worst-case tick — every Cron in the fleet shares one
  schedule and fires on the SAME minute (its own APIServer+Manager
  stack, best of 2 runs). Crons/s from first enqueue to last workload
  create; this is the write-path headline,
- write-path microbench: mean µs per ``update`` / ``patch_status`` /
  no-op ``patch_status`` / ``create`` against the populated store,
  measured with the manager stopped so only the store is on the clock.

Emits a JSON artifact. ``--baseline-ref <git-ref>`` additionally runs the
same measurement against a detached worktree of that ref (the script only
touches APIs present on both sides), reports before/after speedups, and
prints a one-line OK/REGRESSION verdict over the headline metrics;
``--check`` exits non-zero when that verdict is REGRESSION — how the
committed BENCH_CONTROLPLANE.json numbers were produced and gated.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
# Code under test: an explicit tree (baseline subprocess) or this repo.
_TREE = os.environ.get("CPBENCH_TREE", REPO_ROOT)
sys.path.insert(0, _TREE)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

CRON_API_VERSION = "apps.kubedl.io/v1alpha1"
WORKLOAD_API_VERSION = "kubeflow.org/v1"
WORKLOAD_KIND = "JAXJob"
LABEL_CRON_NAME = "kubedl.io/cron-name"

SUCCESS_SERIES = (
    'controller_runtime_reconcile_total'
    '{controller="cron",result="success"}'
)
ERROR_SERIES = (
    'controller_runtime_reconcile_errors_total{controller="cron"}'
)
RECONCILE_HIST = (
    'controller_runtime_reconcile_time_seconds{controller="cron"}'
)


def _cron(i: int) -> dict:
    # Half standard 5-field specs (60 distinct minute offsets — exercises
    # the bit-scan engine and gives the compiled-schedule cache a realistic
    # key population), half one shared @every spec.
    schedule = f"{i % 60} * * * *" if i % 2 == 0 else "@every 3600s"
    return {
        "apiVersion": CRON_API_VERSION,
        "kind": "Cron",
        "metadata": {"name": f"bench-{i}", "namespace": "default"},
        "spec": {
            "schedule": schedule,
            "concurrencyPolicy": "Allow",
            "historyLimit": 3,
            "template": {"workload": {
                "apiVersion": WORKLOAD_API_VERSION,
                "kind": WORKLOAD_KIND,
                "metadata": {"annotations": {
                    "tpu.kubedl.io/accelerator": "v5e",
                    "tpu.kubedl.io/topology": "2x2",
                }},
                "spec": {"replicaSpecs": {"Worker": {"replicas": 1}}},
            }},
        },
    }


def _storm_cron(i: int) -> dict:
    """Same-tick variant: every Cron shares one schedule, so one clock
    advance makes the ENTIRE fleet due at once — the thundering-herd
    write storm the structural-sharing commit path targets."""
    c = _cron(i)
    c["spec"]["schedule"] = "0 * * * *"
    return c


def _hist_percentile(h, q: float):
    """Percentile upper bound from cumulative histogram buckets."""
    if not h or not h["count"]:
        return None
    target = q * h["count"]
    cum = 0
    for le, n in zip(h["buckets"], h["counts"]):
        cum += n
        if cum >= target:
            return le
    return float("inf")


def _time_calls(fn, repeat: int) -> float:
    """Mean µs per call."""
    t0 = time.perf_counter()
    for _ in range(repeat):
        fn()
    return (time.perf_counter() - t0) / repeat * 1e6


def _storm_once(n_crons: int, sweep_timeout_s: float, workers: int) -> dict:
    """One same-tick fire storm on a fresh stack: populate N identical-
    schedule Crons, advance the clock past their shared activation, and
    time from manager start to the last workload create."""
    import threading
    from datetime import timedelta
    from cron_operator_tpu.api.scheme import GVK_CRON, default_scheme
    from cron_operator_tpu.controller import CronReconciler
    from cron_operator_tpu.runtime import APIServer, Manager
    from cron_operator_tpu.utils.clock import FakeClock

    clock = FakeClock()
    api = APIServer(clock=clock)
    for i in range(n_crons):
        api.create(_storm_cron(i))

    created = threading.Semaphore(0)

    def _count(ev):
        if ev.type == "ADDED" and ev.object.get("kind") == WORKLOAD_KIND:
            created.release()

    api.add_watcher(_count)

    mgr = Manager(api, max_concurrent_reconciles=workers)
    rec = CronReconciler(api, metrics=mgr.metrics)
    mgr.add_controller(
        "cron", rec.reconcile, for_gvk=GVK_CRON,
        owns=default_scheme().workload_kinds(),
    )
    clock.advance(timedelta(minutes=61))

    # GC hygiene for the timed window: a cyclic-GC pass during the storm
    # scans every object the earlier (bigger) suite runs left behind and
    # can cost 20%+ of the measurement. Collect up front, then keep the
    # collector out of the storm — identical discipline on every tree.
    import gc

    gc.collect()
    gc.disable()
    try:
        t0 = time.perf_counter()
        mgr.start()
        deadline = t0 + sweep_timeout_s
        done = 0
        while done < n_crons and time.perf_counter() < deadline:
            if created.acquire(
                timeout=min(1.0, deadline - time.perf_counter())
            ):
                done += 1
        storm_s = time.perf_counter() - t0
    finally:
        gc.enable()
    reconciles = mgr.metrics.get(SUCCESS_SERIES)
    mgr.stop()
    api.close()
    return {
        "fire_storm_s": round(storm_s, 3),
        "fire_storm_timed_out": done < n_crons,
        "fire_storm_workloads_created": done,
        "fire_storm_crons_per_s": (
            round(done / storm_s, 1) if storm_s else 0.0
        ),
        "fire_storm_reconciles_at_done": reconciles,
    }


def storm_best_of(
    n_crons: int, sweep_timeout_s: float, workers: int = 1, reps: int = 2
) -> dict:
    """Best of ``reps`` storms (throughput benches conventionally report
    the least-interfered-with run, cf. ``timeit``'s min-of-repeats).

    ``workers`` defaults to 1: the storm is pure CPU against an
    in-process store, so extra workers only add GIL contention — more
    wall-clock AND more run-to-run noise on every tree measured. The
    parallel-worker configuration is still covered by the mixed-schedule
    fire sweep above (workers=10).
    """
    best = None
    for _ in range(reps):
        r = _storm_once(n_crons, sweep_timeout_s, workers)
        if best is None or (
            r["fire_storm_crons_per_s"] > best["fire_storm_crons_per_s"]
        ):
            best = r
    best["fire_storm_workers"] = workers
    best["fire_storm_reps"] = reps
    return best


def _write_microbench(api, repeat: int = 200) -> dict:
    """Mean µs per store write verb against the populated store. Run with
    the manager STOPPED so the numbers isolate the commit path (on trees
    without generation-predicate filtering, a running manager would
    react to every metadata touch and pollute the timing with reconcile
    work)."""
    import copy

    def _update_once():
        obj = copy.deepcopy(
            api.try_get(CRON_API_VERSION, "Cron", "default", "bench-0")
        )
        labels = obj["metadata"].setdefault("labels", {})
        labels["bench-touch"] = obj["metadata"]["resourceVersion"]
        api.update(obj)

    update_us = _time_calls(_update_once, repeat)

    seq = [0]

    def _patch_changed():
        seq[0] += 1
        api.patch_status(
            CRON_API_VERSION, "Cron", "default", "bench-1",
            {"benchSeq": str(seq[0])},
        )

    patch_us = _time_calls(_patch_changed, repeat)

    # Same status every time: with no-op elision this never commits.
    noop_status = {"benchSeq": "steady"}

    def _patch_noop():
        api.patch_status(
            CRON_API_VERSION, "Cron", "default", "bench-2",
            dict(noop_status),
        )

    _patch_noop()  # seed so every timed call is a true no-op
    noop_us = _time_calls(_patch_noop, repeat)

    mk = [0]

    def _create_once():
        mk[0] += 1
        api.create({
            "apiVersion": WORKLOAD_API_VERSION,
            "kind": WORKLOAD_KIND,
            "metadata": {
                "name": f"mb-{mk[0]}", "namespace": "default",
                "labels": {LABEL_CRON_NAME: "bench-0"},
            },
            "spec": {"replicaSpecs": {"Worker": {"replicas": 1}}},
        })

    create_us = _time_calls(_create_once, repeat)

    return {
        "update_us": round(update_us, 1),
        "patch_status_us": round(patch_us, 1),
        "noop_patch_status_us": round(noop_us, 1),
        "create_us": round(create_us, 1),
    }


def _wal_microbench(repeat: int = 200) -> dict:
    """The same write-verb microbench against a WAL-attached store on a
    private tempdir — the steady-state durability overhead. Also proves
    (not just reports) that no-op status elision keeps the WAL silent:
    a bracketed no-op loop must append ZERO records."""
    try:
        from cron_operator_tpu.runtime.persistence import Persistence
    except ImportError:  # baseline trees predate the durability layer
        return {}
    import shutil

    from cron_operator_tpu.runtime import APIServer
    from cron_operator_tpu.utils.clock import FakeClock

    data_dir = tempfile.mkdtemp(prefix="cpbench-wal-")
    try:
        api = APIServer(clock=FakeClock())
        pers = Persistence(data_dir)
        pers.start(api)
        for i in range(3):
            api.create(_cron(i))
        out = {
            f"wal_{k}": v
            for k, v in _write_microbench(api, repeat).items()
        }
        # No-op elision reaches the WAL layer: re-patching an unchanged
        # status never commits, so it never appends either.
        api.patch_status(
            CRON_API_VERSION, "Cron", "default", "bench-2",
            {"benchSeq": "steady"},
        )
        before = pers.stats()["records_appended"]
        for _ in range(repeat):
            api.patch_status(
                CRON_API_VERSION, "Cron", "default", "bench-2",
                {"benchSeq": "steady"},
            )
        noop_records = pers.stats()["records_appended"] - before
        assert noop_records == 0, (
            f"no-op patches appended {noop_records} WAL records"
        )
        stats = pers.stats()
        out["wal_noop_records"] = noop_records
        out["wal_records_appended"] = stats["records_appended"]
        out["wal_fsyncs"] = stats["fsyncs"]
        pers.close()
        api.close()
        return out
    finally:
        shutil.rmtree(data_dir, ignore_errors=True)


#: Absolute ceiling on the self-verifying format's per-record cost:
#: ``stamp_crc`` rides inside the WAL lock on EVERY append and
#: ``verify_line`` on every replayed/scrubbed record, so each is gated
#: here (not merely reported) — the durability-integrity upgrade must
#: stay invisible next to the fsync it protects.
CRC_APPEND_GATE_US = 2.0


def _crc_microbench(repeat: int = 2000) -> dict:
    """The checksummed-WAL overhead, three ways: (a) one bare
    ``stamp_crc`` over a representative serialized record — the exact
    cost added to every append — gated at ``CRC_APPEND_GATE_US``; (b)
    one bare ``verify_line`` over the stamped line — the replay/scrub
    cost per record — gated the same; and (c) the write microbench
    re-run against a ``checksums=False`` legacy-format store
    (``wal_nocrc_*`` keys), so the committed artifact carries the
    end-to-end A/B next to the default checksummed ``wal_*`` numbers."""
    try:
        from cron_operator_tpu.runtime.persistence import (
            CRC_IMPL,
            Persistence,
            stamp_crc,
            verify_line,
        )
    except ImportError:  # baseline trees predate the integrity format
        return {}
    import shutil

    from cron_operator_tpu.runtime import APIServer
    from cron_operator_tpu.utils.clock import FakeClock

    # A representative committed record: the exact shape _append
    # serializes for a populated-store Cron update.
    body = json.dumps(
        {"op": "put", "verb": "update", "rv": 123456, "obj": _cron(7)},
        separators=(",", ":"),
        default=str,
    ).encode("utf-8")
    stamp_us = min(
        _time_calls(lambda: stamp_crc(body), repeat) for _ in range(3)
    )
    assert stamp_us <= CRC_APPEND_GATE_US, (
        f"CRC stamping costs {stamp_us:.2f}µs/record "
        f"(gate: {CRC_APPEND_GATE_US}µs, impl: {CRC_IMPL})"
    )

    line = stamp_crc(body)
    verify_us = min(
        _time_calls(lambda: verify_line(line), repeat) for _ in range(3)
    )
    assert verify_us <= CRC_APPEND_GATE_US, (
        f"CRC verification costs {verify_us:.2f}µs/record "
        f"(gate: {CRC_APPEND_GATE_US}µs, impl: {CRC_IMPL})"
    )

    # (c) the same write microbench against the LEGACY format — the
    # delta against the default checksummed wal_* keys is the whole
    # end-to-end price of the self-verifying format.
    data_dir = tempfile.mkdtemp(prefix="cpbench-nocrc-")
    try:
        api = APIServer(clock=FakeClock())
        pers = Persistence(data_dir, checksums=False)
        pers.start(api)
        for i in range(3):
            api.create(_cron(i))
        out = {
            f"wal_nocrc_{k}": v
            for k, v in _write_microbench(api, 200).items()
        }
        pers.close()
        api.close()
    finally:
        shutil.rmtree(data_dir, ignore_errors=True)
    out.update({
        "crc_impl": CRC_IMPL,
        "crc_stamp_us": round(stamp_us, 3),
        "crc_verify_us": round(verify_us, 3),
        "crc_append_gate_us": CRC_APPEND_GATE_US,
    })
    return out


#: Absolute ceiling on the flight recorder's hot-path cost: one
#: ``AuditJournal.record`` call rides inside the store lock on EVERY
#: committed verb, so its mean cost is pure commit-path overhead and is
#: gated here (not merely reported).
AUDIT_RECORD_GATE_US = 5.0


def _audit_microbench(repeat: int = 500) -> dict:
    """The flight-recorder overhead, three ways: (a) one bare
    ``AuditJournal.record`` call — the exact cost added to every
    committed verb — gated at ``AUDIT_RECORD_GATE_US``; (b) the write
    microbench re-run against a WAL + journal attached store
    (``audited_*`` keys) — the full durable+audited commit path; and
    (c) the audit ≡ WAL cross-check over everything (b) just wrote,
    proving the bench's own traffic satisfies invariant I9."""
    try:
        from cron_operator_tpu.runtime.persistence import Persistence
        from cron_operator_tpu.telemetry.audit import AuditJournal
    except ImportError:  # baseline trees predate the flight recorder
        return {}
    import shutil

    from cron_operator_tpu.runtime import APIServer
    from cron_operator_tpu.utils.clock import FakeClock

    # (a) bare record() — ring only, no sink, exactly what the store
    # lock pays per commit. Best-of-3 reps, same discipline as the
    # storm (report the least-interfered-with run).
    bare = AuditJournal()
    pos = [0]

    def _record_once():
        pos[0] += 1
        bare.record(
            "store", "update",
            key=f"{CRON_API_VERSION}/Cron/default/bench-0",
            wal_pos=pos[0], rv=pos[0],
        )

    record_us = min(_time_calls(_record_once, repeat) for _ in range(3))
    assert record_us <= AUDIT_RECORD_GATE_US, (
        f"audit record() hot path costs {record_us:.2f}µs/verb "
        f"(gate: {AUDIT_RECORD_GATE_US}µs)"
    )

    # (b)+(c) the audited end-to-end write path on a private store.
    data_dir = tempfile.mkdtemp(prefix="cpbench-audit-")
    try:
        api = APIServer(clock=FakeClock())
        journal = AuditJournal()
        pers = Persistence(data_dir)
        pers.attach_audit(journal)
        pers.start(api)
        api.attach_audit(journal)
        for i in range(3):
            api.create(_cron(i))
        out = {
            f"audited_{k}": v
            for k, v in _write_microbench(api, repeat).items()
        }
        out["audit_record_us"] = round(record_us, 2)
        out["audit_record_gate_us"] = AUDIT_RECORD_GATE_US
        # Every durable record audited, every audited verb durable —
        # over the bench's own thousands of writes.
        check = journal.wal_check(pers.stats()["records_appended"])
        assert check["ok"], f"audit ≡ WAL failed under the bench: {check}"
        out["audit_wal_check_ok"] = check["ok"]
        out["audit_records_total"] = journal.total
        pers.close()
        api.close()
        journal.close()
        return out
    finally:
        shutil.rmtree(data_dir, ignore_errors=True)


def _timeseries_microbench(repeat: int = 500) -> dict:
    """History-layer overhead: one ``TimeSeriesStore.append`` (which a
    history-opted ``Metrics.inc``/``set`` pays per sample, across ALL
    resolution rings) — gated at ``TIMESERIES_APPEND_GATE_US``, the same
    discipline as the audit-record gate above. Also times the full
    instrumented ``Metrics.set`` for the end-to-end per-sample cost."""
    try:
        from cron_operator_tpu.telemetry.timeseries import (
            TIMESERIES_APPEND_GATE_US,
            TimeSeriesStore,
        )
    except ImportError:  # baseline trees predate the observatory
        return {}
    from cron_operator_tpu.runtime.manager import Metrics

    store = TimeSeriesStore()
    tick = [0.0]

    def _append_once():
        tick[0] += 0.01
        store.append("fleet_utilization", 0.5, ts=tick[0])

    append_us = min(_time_calls(_append_once, repeat) for _ in range(3))
    assert append_us <= TIMESERIES_APPEND_GATE_US, (
        f"timeseries append() hot path costs {append_us:.2f}µs/sample "
        f"(gate: {TIMESERIES_APPEND_GATE_US}µs)"
    )

    metrics = Metrics()
    metrics.instrument(TimeSeriesStore(), families=("fleet_utilization",))

    def _set_once():
        metrics.set("fleet_utilization", 0.5)

    instrumented_set_us = min(
        _time_calls(_set_once, repeat) for _ in range(3)
    )
    return {
        "timeseries_append_us": round(append_us, 2),
        "timeseries_append_gate_us": TIMESERIES_APPEND_GATE_US,
        "instrumented_gauge_set_us": round(instrumented_set_us, 2),
    }


#: Ceiling on per-frame trace-context plumbing: every traced request pays
#: one format (client header), one parse (server front door), and one
#: ambient set/reset round-trip, all inside the serve path — so the whole
#: bundle is gated, not merely reported.
TRACE_CTX_GATE_US = 5.0


def _trace_ctx_microbench(repeat: int = 2000) -> dict:
    """Trace-context propagation overhead: the exact per-frame work a
    traced request adds — ``format_traceparent`` on the outbound hop,
    ``parse_traceparent`` at the next front door, and the ambient
    contextvar set / read / reset bracket around the handler — timed as
    one bundle and gated at ``TRACE_CTX_GATE_US``."""
    try:
        from cron_operator_tpu.telemetry.trace import (
            TraceContext,
            current_trace_id,
            format_traceparent,
            new_span_id,
            new_trace_id,
            parse_traceparent,
            reset_current_trace,
            set_current_trace,
        )
    except ImportError:  # baseline trees predate distributed tracing
        return {}

    ctx = TraceContext(new_trace_id(), new_span_id())

    def _frame_once():
        header = format_traceparent(ctx.trace_id, ctx.span_id)
        parsed = parse_traceparent(header)
        token = set_current_trace(parsed)
        current_trace_id()
        reset_current_trace(token)

    frame_us = min(_time_calls(_frame_once, repeat) for _ in range(3))
    assert frame_us <= TRACE_CTX_GATE_US, (
        f"trace-context propagation costs {frame_us:.2f}µs/frame "
        f"(gate: {TRACE_CTX_GATE_US}µs)"
    )

    parse_us = min(
        _time_calls(
            lambda: parse_traceparent(
                format_traceparent(ctx.trace_id, ctx.span_id)
            ),
            repeat,
        )
        for _ in range(3)
    )
    return {
        "trace_ctx_frame_us": round(frame_us, 2),
        "trace_ctx_gate_us": TRACE_CTX_GATE_US,
        "trace_ctx_format_parse_us": round(parse_us, 2),
    }


def run_one(n_crons: int, sweep_timeout_s: float) -> dict:
    from datetime import timedelta
    from cron_operator_tpu.api.scheme import GVK_CRON, default_scheme
    from cron_operator_tpu.controller import CronReconciler
    from cron_operator_tpu.runtime import APIServer, Manager
    from cron_operator_tpu.utils.clock import FakeClock

    clock = FakeClock()
    api = APIServer(clock=clock)

    t0 = time.perf_counter()
    for i in range(n_crons):
        api.create(_cron(i))
    populate_s = time.perf_counter() - t0

    list_repeat = max(5, min(50, 20000 // n_crons))
    cron_list_us = _time_calls(
        lambda: api.list(CRON_API_VERSION, "Cron", namespace="default"),
        list_repeat,
    )
    # The reconciler's per-Cron child listing shape (label selector).
    label_list_us = _time_calls(
        lambda: api.list(
            WORKLOAD_API_VERSION, WORKLOAD_KIND, namespace="default",
            label_selector={LABEL_CRON_NAME: "bench-0"},
        ),
        list_repeat,
    )

    # Count workload creations through a watch subscriber: identical cost
    # on every tree, and avoids polling list() during the timed sweep.
    import threading

    created = threading.Semaphore(0)
    created_n = [0]

    def _count(ev):
        if ev.type == "ADDED" and ev.object.get("kind") == WORKLOAD_KIND:
            created_n[0] += 1
            created.release()

    api.add_watcher(_count)

    mgr = Manager(api, max_concurrent_reconciles=10)
    rec = CronReconciler(api, metrics=mgr.metrics)
    mgr.add_controller(
        "cron", rec.reconcile, for_gvk=GVK_CRON,
        owns=default_scheme().workload_kinds(),
    )
    # Every standard spec fires within the next 60 min; the @every specs
    # have exactly one due tick after 61 min.
    clock.advance(timedelta(minutes=61))

    t0 = time.perf_counter()
    mgr.start()
    deadline = t0 + sweep_timeout_s
    done = 0
    while done < n_crons and time.perf_counter() < deadline:
        if created.acquire(timeout=min(1.0, deadline - time.perf_counter())):
            done += 1
    fire_s = time.perf_counter() - t0
    timed_out = done < n_crons
    successes = mgr.metrics.get(SUCCESS_SERIES)
    errors = mgr.metrics.get(ERROR_SERIES)

    # The headline: a full list+reconcile pass over every Cron with no
    # tick due — each reconcile lists its child workloads, recomputes
    # the schedule and syncs status against the populated store. The
    # resourceVersion counter brackets the sweep: every commit bumps it
    # exactly once, so the delta IS the sweep's store-write count (and
    # with no-op status elision it must be zero). A short settle first
    # lets in-flight manager writes from the fire sweep drain so they
    # don't land inside the bracket.
    time.sleep(0.5)
    rv_before = getattr(api, "_rv", None)
    t0 = time.perf_counter()
    for i in range(n_crons):
        rec.reconcile("default", f"bench-{i}")
    list_reconcile_s = time.perf_counter() - t0
    rv_after = getattr(api, "_rv", None)
    sweep_writes = (
        rv_after - rv_before
        if rv_before is not None and rv_after is not None else None
    )

    hist = mgr.metrics.histogram(RECONCILE_HIST)
    mgr.stop()
    write_us = _write_microbench(api)
    write_us.update(_wal_microbench())
    write_us.update(_crc_microbench())
    write_us.update(_audit_microbench())
    write_us.update(_timeseries_microbench())
    write_us.update(_trace_ctx_microbench())
    api.close()

    storm = storm_best_of(n_crons, sweep_timeout_s)

    return {
        **write_us,
        **storm,
        "list_reconcile_store_writes": sweep_writes,
        "n_crons": n_crons,
        "populate_objects_per_s": round(n_crons / populate_s, 1),
        "cron_list_us": round(cron_list_us, 1),
        "workload_label_list_us": round(label_list_us, 1),
        "fire_sweep_s": round(fire_s, 3),
        "fire_sweep_timed_out": timed_out,
        "fire_sweep_workloads_created": done,
        "fire_sweep_crons_per_s": (
            round(done / fire_s, 1) if fire_s else 0.0
        ),
        "fire_sweep_reconciles_per_s": (
            round(successes / fire_s, 1) if fire_s else 0.0
        ),
        "reconcile_errors": errors,
        "reconcile_p50_s": _hist_percentile(hist, 0.50),
        "reconcile_p99_s": _hist_percentile(hist, 0.99),
        "list_reconcile_sweep_per_s": round(
            n_crons / list_reconcile_s, 1),
    }


def _sharded_leg(total: int, n_shards: int) -> dict:
    """One sharded steady-state leg: ``total`` Crons hash-partitioned
    over ``n_shards`` shards (runtime/shard.py), each shard running its
    own reconciler directly against its own store.

    Shards are measured SEQUENTIALLY and the aggregate is their sum:
    this host is single-CPU, and shards share nothing (no lock, no
    store, no WAL), so the sum is the shared-nothing scale-out
    projection — per-shard throughput is the honest primitive, and a
    deployment with one core per shard achieves the aggregate. The
    output says so explicitly (``aggregate_is``).
    """
    import gc

    from cron_operator_tpu.controller import CronReconciler
    from cron_operator_tpu.runtime import APIServer
    from cron_operator_tpu.runtime.shard import ShardRouter, shard_index
    from cron_operator_tpu.utils.clock import FakeClock

    clock = FakeClock()
    stores = [APIServer(clock=clock) for _ in range(n_shards)]
    router = ShardRouter(stores)

    t0 = time.perf_counter()
    for i in range(total):
        router.create(_cron(i))
    populate_s = time.perf_counter() - t0

    # Router fan-in list: the cross-shard read a dashboard/facade makes.
    router_list_us = _time_calls(
        lambda: router.list(CRON_API_VERSION, "Cron", namespace="default"),
        max(3, min(20, 20000 // total)),
    )

    names_by_shard: list = [[] for _ in range(n_shards)]
    for i in range(total):
        name = f"bench-{i}"
        names_by_shard[shard_index("default", name, n_shards)].append(name)

    shards_out = []
    aggregate_per_s = 0.0
    all_zero_writes = True
    for si, store in enumerate(stores):
        rec = CronReconciler(store)
        names = names_by_shard[si]
        # Warm-up pass: first-touch status syncs and schedule-cache fill
        # are allowed to write; the TIMED pass below is steady state.
        for name in names:
            rec.reconcile("default", name)
        gc.collect()
        gc.disable()
        try:
            rv_before = getattr(store, "_rv", None)
            t0 = time.perf_counter()
            for name in names:
                rec.reconcile("default", name)
            sweep_s = time.perf_counter() - t0
            rv_after = getattr(store, "_rv", None)
        finally:
            gc.enable()
        writes = (
            rv_after - rv_before
            if rv_before is not None and rv_after is not None else None
        )
        per_s = round(len(names) / sweep_s, 1) if sweep_s else 0.0
        ok = writes == 0
        all_zero_writes = all_zero_writes and ok
        shards_out.append({
            "shard": si,
            "crons": len(names),
            "list_reconcile_sweep_per_s": per_s,
            "store_writes": writes,
            "verdict": "OK" if ok else "REGRESSION",
        })
        aggregate_per_s += per_s
    for store in stores:
        store.close()

    return {
        "n_shards": n_shards,
        "total_crons": total,
        "populate_objects_per_s": round(total / populate_s, 1),
        "router_cron_list_us": round(router_list_us, 1),
        "shards": shards_out,
        "aggregate_list_reconcile_sweep_per_s": round(aggregate_per_s, 1),
        "all_shards_zero_writes": all_zero_writes,
        "aggregate_is": (
            "sum of per-shard throughputs measured sequentially on one "
            "core; shards share nothing, so a one-core-per-shard "
            "deployment achieves this aggregate"
        ),
    }


def run_sharded_suite(total: int, shard_counts, min_scaleup: float) -> dict:
    """The sharded scale-out sweep (``make bench-shards``): the same
    100k-Cron steady-state workload at each shard count, with per-shard
    and aggregate OK/REGRESSION verdicts. The aggregate verdict needs
    the largest shard count to reach ``min_scaleup``× the smallest's
    aggregate throughput AND zero steady-state writes on every shard."""
    legs = [_sharded_leg(total, n) for n in shard_counts]
    base = min(legs, key=lambda leg: leg["n_shards"])
    peak = max(legs, key=lambda leg: leg["n_shards"])
    scaleup = None
    if base["aggregate_list_reconcile_sweep_per_s"]:
        scaleup = round(
            peak["aggregate_list_reconcile_sweep_per_s"]
            / base["aggregate_list_reconcile_sweep_per_s"], 2,
        )
    zero = all(leg["all_shards_zero_writes"] for leg in legs)
    ok = scaleup is not None and scaleup >= min_scaleup and zero
    verdict = {
        "status": "OK" if ok else "REGRESSION",
        "scaleup": scaleup,
        "required_scaleup": min_scaleup,
        "all_shards_zero_writes": zero,
        "summary": (
            f"{'OK' if ok else 'REGRESSION'}: aggregate sweep at "
            f"{peak['n_shards']} shards is {scaleup}x the "
            f"{base['n_shards']}-shard aggregate (need >= {min_scaleup}x); "
            f"steady-state store writes "
            f"{'zero on every shard' if zero else 'NONZERO on some shard'}"
        ),
    }
    return {
        "schema": "controlplane-bench-sharded/v1",
        "git_ref": _git_ref(_TREE),
        "total_crons": total,
        "legs": legs,
        "verdict": verdict,
    }


def _git_ref(tree: str) -> str:
    try:
        ref = subprocess.run(
            ["git", "-C", tree, "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, check=True,
        ).stdout.strip()
        porcelain = subprocess.run(
            ["git", "-C", tree, "status", "--porcelain"],
            capture_output=True, text=True, check=True,
        ).stdout.strip()
        return f"{ref}-dirty" if porcelain else ref
    except Exception:
        return "unknown"


def run_suite(sizes, sweep_timeout_s: float) -> dict:
    return {
        "schema": "controlplane-bench/v1",
        "git_ref": _git_ref(_TREE),
        "results": [run_one(n, sweep_timeout_s) for n in sizes],
    }


def _run_baseline(ref: str, sizes, timeout_s: float) -> dict:
    """Run this same script against a detached worktree of ``ref``."""
    tree = tempfile.mkdtemp(prefix="cpbench-baseline-")
    subprocess.run(
        ["git", "-C", REPO_ROOT, "worktree", "add", "--detach", tree, ref],
        check=True, capture_output=True, text=True,
    )
    try:
        env = dict(os.environ, CPBENCH_TREE=tree, JAX_PLATFORMS="cpu")
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--sizes", ",".join(str(s) for s in sizes),
             "--sweep-timeout", str(timeout_s), "--stdout"],
            env=env, capture_output=True, text=True,
            timeout=timeout_s * (len(sizes) + 1) + 600,
        )
        if out.returncode != 0:
            raise RuntimeError(
                f"baseline run failed rc={out.returncode}: "
                f"{out.stderr[-800:]}"
            )
        return json.loads(out.stdout.strip().splitlines()[-1])
    finally:
        subprocess.run(
            ["git", "-C", REPO_ROOT, "worktree", "remove", "--force", tree],
            capture_output=True,
        )


def _speedups(before: dict, after: dict) -> list:
    out = []
    by_n = {r["n_crons"]: r for r in before["results"]}
    for a in after["results"]:
        b = by_n.get(a["n_crons"])
        if not b:
            continue

        def ratio(key, invert=False):
            x, y = b.get(key), a.get(key)
            if not x or not y:
                return None
            return round(x / y, 2) if invert else round(y / x, 2)

        out.append({
            "n_crons": a["n_crons"],
            "list_reconcile_sweep_per_s": ratio(
                "list_reconcile_sweep_per_s"),
            "fire_sweep_crons_per_s": ratio("fire_sweep_crons_per_s"),
            "fire_storm_crons_per_s": ratio("fire_storm_crons_per_s"),
            "cron_list_us": ratio("cron_list_us", invert=True),
            "workload_label_list_us": ratio(
                "workload_label_list_us", invert=True),
            "populate_objects_per_s": ratio("populate_objects_per_s"),
            "update_us": ratio("update_us", invert=True),
            "patch_status_us": ratio("patch_status_us", invert=True),
            "noop_patch_status_us": ratio(
                "noop_patch_status_us", invert=True),
            "create_us": ratio("create_us", invert=True),
            "wal_create_us": ratio("wal_create_us", invert=True),
            "wal_patch_status_us": ratio(
                "wal_patch_status_us", invert=True),
        })
    return out


# The metrics the OK/REGRESSION verdict (and ``--check``) gates on: the
# steady-state headline and the write-path headline.
HEADLINE_METRICS = ("list_reconcile_sweep_per_s", "fire_storm_crons_per_s")


def _verdict(speedups: list) -> dict:
    """One-line regression verdict over the headline speedups."""
    parts = []
    worst = None
    for s in speedups:
        for key in HEADLINE_METRICS:
            r = s.get(key)
            if r is None:
                continue
            parts.append(f"{key}@{s['n_crons']}={r}x")
            if worst is None or r < worst:
                worst = r
    status = "OK" if worst is not None and worst >= 1.0 else "REGRESSION"
    if worst is None:
        summary = "REGRESSION: no comparable headline metrics"
    else:
        summary = f"{status}: worst headline speedup {worst}x ({', '.join(parts)})"
    return {"status": status, "worst_speedup": worst, "summary": summary}


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--sizes", default="1000,5000",
                   help="comma-separated Cron counts")
    p.add_argument("--out", default=os.path.join(
        REPO_ROOT, "BENCH_CONTROLPLANE.json"))
    p.add_argument("--baseline-ref", default=None,
                   help="git ref to measure as the 'before' tree")
    p.add_argument("--sweep-timeout", type=float, default=900.0)
    p.add_argument("--stdout", action="store_true",
                   help="print the artifact JSON to stdout only")
    p.add_argument("--check", action="store_true",
                   help="with --baseline-ref (or --shards-sweep): exit "
                        "non-zero when the verdict is REGRESSION")
    p.add_argument("--shards-sweep", action="store_true",
                   help="run the sharded scale-out sweep instead of the "
                        "single-store suite; merges a 'sharded' section "
                        "into --out (make bench-shards)")
    p.add_argument("--shards-total", type=int, default=100000,
                   help="total Crons for the sharded sweep")
    p.add_argument("--shard-counts", default="1,4",
                   help="comma-separated shard counts for the sharded "
                        "sweep")
    p.add_argument("--shards-min-scaleup", type=float, default=3.0,
                   help="required aggregate speedup of the largest shard "
                        "count over the smallest")
    args = p.parse_args()
    if args.check and not (args.baseline_ref or args.shards_sweep):
        p.error("--check requires --baseline-ref or --shards-sweep")
    sizes = [int(s) for s in args.sizes.split(",") if s]

    if args.shards_sweep:
        counts = [int(s) for s in args.shard_counts.split(",") if s]
        sharded = run_sharded_suite(
            args.shards_total, counts, args.shards_min_scaleup
        )
        for leg in sharded["legs"]:
            for s in leg["shards"]:
                print(
                    f"shard {s['shard']}/{leg['n_shards']}: "
                    f"{s['list_reconcile_sweep_per_s']} crons/s, "
                    f"store_writes={s['store_writes']} [{s['verdict']}]",
                    file=sys.stderr,
                )
            print(
                f"aggregate@{leg['n_shards']} shards: "
                f"{leg['aggregate_list_reconcile_sweep_per_s']} crons/s",
                file=sys.stderr,
            )
        print(sharded["verdict"]["summary"], file=sys.stderr)
        if args.stdout:
            print(json.dumps(sharded))
        else:
            # Merge into the existing artifact (the single-store suite's
            # numbers stay authoritative for their sections).
            merged = {}
            if os.path.exists(args.out):
                with open(args.out) as f:
                    merged = json.load(f)
            merged["sharded"] = sharded
            with open(args.out, "w") as f:
                f.write(json.dumps(merged, indent=2, sort_keys=True) + "\n")
            print(f"wrote {args.out} (sharded section)", file=sys.stderr)
        if args.check and sharded["verdict"]["status"] != "OK":
            return 2
        return 0

    after = run_suite(sizes, args.sweep_timeout)
    artifact = after
    verdict = None
    if args.baseline_ref:
        before = _run_baseline(args.baseline_ref, sizes, args.sweep_timeout)
        speedup = _speedups(before, after)
        verdict = _verdict(speedup)
        artifact = {
            "schema": "controlplane-bench-compare/v1",
            "before": before,
            "after": after,
            "speedup": speedup,
            "verdict": verdict,
        }

    text = json.dumps(artifact, indent=2, sort_keys=True)
    if args.stdout:
        print(json.dumps(artifact))
    else:
        with open(args.out, "w") as f:
            f.write(text + "\n")
        print(text)
        print(f"\nwrote {args.out}", file=sys.stderr)
    if verdict is not None:
        print(verdict["summary"], file=sys.stderr)
        if args.check and verdict["status"] != "OK":
            return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
