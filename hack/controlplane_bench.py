"""Reproducible control-plane benchmark (``make bench-controlplane``).

Measures what the embedded control plane sustains at 1k/5k Crons using
the REAL stack — ``APIServer`` + ``Manager`` worker pool + ``CronReconciler``
on a ``FakeClock`` — not a stripped-down reconcile loop:

- populate: N Cron creates (objects/s),
- ``list()`` latency: the two controller-shaped hot calls, all-Crons and
  label-selector workload listing (mean µs/call),
- fire sweep: advance the fake clock so every Cron has a due tick, start
  the manager (informer seed enqueues all N), and time until every Cron
  has created its workload — creation-bound by design; reconciles/s plus
  p50/p99 reconcile latency read from the live
  ``controller_runtime_reconcile_time_seconds`` histogram,
- list+reconcile sweep: a full no-tick-due reconcile pass over all N
  Crons against the now-populated store (every reconcile lists its
  children, recomputes the schedule, syncs status). This is the
  steady-state hot loop the indexes and schedule cache target, and the
  headline throughput number.

Emits a JSON artifact. ``--baseline-ref <git-ref>`` additionally runs the
same measurement against a detached worktree of that ref (the script only
touches APIs present on both sides) and reports before/after speedups —
how the committed BENCH_CONTROLPLANE.json numbers were produced.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
# Code under test: an explicit tree (baseline subprocess) or this repo.
_TREE = os.environ.get("CPBENCH_TREE", REPO_ROOT)
sys.path.insert(0, _TREE)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

CRON_API_VERSION = "apps.kubedl.io/v1alpha1"
WORKLOAD_API_VERSION = "kubeflow.org/v1"
WORKLOAD_KIND = "JAXJob"
LABEL_CRON_NAME = "kubedl.io/cron-name"

SUCCESS_SERIES = (
    'controller_runtime_reconcile_total'
    '{controller="cron",result="success"}'
)
ERROR_SERIES = (
    'controller_runtime_reconcile_errors_total{controller="cron"}'
)
RECONCILE_HIST = (
    'controller_runtime_reconcile_time_seconds{controller="cron"}'
)


def _cron(i: int) -> dict:
    # Half standard 5-field specs (60 distinct minute offsets — exercises
    # the bit-scan engine and gives the compiled-schedule cache a realistic
    # key population), half one shared @every spec.
    schedule = f"{i % 60} * * * *" if i % 2 == 0 else "@every 3600s"
    return {
        "apiVersion": CRON_API_VERSION,
        "kind": "Cron",
        "metadata": {"name": f"bench-{i}", "namespace": "default"},
        "spec": {
            "schedule": schedule,
            "concurrencyPolicy": "Allow",
            "historyLimit": 3,
            "template": {"workload": {
                "apiVersion": WORKLOAD_API_VERSION,
                "kind": WORKLOAD_KIND,
                "metadata": {"annotations": {
                    "tpu.kubedl.io/accelerator": "v5e",
                    "tpu.kubedl.io/topology": "2x2",
                }},
                "spec": {"replicaSpecs": {"Worker": {"replicas": 1}}},
            }},
        },
    }


def _hist_percentile(h, q: float):
    """Percentile upper bound from cumulative histogram buckets."""
    if not h or not h["count"]:
        return None
    target = q * h["count"]
    cum = 0
    for le, n in zip(h["buckets"], h["counts"]):
        cum += n
        if cum >= target:
            return le
    return float("inf")


def _time_calls(fn, repeat: int) -> float:
    """Mean µs per call."""
    t0 = time.perf_counter()
    for _ in range(repeat):
        fn()
    return (time.perf_counter() - t0) / repeat * 1e6


def run_one(n_crons: int, sweep_timeout_s: float) -> dict:
    from datetime import timedelta
    from cron_operator_tpu.api.scheme import GVK_CRON, default_scheme
    from cron_operator_tpu.controller import CronReconciler
    from cron_operator_tpu.runtime import APIServer, Manager
    from cron_operator_tpu.utils.clock import FakeClock

    clock = FakeClock()
    api = APIServer(clock=clock)

    t0 = time.perf_counter()
    for i in range(n_crons):
        api.create(_cron(i))
    populate_s = time.perf_counter() - t0

    list_repeat = max(5, min(50, 20000 // n_crons))
    cron_list_us = _time_calls(
        lambda: api.list(CRON_API_VERSION, "Cron", namespace="default"),
        list_repeat,
    )
    # The reconciler's per-Cron child listing shape (label selector).
    label_list_us = _time_calls(
        lambda: api.list(
            WORKLOAD_API_VERSION, WORKLOAD_KIND, namespace="default",
            label_selector={LABEL_CRON_NAME: "bench-0"},
        ),
        list_repeat,
    )

    # Count workload creations through a watch subscriber: identical cost
    # on every tree, and avoids polling list() during the timed sweep.
    import threading

    created = threading.Semaphore(0)
    created_n = [0]

    def _count(ev):
        if ev.type == "ADDED" and ev.object.get("kind") == WORKLOAD_KIND:
            created_n[0] += 1
            created.release()

    api.add_watcher(_count)

    mgr = Manager(api, max_concurrent_reconciles=10)
    rec = CronReconciler(api, metrics=mgr.metrics)
    mgr.add_controller(
        "cron", rec.reconcile, for_gvk=GVK_CRON,
        owns=default_scheme().workload_kinds(),
    )
    # Every standard spec fires within the next 60 min; the @every specs
    # have exactly one due tick after 61 min.
    clock.advance(timedelta(minutes=61))

    t0 = time.perf_counter()
    mgr.start()
    deadline = t0 + sweep_timeout_s
    done = 0
    while done < n_crons and time.perf_counter() < deadline:
        if created.acquire(timeout=min(1.0, deadline - time.perf_counter())):
            done += 1
    fire_s = time.perf_counter() - t0
    timed_out = done < n_crons
    successes = mgr.metrics.get(SUCCESS_SERIES)
    errors = mgr.metrics.get(ERROR_SERIES)

    # The headline: a full list+reconcile pass over every Cron with no
    # tick due — each reconcile lists its child workloads, recomputes
    # the schedule and syncs status against the populated store.
    t0 = time.perf_counter()
    for i in range(n_crons):
        rec.reconcile("default", f"bench-{i}")
    list_reconcile_s = time.perf_counter() - t0

    hist = mgr.metrics.histogram(RECONCILE_HIST)
    mgr.stop()
    api.close()

    return {
        "n_crons": n_crons,
        "populate_objects_per_s": round(n_crons / populate_s, 1),
        "cron_list_us": round(cron_list_us, 1),
        "workload_label_list_us": round(label_list_us, 1),
        "fire_sweep_s": round(fire_s, 3),
        "fire_sweep_timed_out": timed_out,
        "fire_sweep_workloads_created": done,
        "fire_sweep_crons_per_s": (
            round(done / fire_s, 1) if fire_s else 0.0
        ),
        "fire_sweep_reconciles_per_s": (
            round(successes / fire_s, 1) if fire_s else 0.0
        ),
        "reconcile_errors": errors,
        "reconcile_p50_s": _hist_percentile(hist, 0.50),
        "reconcile_p99_s": _hist_percentile(hist, 0.99),
        "list_reconcile_sweep_per_s": round(
            n_crons / list_reconcile_s, 1),
    }


def _git_ref(tree: str) -> str:
    try:
        return subprocess.run(
            ["git", "-C", tree, "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, check=True,
        ).stdout.strip()
    except Exception:
        return "unknown"


def run_suite(sizes, sweep_timeout_s: float) -> dict:
    return {
        "schema": "controlplane-bench/v1",
        "git_ref": _git_ref(_TREE),
        "results": [run_one(n, sweep_timeout_s) for n in sizes],
    }


def _run_baseline(ref: str, sizes, timeout_s: float) -> dict:
    """Run this same script against a detached worktree of ``ref``."""
    tree = tempfile.mkdtemp(prefix="cpbench-baseline-")
    subprocess.run(
        ["git", "-C", REPO_ROOT, "worktree", "add", "--detach", tree, ref],
        check=True, capture_output=True, text=True,
    )
    try:
        env = dict(os.environ, CPBENCH_TREE=tree, JAX_PLATFORMS="cpu")
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--sizes", ",".join(str(s) for s in sizes),
             "--sweep-timeout", str(timeout_s), "--stdout"],
            env=env, capture_output=True, text=True,
            timeout=timeout_s * (len(sizes) + 1) + 600,
        )
        if out.returncode != 0:
            raise RuntimeError(
                f"baseline run failed rc={out.returncode}: "
                f"{out.stderr[-800:]}"
            )
        return json.loads(out.stdout.strip().splitlines()[-1])
    finally:
        subprocess.run(
            ["git", "-C", REPO_ROOT, "worktree", "remove", "--force", tree],
            capture_output=True,
        )


def _speedups(before: dict, after: dict) -> list:
    out = []
    by_n = {r["n_crons"]: r for r in before["results"]}
    for a in after["results"]:
        b = by_n.get(a["n_crons"])
        if not b:
            continue

        def ratio(key, invert=False):
            x, y = b.get(key), a.get(key)
            if not x or not y:
                return None
            return round(x / y, 2) if invert else round(y / x, 2)

        out.append({
            "n_crons": a["n_crons"],
            "list_reconcile_sweep_per_s": ratio(
                "list_reconcile_sweep_per_s"),
            "fire_sweep_crons_per_s": ratio("fire_sweep_crons_per_s"),
            "cron_list_us": ratio("cron_list_us", invert=True),
            "workload_label_list_us": ratio(
                "workload_label_list_us", invert=True),
            "populate_objects_per_s": ratio("populate_objects_per_s"),
        })
    return out


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--sizes", default="1000,5000",
                   help="comma-separated Cron counts")
    p.add_argument("--out", default=os.path.join(
        REPO_ROOT, "BENCH_CONTROLPLANE.json"))
    p.add_argument("--baseline-ref", default=None,
                   help="git ref to measure as the 'before' tree")
    p.add_argument("--sweep-timeout", type=float, default=900.0)
    p.add_argument("--stdout", action="store_true",
                   help="print the artifact JSON to stdout only")
    args = p.parse_args()
    sizes = [int(s) for s in args.sizes.split(",") if s]

    after = run_suite(sizes, args.sweep_timeout)
    artifact = after
    if args.baseline_ref:
        before = _run_baseline(args.baseline_ref, sizes, args.sweep_timeout)
        artifact = {
            "schema": "controlplane-bench-compare/v1",
            "before": before,
            "after": after,
            "speedup": _speedups(before, after),
        }

    text = json.dumps(artifact, indent=2, sort_keys=True)
    if args.stdout:
        print(json.dumps(artifact))
    else:
        with open(args.out, "w") as f:
            f.write(text + "\n")
        print(text)
        print(f"\nwrote {args.out}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
