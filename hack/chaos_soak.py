"""Invariant-checking chaos soak (``make chaos-soak`` → ``CHAOS.json``).

Drives the REAL operator stack — ``APIServer`` + ``Manager`` worker pool +
leader election + ``CronReconciler`` on a ``FakeClock`` — through a seeded
fault storm injected by :mod:`cron_operator_tpu.runtime.faults`, then
asserts five end-state invariants:

- **I1 forbid_no_concurrent** — at no point in the run (observed on the
  raw store's every-event watch stream) does a ``Forbid`` Cron have more
  than one non-terminal workload.
- **I2 history_bounded** — every Cron ends with
  ``len(status.history) <= historyLimit``.
- **I3 tick_exactly_once** — ``cron_ticks_fired_total`` equals the number
  of workload ADDED events (every fired tick yields exactly one
  workload), and no workload name is ever created twice.
- **I4 converges_zero_writes** — once faults stop and the system
  quiesces, a direct synchronous reconcile sweep over every Cron
  performs ZERO store writes (resourceVersion bracketing).
- **I5 matches_fault_free_replay** — the semantic end state (per-cron
  fired-tick names, workload names + terminal phases, history entries,
  active sets) is identical to a replay of the same seed with all
  API/watch/leader faults disabled.

Determinism model: every fault decision and every simulated workload
outcome is a pure function of ``(seed, injection point)`` (see
``runtime/faults.seeded_fraction``), the clock is fake and advances in
fixed rounds, and the harness quiesces the manager between rounds — so
one seed defines one fault trace (``fault_trace_hash``) and one
convergent end state.  Workload outcomes and slice-preemption storms are
*environment*, not infrastructure: the fault-free replay applies them
identically, and only conflicts/transients/latency/watch-breaks/leader
revocations differ between the two runs.

``--unhardened`` reverts the process to the pre-hardening behavior
(single-attempt writes, no resync on watch error) to demonstrate that
the invariants genuinely depend on the hardening — expect I5 (and
possibly others) to fail there.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
from dataclasses import asdict
from datetime import timedelta

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

CRON_API_VERSION = "apps.kubedl.io/v1alpha1"
WORKLOAD_API_VERSION = "kubeflow.org/v1"
WORKLOAD_KIND = "JAXJob"
LABEL_CRON_NAME = "kubedl.io/cron-name"
POLICIES = ("Forbid", "Allow", "Replace")
HISTORY_LIMIT = 2
NAMESPACE = "default"


def _cron(i: int) -> dict:
    return {
        "apiVersion": CRON_API_VERSION,
        "kind": "Cron",
        "metadata": {"name": f"chaos-{i}", "namespace": NAMESPACE},
        "spec": {
            "schedule": "*/1 * * * *",
            "concurrencyPolicy": POLICIES[i % len(POLICIES)],
            "historyLimit": HISTORY_LIMIT,
            "template": {"workload": {
                "apiVersion": WORKLOAD_API_VERSION,
                "kind": WORKLOAD_KIND,
                "metadata": {},
                "spec": {"replicaSpecs": {"Worker": {"replicas": 1}}},
            }},
        },
    }


def _is_terminal(obj: dict) -> str:
    """Terminal condition type ('' while running) per the JobStatus
    last-condition convention used across the operator."""
    conds = (obj.get("status") or {}).get("conditions") or []
    if conds:
        last = conds[-1].get("type", "")
        if last in ("Succeeded", "Failed"):
            return last
    return ""


class WatchLog:
    """Every-event subscriber on the RAW store (immune to injected watch
    breaks): tracks workload creations per Cron and the live concurrency
    level of Forbid Crons — the I1/I3 evidence stream."""

    def __init__(self, forbid_crons) -> None:
        self._forbid = set(forbid_crons)
        self._lock = threading.Lock()
        self.created: dict = {}       # cron -> [workload names, ADDED order]
        self.created_count = 0
        self._active: dict = {}       # workload name -> cron
        self._level: dict = {}        # cron -> current non-terminal count
        self.violations: list = []    # I1 breaches, as readable strings

    def __call__(self, ev) -> None:
        obj = ev.object
        if obj.get("kind") != WORKLOAD_KIND:
            return
        meta = obj.get("metadata") or {}
        cron = (meta.get("labels") or {}).get(LABEL_CRON_NAME)
        if not cron:
            return
        name = meta.get("name", "")
        terminal = bool(_is_terminal(obj))
        with self._lock:
            if ev.type == "ADDED":
                self.created.setdefault(cron, []).append(name)
                self.created_count += 1
                if not terminal:
                    self._mark_active(cron, name)
            elif ev.type == "MODIFIED":
                if terminal:
                    self._mark_inactive(name)
                else:
                    self._mark_active(cron, name)
            elif ev.type == "DELETED":
                self._mark_inactive(name)

    def _mark_active(self, cron: str, name: str) -> None:
        if name in self._active:
            return
        self._active[name] = cron
        level = self._level.get(cron, 0) + 1
        self._level[cron] = level
        if cron in self._forbid and level > 1:
            self.violations.append(
                f"{cron}: {level} concurrent workloads (latest {name})"
            )

    def _mark_inactive(self, name: str) -> None:
        cron = self._active.pop(name, None)
        if cron is not None:
            self._level[cron] = self._level.get(cron, 1) - 1


def _queues_idle(mgr, horizon_s: float = 2.0) -> bool:
    for c in mgr._controllers:
        queued, processing, next_delay = c.queue.stats()
        if queued or processing:
            return False
        if next_delay is not None and next_delay < horizon_s:
            # A rate-limited requeue is about to fire — not idle yet.
            # (RequeueAfter schedule timers sit a fake-minute out in real
            # seconds and are correctly treated as idle.)
            return False
    return True


def _quiesce(mgr, store, timeout_s: float) -> bool:
    """Drain to a fixed point: watch events delivered, queues empty,
    nothing processing, no imminent rate-limited requeue, and (when
    electing) leadership held."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if mgr.leader_elect and not mgr._is_leader.is_set():
            time.sleep(0.02)
            continue
        store.flush(2.0)
        if _queues_idle(mgr):
            store.flush(1.0)
            if _queues_idle(mgr):
                return True
        time.sleep(0.005)
    return False


def run_soak(
    seed: int,
    n_crons: int,
    rounds: int,
    workers: int = 4,
    chaotic: bool = True,
    unhardened: bool = False,
    quiesce_timeout_s: float = 30.0,
) -> dict:
    """One soak run. ``chaotic=False`` is the fault-free replay: same
    seed, same rounds, same workload outcomes and preemption storms, but
    no API/watch/leader faults."""
    from cron_operator_tpu.api.scheme import GVK_CRON, default_scheme
    from cron_operator_tpu.api.v1alpha1 import rfc3339
    from cron_operator_tpu.controller.cron_controller import CronReconciler
    from cron_operator_tpu.runtime import retry as retry_mod
    from cron_operator_tpu.runtime.faults import (
        FaultInjector,
        FaultPlan,
        seeded_fraction,
    )
    from cron_operator_tpu.runtime.kube import (
        APIServer,
        ConflictError,
        NotFoundError,
        ServerTimeoutError,
    )
    from cron_operator_tpu.runtime.manager import Manager
    from cron_operator_tpu.runtime.retry import with_conflict_retry
    from cron_operator_tpu.utils.clock import FakeClock

    storm_plan = FaultPlan.default_chaos(seed)
    plan = storm_plan if chaotic else FaultPlan.quiet(seed)
    schedule = storm_plan.schedule(rounds)
    by_round: dict = {}
    for ev in schedule:
        by_round.setdefault(ev["round"], set()).add(ev["fault"])

    clock = FakeClock()
    store = APIServer(clock=clock)
    api = FaultInjector(store, plan)

    forbid = {
        f"chaos-{i}" for i in range(n_crons)
        if POLICIES[i % len(POLICIES)] == "Forbid"
    }
    watchlog = WatchLog(forbid)
    store.add_watcher(watchlog)

    for i in range(n_crons):
        store.create(_cron(i))

    prev_attempts = retry_mod.DEFAULT_ATTEMPTS
    retry_mod.DEFAULT_ATTEMPTS = 1 if unhardened else 5
    mgr = Manager(
        api,
        max_concurrent_reconciles=workers,
        leader_elect=True,
        identity="chaos-soak",
        lease_duration_s=1.0,
    )
    mgr.resync_on_watch_error = not unhardened
    rec = CronReconciler(api, metrics=mgr.metrics)
    mgr.add_controller(
        "cron", rec.reconcile, for_gvk=GVK_CRON,
        owns=default_scheme().workload_kinds(),
    )

    first_seen: dict = {}   # workload name -> round index first observed
    preempted: set = set()
    lost_flips = 0
    quiesce_timeouts = 0
    readyz_degraded_seen = False
    leadership_lost_seen = False

    def _dur(name: str) -> int:
        # Rounds a workload runs before its terminal flip (0..2) — long
        # enough that Forbid Crons regularly carry an active workload
        # across a tick (exercising skips).
        return int(seeded_fraction(seed, "dur", name) * 3)

    def _terminal_for(name: str) -> str:
        return (
            "Succeeded"
            if seeded_fraction(seed, "term", name) < 0.8 else "Failed"
        )

    def _flip(name: str, cond_type: str, reason: str) -> None:
        """Harness-driven status flip through the (possibly faulty) API —
        the executor-status-write analog the conflict-retry helper
        hardens. In unhardened mode exhausted retries surface here and
        the flip is LOST, exactly like the pre-hardening executor."""
        nonlocal lost_flips

        def _apply() -> None:
            obj = api.try_get(WORKLOAD_API_VERSION, WORKLOAD_KIND,
                              NAMESPACE, name)
            if obj is None:
                return
            status = dict(obj.get("status") or {})
            conds = list(status.get("conditions") or [])
            now = rfc3339(clock.now())
            conds.append({
                "type": cond_type, "status": "True", "reason": reason,
                "lastUpdateTime": now, "lastTransitionTime": now,
            })
            status["conditions"] = conds
            status["completionTime"] = now
            api.patch_status(WORKLOAD_API_VERSION, WORKLOAD_KIND,
                             NAMESPACE, name, status)

        try:
            with_conflict_retry(_apply)
        except (ConflictError, ServerTimeoutError):
            lost_flips += 1
        except NotFoundError:
            pass

    def _environment_step(r: int) -> None:
        """Deterministic workload environment for round ``r``: the
        scheduled preemption storm plus age-based terminal flips. Applied
        identically in the chaotic run and the replay — only the API
        faults underneath the flips differ."""
        workloads = store.list(
            WORKLOAD_API_VERSION, WORKLOAD_KIND, namespace=NAMESPACE
        )
        running = []
        for w in workloads:
            name = (w.get("metadata") or {}).get("name", "")
            first_seen.setdefault(name, r)
            if not _is_terminal(w):
                running.append(name)
        storm = "preempt_storm" in by_round.get(r, ())
        for name in sorted(running):
            age = r - first_seen[name]
            if (
                storm
                and age < _dur(name)
                and seeded_fraction(seed, "preempt", r, name)
                < storm_plan.preempt_frac
            ):
                preempted.add(name)
                _flip(name, "Failed", "TPUSlicePreempted")
            elif name not in preempted and age >= _dur(name):
                flip_to = _terminal_for(name)
                _flip(name, flip_to,
                      "JobSucceeded" if flip_to == "Succeeded"
                      else "JobFailed")

    t0 = time.monotonic()
    try:
        mgr.start()
        if not _quiesce(mgr, store, quiesce_timeout_s):
            quiesce_timeouts += 1

        for r in range(rounds):
            faults_now = by_round.get(r, set()) if chaotic else set()
            clock.advance(timedelta(seconds=60))
            if "watch_break" in faults_now:
                api.break_watches()
            if "leader_revoke" in faults_now:
                api.revoke_leader()
                deadline = time.monotonic() + 3.0
                while time.monotonic() < deadline:
                    if not mgr._is_leader.is_set():
                        leadership_lost_seen = True
                        break
                    time.sleep(0.02)
                api.expire_leader_lease()
            # Round tick: level-triggered enqueue-all (a real operator
            # gets this from its RequeueAfter timers; the soak drives it
            # explicitly so rounds stay aligned with the fake clock).
            mgr.resync()
            if "watch_break" in faults_now and not mgr.readyz():
                readyz_degraded_seen = True
            if not _quiesce(mgr, store, quiesce_timeout_s):
                quiesce_timeouts += 1
            _environment_step(r)
            if "watch_break" in faults_now:
                # Stream comes back: BOOKMARK frame → hardened managers
                # resync (re-list + enqueue all); unhardened ones ignore
                # it and stay degraded.
                api.repair_watches()
            if not _quiesce(mgr, store, quiesce_timeout_s):
                quiesce_timeouts += 1

        # ---- faults stop: convergence phase ------------------------------
        api.disarm()
        api.repair_watches()
        mgr.resync()
        if not _quiesce(mgr, store, quiesce_timeout_s):
            quiesce_timeouts += 1

        surface = _surface(store, watchlog)
        fired_metric = mgr.metrics.get(
            'controller_runtime_reconcile_total{controller="cron",'
            'result="success"}'
        )
        metrics = {
            "reconciles_ok": fired_metric,
            "reconcile_errors": mgr.metrics.get(
                'controller_runtime_reconcile_errors_total'
                '{controller="cron"}'
            ),
            "ticks_fired": mgr.metrics.get("cron_ticks_fired_total"),
            "ticks_skipped": mgr.metrics.get(
                'cron_ticks_skipped_total{policy="Forbid"}'
            ),
            "missed_runs": mgr.metrics.get("cron_missed_runs_total"),
            "watch_resyncs": mgr.metrics.get("watch_resyncs_total"),
            "submit_retries": mgr.metrics.get("cron_submit_retries_total"),
        }
    finally:
        mgr.stop()
        retry_mod.DEFAULT_ATTEMPTS = prev_attempts

    # ---- I4: converged state needs zero further writes -------------------
    # Manager stopped, faults disarmed: a direct sweep over every Cron
    # must not commit anything (rv bracketing counts store writes).
    rv_before = int(getattr(store, "_rv"))
    for i in range(n_crons):
        rec.reconcile(NAMESPACE, f"chaos-{i}")
    final_sweep_writes = int(getattr(store, "_rv")) - rv_before
    store.close()

    duplicate_names = sorted(
        name
        for names in watchlog.created.values()
        for name in {n for n in names if names.count(n) > 1}
    )

    return {
        "seed": seed,
        "chaotic": chaotic,
        "unhardened": unhardened,
        "elapsed_s": round(time.monotonic() - t0, 2),
        "plan": asdict(plan),
        "fault_schedule": schedule,
        "fault_trace_hash": storm_plan.trace_hash(rounds),
        "faults_injected": api.fault_counts(),
        "dropped_watch_events": api.dropped_events(),
        "lost_flips": lost_flips,
        "quiesce_timeouts": quiesce_timeouts,
        "readyz_degraded_seen": readyz_degraded_seen,
        "leadership_lost_seen": leadership_lost_seen,
        "metrics": metrics,
        "surface": surface,
        "created_count": watchlog.created_count,
        "duplicate_names": duplicate_names,
        "forbid_violations": list(watchlog.violations),
        "final_sweep_writes": final_sweep_writes,
    }


def _surface(store, watchlog) -> dict:
    """Semantic end state, shorn of run-varying identifiers (uids,
    resourceVersions, timestamps): the I5 comparison surface."""
    out: dict = {}
    for cron in store.list(CRON_API_VERSION, "Cron", namespace=NAMESPACE):
        name = (cron.get("metadata") or {}).get("name", "")
        st = cron.get("status") or {}
        out[name] = {
            "active": sorted(
                (ref.get("name", "") for ref in st.get("active") or []),
            ),
            "history": sorted(
                (
                    (h.get("object") or {}).get("name", ""),
                    h.get("status", ""),
                )
                for h in st.get("history") or []
            ),
            "fired": sorted(watchlog.created.get(name, [])),
        }
    workloads: dict = {}
    for w in store.list(
        WORKLOAD_API_VERSION, WORKLOAD_KIND, namespace=NAMESPACE
    ):
        meta = w.get("metadata") or {}
        cron = (meta.get("labels") or {}).get(LABEL_CRON_NAME, "?")
        workloads.setdefault(cron, []).append(
            (meta.get("name", ""), _is_terminal(w) or "Running")
        )
    for cron, entries in workloads.items():
        out.setdefault(cron, {})["workloads"] = sorted(entries)
    return out


def check_invariants(chaotic: dict, replay: dict, history_limit: int) -> dict:
    """The five invariants, each with a human-readable detail string."""
    inv: dict = {}

    inv["I1_forbid_no_concurrent"] = {
        "ok": not chaotic["forbid_violations"],
        "detail": chaotic["forbid_violations"][:5] or "never exceeded 1",
    }

    over = [
        (name, len(state.get("history", [])))
        for name, state in chaotic["surface"].items()
        if len(state.get("history", [])) > history_limit
    ]
    inv["I2_history_bounded"] = {
        "ok": not over,
        "detail": over[:5] or f"all <= historyLimit={history_limit}",
    }

    fired = chaotic["metrics"]["ticks_fired"]
    created = chaotic["created_count"]
    dups = chaotic["duplicate_names"]
    inv["I3_tick_exactly_once"] = {
        "ok": fired == created and not dups,
        "detail": (
            f"cron_ticks_fired_total={fired} workload_creates={created} "
            f"duplicate_names={dups[:5]}"
        ),
    }

    inv["I4_converges_zero_writes"] = {
        "ok": chaotic["final_sweep_writes"] == 0,
        "detail": (
            f"{chaotic['final_sweep_writes']} store writes in the "
            "post-convergence sweep"
        ),
    }

    diffs = []
    crons = sorted(set(chaotic["surface"]) | set(replay["surface"]))
    for name in crons:
        a = chaotic["surface"].get(name)
        b = replay["surface"].get(name)
        if a != b:
            diffs.append({"cron": name, "chaotic": a, "replay": b})
    inv["I5_matches_fault_free_replay"] = {
        "ok": not diffs,
        "detail": diffs[:3] or "chaotic end state == replay end state",
    }
    return inv


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--crons", type=int, default=200)
    ap.add_argument("--rounds", type=int, default=6)
    ap.add_argument("--workers", type=int, default=4)
    ap.add_argument("--quiesce-timeout", type=float, default=30.0)
    ap.add_argument("--unhardened", action="store_true", default=False,
                    help="pre-hardening mode: single-attempt writes, no "
                         "watch resync — demonstrates the invariant "
                         "violations the hardening prevents")
    ap.add_argument("--expect-violation", action="store_true", default=False,
                    help="exit 0 iff at least one invariant is violated "
                         "(for asserting the --unhardened demonstration)")
    ap.add_argument("--out", default=os.path.join(REPO_ROOT, "CHAOS.json"))
    args = ap.parse_args(argv)

    from cron_operator_tpu.runtime.faults import FaultPlan

    # Determinism of the fault trace: the schedule expansion is a pure
    # function of the plan — expand twice from fresh objects and compare.
    plan_a = FaultPlan.default_chaos(args.seed)
    plan_b = FaultPlan.default_chaos(args.seed)
    deterministic = (
        plan_a.schedule(args.rounds) == plan_b.schedule(args.rounds)
        and plan_a.trace_hash(args.rounds) == plan_b.trace_hash(args.rounds)
    )

    print(
        f"chaos soak: seed={args.seed} crons={args.crons} "
        f"rounds={args.rounds} unhardened={args.unhardened}",
        flush=True,
    )
    chaotic = run_soak(
        args.seed, args.crons, args.rounds, workers=args.workers,
        chaotic=True, unhardened=args.unhardened,
        quiesce_timeout_s=args.quiesce_timeout,
    )
    print(
        f"  chaotic run: {chaotic['elapsed_s']}s "
        f"faults={chaotic['faults_injected']} "
        f"dropped_events={chaotic['dropped_watch_events']} "
        f"lost_flips={chaotic['lost_flips']}",
        flush=True,
    )
    replay = run_soak(
        args.seed, args.crons, args.rounds, workers=args.workers,
        chaotic=False, unhardened=False,
        quiesce_timeout_s=args.quiesce_timeout,
    )
    print(f"  replay run: {replay['elapsed_s']}s", flush=True)

    invariants = check_invariants(chaotic, replay, HISTORY_LIMIT)
    ok = all(v["ok"] for v in invariants.values()) and deterministic

    report = {
        "seed": args.seed,
        "n_crons": args.crons,
        "rounds": args.rounds,
        "workers": args.workers,
        "unhardened": args.unhardened,
        "deterministic_schedule": deterministic,
        "fault_trace_hash": chaotic["fault_trace_hash"],
        "fault_schedule": chaotic["fault_schedule"],
        "faults_injected": chaotic["faults_injected"],
        "dropped_watch_events": chaotic["dropped_watch_events"],
        "lost_flips": chaotic["lost_flips"],
        "quiesce_timeouts": chaotic["quiesce_timeouts"],
        "readyz_degraded_seen": chaotic["readyz_degraded_seen"],
        "leadership_lost_seen": chaotic["leadership_lost_seen"],
        "metrics": chaotic["metrics"],
        "elapsed_s": {
            "chaotic": chaotic["elapsed_s"],
            "replay": replay["elapsed_s"],
        },
        "invariants": invariants,
        "ok": ok,
    }
    # The full surfaces are bulky at N>=200; persist only on divergence.
    if not invariants["I5_matches_fault_free_replay"]["ok"]:
        report["surface_chaotic"] = chaotic["surface"]
        report["surface_replay"] = replay["surface"]

    with open(args.out, "w") as f:
        json.dump(report, f, indent=2, default=str)
        f.write("\n")

    for name, v in invariants.items():
        mark = "PASS" if v["ok"] else "FAIL"
        print(f"  [{mark}] {name}: {v['detail']}")
    print(f"wrote {args.out} (ok={ok})")

    if args.expect_violation:
        violated = not all(v["ok"] for v in invariants.values())
        if violated:
            print("expected violation observed — unhardened mode "
                  "demonstrably breaks an invariant")
            return 0
        print("ERROR: expected an invariant violation but all passed")
        return 1
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
