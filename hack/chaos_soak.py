"""Invariant-checking chaos soak (``make chaos-soak`` → ``CHAOS.json``).

Drives the REAL operator stack — ``APIServer`` + ``Manager`` worker pool +
leader election + ``CronReconciler`` on a ``FakeClock`` — through a seeded
fault storm injected by :mod:`cron_operator_tpu.runtime.faults`, including
**crash-restart rounds**: at a PRF-chosen WAL append the control plane is
killed at a PRF-chosen kill-point (before/after append, torn tail,
mid-snapshot), then restarted from its ``--data-dir`` (WAL + snapshot
recovery, :mod:`cron_operator_tpu.runtime.persistence`).  Asserts these
end-state invariants:

- **I1 forbid_no_concurrent** — at no point in the run (observed on the
  raw store's every-event watch stream) does a ``Forbid`` Cron have more
  than one non-terminal workload.
- **I2 history_bounded** — every Cron ends with
  ``len(status.history) <= historyLimit``.
- **I3 tick_exactly_once** — workload ADDED observations equal fired
  ticks plus recovery orphans (creates whose WAL record survived a crash
  the submitting process never acknowledged), and no workload name is
  ever created twice (dup accounting in I7).
- **I4 converges_zero_writes** — once faults stop and the system
  quiesces, a direct synchronous reconcile sweep over every Cron
  performs ZERO store writes (resourceVersion bracketing).
- **I5 matches_fault_free_replay** — the semantic end state (per-cron
  fired-tick names, workload names + terminal phases, history entries,
  active sets) is identical to a replay of the same seed with all
  API/watch/leader faults AND crashes disabled.
- **I6 recovery_equals_replay** — after every restart, the recovered
  store state is byte-identical to an independent snapshot+WAL replay of
  the same data dir (and recovering twice yields the same bytes).
- **I7 restart_tick_integrity** — no tick fires twice across a restart
  (a workload name that survived the crash is never re-created), and no
  in-window tick is permanently lost (every name ever created is, at the
  end, either live in the store or was legitimately deleted — crash-lost
  creates must be re-fired by recovery catch-up).
- **I8 elastic_resume** (``--preempt-storm``) — an extra leg where REAL
  CPU-mesh training jobs (``make chaos-soak-preempt``) are hit by
  preemption storms and resumed by the controller on the surviving
  devices: after the storm every logical run finishes at exactly its
  step target, each resume restarts at most one checkpoint interval
  behind the preempted attempt's observed progress, resume chains are
  step-monotonic, and each run appears exactly once in history with the
  right ``resumes`` count.  ``--no-elastic`` is the counter-proof: the
  same storms against restart-on-preemption jobs (no checkpoint) must
  violate I8 — restarted runs start over at step 0.
- **I9 flight_recorder** (crash mode) — the audit journal
  (:mod:`cron_operator_tpu.telemetry.audit`) is cross-checkable against
  the WAL, record for record: per generation (single store) / per shard
  (sharded), the audited ``wal_pos`` stream is exactly contiguous
  ``1..N`` with ``N == records_appended``, tolerating at most ONE
  kill-stranded tail record (appended but never committed).  The
  sharded soak adds the lag-telemetry leg — follower replication lag is
  observed >0 before round-boundary flushes and drains to exactly zero
  after each — and ``--preempt-storm`` adds the goodput leg: productive
  steps over total steps trained across every attempt chain must clear
  ``GOODPUT_FLOOR``.
- **I12 storage_integrity** (``--disk``) — a dedicated disk-fault leg
  cycles every :data:`runtime.faults.DISK_FAULT_KINDS` kind against the
  checksummed store: no corrupted (or never-acknowledged) record is
  ever applied — recovery always lands on a verifiable prefix of the
  acknowledged history (I12a); every damage round is *detected* — a
  non-clean integrity verdict, quarantine forensics under
  ``wal.quarantine/``, and the background scrubber finding a latent
  bit-flip in cold sealed-segment bytes (I12b); and injected
  EIO/ENOSPC fail closed — the refused write exists NOWHERE, the shard
  degrades read-only with a metrics-visible gauge, and a probe append
  heals it (I12c).  ``--disk --no-checksums`` is the counter-proof: the
  same seeded bit-flip is applied SILENTLY to the legacy format,
  violating I12a (use with ``--expect-violation``).

Determinism model: every fault decision, kill-point, and simulated
workload outcome is a pure function of ``(seed, injection point)`` (see
``runtime/faults.seeded_fraction``), the clock is fake and advances in
fixed rounds, and the harness quiesces the manager between rounds — so
one seed defines one fault trace (``fault_trace_hash``) and one
convergent end state.  Crashes take **zero fake time**: the restarted
process resumes in the same fake minute, so crash runs stay
I5-comparable to the no-crash replay (downtime catch-up and
``startingDeadlineSeconds`` capping are covered by unit tests in
``tests/test_persistence.py``).

``--unhardened`` reverts the process to the pre-hardening behavior
(single-attempt writes, no resync on watch error) to demonstrate that
the invariants genuinely depend on the hardening — expect I5 (and
possibly others) to fail there.  ``--no-durability`` keeps the kill
schedule but restarts every crash from an EMPTY data dir (the behavior
of an unset ``--data-dir``): prior workloads and ``lastScheduleTime``
vanish, so I7 demonstrably fails — the violation the persistence layer
exists to prevent.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import shutil
import sys
import tempfile
import threading
import time
from dataclasses import asdict, replace
from datetime import timedelta

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

CRON_API_VERSION = "apps.kubedl.io/v1alpha1"
WORKLOAD_API_VERSION = "kubeflow.org/v1"
WORKLOAD_KIND = "JAXJob"
LABEL_CRON_NAME = "kubedl.io/cron-name"
POLICIES = ("Forbid", "Allow", "Replace")
HISTORY_LIMIT = 2
NAMESPACE = "default"
#: Probability a round ends in a kill+restart (crash mode). The schedule
#: forces at least one kill round regardless (see FaultPlan.schedule).
KILL_PROB = 0.35
#: Upper bound for the PRF-chosen kill append index within a kill round
#: (rounds at soak scale append hundreds of records, so the kill lands
#: early in the round's write stream).
KILL_MAX_APPENDS = 40


def _cron(i: int) -> dict:
    return {
        "apiVersion": CRON_API_VERSION,
        "kind": "Cron",
        "metadata": {"name": f"chaos-{i}", "namespace": NAMESPACE},
        "spec": {
            "schedule": "*/1 * * * *",
            "concurrencyPolicy": POLICIES[i % len(POLICIES)],
            "historyLimit": HISTORY_LIMIT,
            "template": {"workload": {
                "apiVersion": WORKLOAD_API_VERSION,
                "kind": WORKLOAD_KIND,
                "metadata": {},
                "spec": {"replicaSpecs": {"Worker": {"replicas": 1}}},
            }},
        },
    }


def _is_terminal(obj: dict) -> str:
    """Terminal condition type ('' while running) per the JobStatus
    last-condition convention used across the operator."""
    conds = (obj.get("status") or {}).get("conditions") or []
    if conds:
        last = conds[-1].get("type", "")
        if last in ("Succeeded", "Failed"):
            return last
    return ""


class _CrashNoiseFilter(logging.Filter):
    """Drop the expected SimulatedCrash tracebacks a dead-persistence
    window produces (every worker write fails until the harness restarts
    the control plane) — real failures still log."""

    def filter(self, record: logging.LogRecord) -> bool:
        if record.exc_info and record.exc_info[1] is not None:
            from cron_operator_tpu.runtime.persistence import SimulatedCrash

            if isinstance(record.exc_info[1], SimulatedCrash):
                return False
        msg = record.getMessage()
        return "SimulatedCrash" not in msg and "kill-point" not in msg


class WatchLog:
    """Every-event subscriber on the RAW store (immune to injected watch
    breaks): tracks workload creations per Cron and the live concurrency
    level of Forbid Crons — the I1/I3 evidence stream.

    Crash-aware: ``begin_generation(recovered)`` re-bases the live
    tracking on a restarted store's recovered state — seeding **orphans**
    (durable-but-unacknowledged creates the pre-crash stream never saw),
    computing the **crash-lost** name set (created, never deleted, absent
    from recovery — the only names recovery catch-up may legitimately
    re-create), un-deleting **resurrections** (deletes whose WAL record
    the crash lost), and honoring **phantom deletes** (deletes whose WAL
    record is durable but whose DELETED event the crash swallowed — the
    after-append kill between persist and evict).  A re-ADDED name
    outside the crash-lost set fired the same tick twice — an I7
    violation."""

    def __init__(self, forbid_crons) -> None:
        self._forbid = set(forbid_crons)
        self._lock = threading.Lock()
        self.created: dict = {}       # cron -> [names, ADDED/seed order]
        self.created_count = 0
        self._active: dict = {}       # workload name -> cron
        self._level: dict = {}        # cron -> current non-terminal count
        self.violations: list = []    # I1 breaches, as readable strings
        self.ever_created: dict = {}  # name -> cron, every name ever seen
        self.deleted: set = set()     # names watched DELETED
        self.orphans: list = []       # recovered names never seen ADDED
        self.refires: list = []       # crash-lost names re-created
        self.resurrections: list = [] # deleted names recovery brought back
        self.phantom_deletes: list = []  # durable deletes the stream missed
        self.dup_violations: list = []  # I7a: live name re-created
        self.generation = 0
        self._crash_lost: set = set()

    def __call__(self, ev) -> None:
        obj = ev.object
        if obj.get("kind") != WORKLOAD_KIND:
            return
        meta = obj.get("metadata") or {}
        cron = (meta.get("labels") or {}).get(LABEL_CRON_NAME)
        if not cron:
            return
        name = meta.get("name", "")
        terminal = bool(_is_terminal(obj))
        with self._lock:
            if ev.type == "ADDED":
                if name in self.ever_created:
                    if name in self._crash_lost:
                        # Recovery catch-up re-firing a tick the crash
                        # swallowed — the exactly-once repair, not a dup.
                        self.refires.append(name)
                        self._crash_lost.discard(name)
                    else:
                        self.dup_violations.append(
                            f"gen{self.generation}: {name} re-created "
                            "while its first incarnation survived"
                        )
                self.ever_created[name] = cron
                self.created.setdefault(cron, []).append(name)
                self.created_count += 1
                self.deleted.discard(name)
                if not terminal:
                    self._mark_active(cron, name)
            elif ev.type == "MODIFIED":
                if terminal:
                    self._mark_inactive(name, watched_delete=False)
                else:
                    self._mark_active(cron, name)
            elif ev.type == "DELETED":
                self._mark_inactive(name, watched_delete=True)
                self.deleted.add(name)

    def begin_generation(
        self, recovered_workloads, wal_deleted_names=()
    ) -> None:
        """Re-base on a restarted store. ``recovered_workloads`` is the
        post-recovery workload list (empty when durability is off);
        ``wal_deleted_names`` are workload names whose final WAL
        disposition is a ``del`` record."""
        with self._lock:
            self.generation += 1
            self._active = {}
            self._level = {}
            recovered_names = set()
            for obj in recovered_workloads:
                meta = obj.get("metadata") or {}
                cron = (meta.get("labels") or {}).get(LABEL_CRON_NAME)
                if obj.get("kind") != WORKLOAD_KIND or not cron:
                    continue
                name = meta.get("name", "")
                recovered_names.add(name)
                if name not in self.ever_created:
                    # Durable WAL record, crash before the in-memory
                    # commit (after-append / pre-rotation kill): the ADDED
                    # never reached the stream, recovery resurrects it.
                    self.orphans.append(name)
                    self.ever_created[name] = cron
                    self.created.setdefault(cron, []).append(name)
                    self.created_count += 1
                if name in self.deleted:
                    # The delete's WAL record was in the crash-lost
                    # suffix; the object is legitimately back.
                    self.resurrections.append(name)
                    self.deleted.discard(name)
                if not _is_terminal(obj):
                    self._mark_active(cron, name)
            for name in wal_deleted_names:
                if name in self.ever_created and name not in self.deleted \
                        and name not in recovered_names:
                    # Phantom delete — the mirror image of an orphan: the
                    # kill hit between a delete's WAL append and its
                    # in-memory evict, so the delete is durable but its
                    # DELETED event never reached the stream. Honor the
                    # disk's verdict; otherwise the name would be
                    # misclassified crash-lost and, once its tick is
                    # superseded, falsely counted permanently lost.
                    self.phantom_deletes.append(name)
                    self.deleted.add(name)
            self._crash_lost = {
                n for n in self.ever_created
                if n not in self.deleted and n not in recovered_names
            }

    def _mark_active(self, cron: str, name: str) -> None:
        if name in self._active:
            return
        self._active[name] = cron
        level = self._level.get(cron, 0) + 1
        self._level[cron] = level
        if cron in self._forbid and level > 1:
            self.violations.append(
                f"{cron}: {level} concurrent workloads (latest {name})"
            )

    def _mark_inactive(self, name: str, watched_delete: bool) -> None:
        cron = self._active.pop(name, None)
        if cron is not None:
            self._level[cron] = self._level.get(cron, 1) - 1


def _queues_idle(mgr, horizon_s: float = 2.0) -> bool:
    for c in mgr._controllers:
        queued, processing, next_delay = c.queue.stats()
        if queued or processing:
            return False
        if next_delay is not None and next_delay < horizon_s:
            # A rate-limited requeue is about to fire — not idle yet.
            # (RequeueAfter schedule timers sit a fake-minute out in real
            # seconds and are correctly treated as idle.)
            return False
    return True


def _quiesce(mgr, store, timeout_s: float, pers=None) -> str:
    """Drain to a fixed point: watch events delivered, queues empty,
    nothing processing, no imminent rate-limited requeue, and (when
    electing) leadership held. Returns 'idle', 'timeout', or 'dead'
    (the persistence kill-point fired — stop draining, restart)."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pers is not None and pers.dead:
            return "dead"
        if mgr.leader_elect and not mgr._is_leader.is_set():
            time.sleep(0.02)
            continue
        store.flush(2.0)
        if _queues_idle(mgr):
            store.flush(1.0)
            if _queues_idle(mgr):
                return "idle"
        time.sleep(0.005)
    return "timeout"


def run_soak(
    seed: int,
    n_crons: int,
    rounds: int,
    workers: int = 4,
    chaotic: bool = True,
    unhardened: bool = False,
    quiesce_timeout_s: float = 30.0,
    crash: bool = False,
    durability: bool = True,
    data_dir: str | None = None,
) -> dict:
    """One soak run. ``chaotic=False`` is the fault-free replay: same
    seed, same rounds, same workload outcomes and preemption storms, but
    no API/watch/leader faults and no crashes. ``crash=True`` adds
    PRF-scheduled kill+restart rounds; ``durability=False`` makes every
    restart recover from an empty data dir (the I7 violation demo)."""
    from cron_operator_tpu.api.scheme import GVK_CRON, default_scheme
    from cron_operator_tpu.api.v1alpha1 import rfc3339
    from cron_operator_tpu.controller.cron_controller import CronReconciler
    from cron_operator_tpu.runtime import retry as retry_mod
    from cron_operator_tpu.runtime.faults import (
        FaultInjector,
        FaultPlan,
        KillSwitch,
        seeded_fraction,
    )
    from cron_operator_tpu.runtime.kube import (
        APIServer,
        AlreadyExistsError,
        ConflictError,
        NotFoundError,
        ServerTimeoutError,
    )
    from cron_operator_tpu.runtime.manager import Manager
    from cron_operator_tpu.runtime.persistence import (
        Persistence,
        SimulatedCrash,
    )
    from cron_operator_tpu.runtime.retry import with_conflict_retry
    from cron_operator_tpu.telemetry.audit import AuditJournal
    from cron_operator_tpu.utils.clock import FakeClock

    storm_plan = FaultPlan.default_chaos(seed)
    if crash:
        storm_plan = replace(storm_plan, kill_prob=KILL_PROB)
    plan = storm_plan if chaotic else FaultPlan.quiet(seed)
    schedule = storm_plan.schedule(rounds)
    by_round: dict = {}
    for ev in schedule:
        by_round.setdefault(ev["round"], set()).add(ev["fault"])

    own_data_dir = crash and data_dir is None
    if own_data_dir:
        data_dir = tempfile.mkdtemp(prefix="chaos-soak-")

    clock = FakeClock()
    start_epoch = int(clock.now().timestamp())
    store = APIServer(clock=clock)
    pers = None
    # Flight recorder (I9): one journal per PROCESS GENERATION — a
    # restart's fresh Persistence restarts the WAL position counter, so
    # the audit≡WAL continuity check is per generation too. The check
    # itself is taken at every kill (crash_tail=1: the kill can land
    # between the WAL append and the commit) and once at the clean end.
    journal = None
    audit_checks: list = []
    if crash and chaotic:
        # Durable mode recovers from this dir across kills; no-durability
        # mode still runs a persistence layer (the kill-points live in
        # its append path, and determinism needs the same kill trace) but
        # each restart recovers from a FRESH empty dir.
        # flush_interval_s=0: the soak controls every flush point itself
        # (round boundaries) so suffix loss is a pure function of the seed,
        # not of wall-clock flusher timing.
        pers = Persistence(os.path.join(data_dir, "gen-0"),
                           flush_interval_s=0)
        journal = AuditJournal()
        pers.attach_audit(journal)
        pers.start(store)
        store.attach_audit(journal)
    api = FaultInjector(store, plan)

    forbid = {
        f"chaos-{i}" for i in range(n_crons)
        if POLICIES[i % len(POLICIES)] == "Forbid"
    }
    watchlog = WatchLog(forbid)
    store.add_watcher(watchlog)

    for i in range(n_crons):
        store.create(_cron(i))

    prev_attempts = retry_mod.DEFAULT_ATTEMPTS
    retry_mod.DEFAULT_ATTEMPTS = 1 if unhardened else 5

    def _new_manager(recovering: bool):
        m = Manager(
            api,
            max_concurrent_reconciles=workers,
            leader_elect=True,
            identity="chaos-soak",
            lease_duration_s=1.0,
            recovering=recovering,
            audit=journal,
        )
        m.resync_on_watch_error = not unhardened
        r = CronReconciler(api, metrics=m.metrics, audit=journal)
        m.add_controller(
            "cron", r.reconcile, for_gvk=GVK_CRON,
            owns=default_scheme().workload_kinds(),
        )
        if pers is not None:
            pers.instrument(m.metrics)
        return m, r

    mgr, rec = _new_manager(recovering=False)

    preempted: set = set()
    lost_flips = 0
    quiesce_timeouts = 0
    readyz_degraded_seen = False
    leadership_lost_seen = False
    kills: list = []        # per-restart forensics (+ I6 evidence)
    metric_gens: list = []  # per-generation metric dumps (summed at end)
    fault_gens: list = []   # per-generation injector counters (ditto)
    noise_filter = _CrashNoiseFilter()
    if crash and chaotic:
        for h in logging.getLogger().handlers or [logging.lastResort]:
            h.addFilter(noise_filter)

    def _collect_metrics(m) -> dict:
        g = m.metrics.get
        return {
            "reconciles_ok": g(
                'controller_runtime_reconcile_total{controller="cron",'
                'result="success"}'
            ),
            "reconcile_errors": g(
                'controller_runtime_reconcile_errors_total'
                '{controller="cron"}'
            ),
            "ticks_fired": g("cron_ticks_fired_total"),
            "ticks_skipped": g(
                'cron_ticks_skipped_total{policy="Forbid"}'
            ),
            "ticks_skipped_deadline": g(
                'cron_ticks_skipped_total{policy="StartingDeadline"}'
            ),
            "missed_runs": g("cron_missed_runs_total"),
            "watch_resyncs": g("watch_resyncs_total"),
            "submit_retries": g("cron_submit_retries_total"),
        }

    def _birth_round(name: str) -> int:
        # Workload names embed their tick (the nextRun epoch), so a
        # workload's birth round is a pure function of its NAME — and
        # therefore identical across crash-restart generations and the
        # fault-free replay. Observation-order bookkeeping would drift:
        # a restart's catch-up can create a workload in a different
        # quiesce window than the replay does, shifting its perceived
        # age (and thus its terminal-flip round, and thus which later
        # ticks a Forbid Cron skips) by one.
        try:
            epoch = int(name.rsplit("-", 1)[1])
        except (IndexError, ValueError):
            return 0
        return max(0, (epoch - start_epoch) // 60 - 2)

    def _dur(name: str) -> int:
        # Rounds a workload runs before its terminal flip (0..2) — long
        # enough that Forbid Crons regularly carry an active workload
        # across a tick (exercising skips).
        return int(seeded_fraction(seed, "dur", name) * 3)

    def _terminal_for(name: str) -> str:
        return (
            "Succeeded"
            if seeded_fraction(seed, "term", name) < 0.8 else "Failed"
        )

    def _flip(name: str, cond_type: str, reason: str) -> None:
        """Harness-driven status flip through the (possibly faulty) API —
        the executor-status-write analog the conflict-retry helper
        hardens. In unhardened mode exhausted retries surface here and
        the flip is LOST, exactly like the pre-hardening executor. A
        SimulatedCrash loses the flip with the process — the post-restart
        environment redo re-applies it (flips are deterministic by
        name, so the redo converges)."""
        nonlocal lost_flips

        def _apply() -> None:
            obj = api.try_get(WORKLOAD_API_VERSION, WORKLOAD_KIND,
                              NAMESPACE, name)
            if obj is None:
                return
            status = dict(obj.get("status") or {})
            conds = list(status.get("conditions") or [])
            now = rfc3339(clock.now())
            conds.append({
                "type": cond_type, "status": "True", "reason": reason,
                "lastUpdateTime": now, "lastTransitionTime": now,
            })
            status["conditions"] = conds
            status["completionTime"] = now
            api.patch_status(WORKLOAD_API_VERSION, WORKLOAD_KIND,
                             NAMESPACE, name, status)

        try:
            with_conflict_retry(_apply)
        except (ConflictError, ServerTimeoutError):
            lost_flips += 1
        except SimulatedCrash:
            pass
        except NotFoundError:
            pass

    def _environment_step(r: int) -> None:
        """Deterministic workload environment for round ``r``: the
        scheduled preemption storm plus age-based terminal flips. Applied
        identically in the chaotic run and the replay — only the API
        faults underneath the flips differ. Re-run after a crash restart
        (decisions are pure functions of (seed, name), so the redo
        converges to what the replay applies)."""
        workloads = store.list(
            WORKLOAD_API_VERSION, WORKLOAD_KIND, namespace=NAMESPACE
        )
        running = []
        for w in workloads:
            name = (w.get("metadata") or {}).get("name", "")
            if not _is_terminal(w):
                running.append(name)
        storm = "preempt_storm" in by_round.get(r, ())
        for name in sorted(running):
            if pers is not None and pers.dead:
                return  # crashed mid-step; the restart redo finishes it
            age = r - _birth_round(name)
            if (
                storm
                and age < _dur(name)
                and seeded_fraction(seed, "preempt", r, name)
                < storm_plan.preempt_frac
            ):
                preempted.add(name)
                _flip(name, "Failed", "TPUSlicePreempted")
            elif name not in preempted and age >= _dur(name):
                flip_to = _terminal_for(name)
                _flip(name, flip_to,
                      "JobSucceeded" if flip_to == "Succeeded"
                      else "JobFailed")

    def _canonical(objects, rv) -> str:
        return json.dumps(
            {"rv": int(rv), "objects": sorted(
                (dict(o) for o in objects),
                key=lambda o: json.dumps(o, sort_keys=True, default=str),
            )},
            sort_keys=True, default=str,
        )

    def _restart(r: int) -> None:
        """The crash happened: bury this generation, recover the next one
        from disk (or from nothing with durability off), and catch up.
        Zero fake time passes — the restarted process resumes in the same
        fake minute, so recovery catch-up re-fires the crashed round's
        ticks under the same deterministic names."""
        nonlocal store, pers, api, mgr, rec, quiesce_timeouts, journal
        mgr.stop()
        metric_gens.append(_collect_metrics(mgr))
        fault_gens.append(
            (api.fault_counts(), api.dropped_events())
        )
        store.close()  # drains the dispatcher into the watchlog
        if journal is not None:
            # I9, dying generation's verdict: every durable WAL record
            # was audited and vice versa — tolerating ONE record the
            # kill stranded between WAL append and commit.
            audit_checks.append({
                "round": r,
                "generation": watchlog.generation,
                **journal.wal_check(pers.records_appended, crash_tail=1),
            })
            journal.close()
        kill_info = (
            dict(pers.kill_switch.describe()) if pers.kill_switch else
            {"round": r, "point": "end_of_round", "fired": True}
        )
        if not kill_info.get("fired"):
            # The PRF append index exceeded the round's write count; the
            # harness killed at the round boundary instead.
            kill_info["point"] = "end_of_round"
        gen = watchlog.generation + 1
        if durability:
            new_dir = pers.data_dir
        else:
            # Unset --data-dir semantics: nothing survives the process.
            new_dir = os.path.join(data_dir, f"gen-{gen}")
        pers = Persistence(new_dir, flush_interval_s=0)
        journal = AuditJournal()
        pers.attach_audit(journal)
        store = APIServer(clock=clock)
        store.attach_audit(journal)
        recovered = pers.recover()
        # I6: recovery is a pure function of the on-disk bytes — an
        # independent second replay must be byte-identical.
        recheck = Persistence(new_dir).recover()
        i6_ok = _canonical(recovered.objects, recovered.rv) == _canonical(
            recheck.objects, recheck.rv
        )
        state = pers.start(store)
        i6_ok = i6_ok and _canonical(
            store.all_objects(), getattr(store, "_rv")
        ) == _canonical(state.objects, state.rv) if not state.empty else i6_ok
        kills.append({
            **kill_info,
            "recovered_objects": len(state.objects),
            "recovered_rv": state.rv,
            "had_snapshot": state.had_snapshot,
            "wal_records_replayed": state.wal_records_replayed,
            "torn_records_dropped": state.torn_records_dropped,
            "i6_recovery_equals_replay": i6_ok,
        })
        api = FaultInjector(store, plan)
        watchlog.begin_generation(
            store.list(WORKLOAD_API_VERSION, WORKLOAD_KIND,
                       namespace=NAMESPACE),
            wal_deleted_names=[
                k[3] for k in state.wal_deleted_keys
                if k[1] == WORKLOAD_KIND
            ],
        )
        store.add_watcher(watchlog)
        for i in range(n_crons):
            # Durable recovery already holds the Crons (create is then a
            # no-op AlreadyExists); a durability-off restart re-applies
            # the manifests like a fresh --load boot — spec recovered,
            # STATUS (lastScheduleTime!) gone.
            try:
                store.create(_cron(i))
            except AlreadyExistsError:
                pass
        mgr, rec = _new_manager(recovering=not state.empty)
        mgr.start()
        if _quiesce(mgr, store, quiesce_timeout_s, pers) != "idle":
            quiesce_timeouts += 1
        # Redo the crashed round's environment step (flips lost with the
        # process re-apply; decisions are name-keyed so this converges),
        # then let the controllers settle the round.
        _environment_step(r)
        mgr.resync()
        if _quiesce(mgr, store, quiesce_timeout_s, pers) != "idle":
            quiesce_timeouts += 1

    t0 = time.monotonic()
    try:
        mgr.start()
        if _quiesce(mgr, store, quiesce_timeout_s, pers) != "idle":
            quiesce_timeouts += 1

        for r in range(rounds):
            faults_now = by_round.get(r, set()) if chaotic else set()
            kill_round = crash and chaotic and "kill" in faults_now
            if kill_round:
                assert pers is not None
                pers.kill_switch = KillSwitch(
                    seed, r, max_appends=KILL_MAX_APPENDS
                )
            clock.advance(timedelta(seconds=60))
            if "watch_break" in faults_now:
                api.break_watches()
            if "leader_revoke" in faults_now:
                api.revoke_leader()
                deadline = time.monotonic() + 3.0
                while time.monotonic() < deadline:
                    if not mgr._is_leader.is_set():
                        leadership_lost_seen = True
                        break
                    time.sleep(0.02)
                api.expire_leader_lease()
            # Round tick: level-triggered enqueue-all (a real operator
            # gets this from its RequeueAfter timers; the soak drives it
            # explicitly so rounds stay aligned with the fake clock).
            mgr.resync()
            if "watch_break" in faults_now and not mgr.readyz():
                readyz_degraded_seen = True
            q = _quiesce(mgr, store, quiesce_timeout_s, pers)
            if q == "timeout":
                quiesce_timeouts += 1
            if q != "dead":
                _environment_step(r)
                if "watch_break" in faults_now:
                    # Stream comes back: BOOKMARK frame → hardened
                    # managers resync (re-list + enqueue all); unhardened
                    # ones ignore it and stay degraded.
                    api.repair_watches()
                q = _quiesce(mgr, store, quiesce_timeout_s, pers)
                if q == "timeout":
                    quiesce_timeouts += 1
            if kill_round:
                if not pers.dead:
                    # Too few appends for the PRF index this round — kill
                    # at the round boundary instead (still deterministic:
                    # same seed, same boundary).
                    pers.kill(f"end_of_round/{r}")
                _restart(r)
            if pers is not None and not pers.dead:
                # Round-boundary durability point: a kill in round r+1 can
                # only lose records from round r+1 itself. The crashed
                # round's tick is then always the LATEST missed run per
                # cron, which catch-up re-fires — older ticks would fall
                # off the single-fire catch-up (CronJob parity) and show
                # up as permanent losses the WAL cannot repair.
                pers.flush()

        # ---- faults stop: convergence phase ------------------------------
        api.disarm()
        api.repair_watches()
        mgr.resync()
        if _quiesce(mgr, store, quiesce_timeout_s) != "idle":
            quiesce_timeouts += 1

        surface = _surface(store, watchlog)
        metric_gens.append(_collect_metrics(mgr))
        fault_gens.append((api.fault_counts(), api.dropped_events()))
        metrics = {
            k: sum(g[k] for g in metric_gens) for k in metric_gens[0]
        }
        faults_injected: dict = {}
        dropped_events = 0
        for counts, dropped in fault_gens:
            for k, v in counts.items():
                faults_injected[k] = faults_injected.get(k, 0) + v
            dropped_events += dropped
    finally:
        mgr.stop()
        retry_mod.DEFAULT_ATTEMPTS = prev_attempts
        if crash and chaotic:
            for h in logging.getLogger().handlers or [logging.lastResort]:
                h.removeFilter(noise_filter)

    # ---- I4: converged state needs zero further writes -------------------
    # Manager stopped, faults disarmed: a direct sweep over every Cron
    # must not commit anything (rv bracketing counts store writes).
    rv_before = int(getattr(store, "_rv"))
    for i in range(n_crons):
        rec.reconcile(NAMESPACE, f"chaos-{i}")
    final_sweep_writes = int(getattr(store, "_rv")) - rv_before

    # ---- I9: audit ≡ WAL for the surviving generation --------------------
    # Clean end, no kill in flight: zero crash tail tolerated.
    if journal is not None:
        audit_checks.append({
            "round": rounds,
            "generation": watchlog.generation,
            **journal.wal_check(pers.records_appended, crash_tail=0),
        })
        journal.close()

    # ---- I7b: nothing permanently lost across restarts -------------------
    final_names = {
        (w.get("metadata") or {}).get("name", "")
        for w in store.list(
            WORKLOAD_API_VERSION, WORKLOAD_KIND, namespace=NAMESPACE
        )
    }
    store.close()
    if pers is not None:
        pers.close()
    if own_data_dir:
        shutil.rmtree(data_dir, ignore_errors=True)
    permanently_lost = sorted(
        n for n in watchlog.ever_created
        if n not in watchlog.deleted and n not in final_names
    )

    return {
        "seed": seed,
        "chaotic": chaotic,
        "unhardened": unhardened,
        "crash": crash,
        "durability": durability,
        "elapsed_s": round(time.monotonic() - t0, 2),
        "plan": asdict(plan),
        "fault_schedule": schedule,
        "fault_trace_hash": storm_plan.trace_hash(rounds),
        "faults_injected": faults_injected,
        "dropped_watch_events": dropped_events,
        "lost_flips": lost_flips,
        "quiesce_timeouts": quiesce_timeouts,
        "readyz_degraded_seen": readyz_degraded_seen,
        "leadership_lost_seen": leadership_lost_seen,
        "kills": kills,
        "generations": watchlog.generation + 1,
        "orphans": list(watchlog.orphans),
        "refires": list(watchlog.refires),
        "resurrections": list(watchlog.resurrections),
        "phantom_deletes": list(watchlog.phantom_deletes),
        "dup_violations": list(watchlog.dup_violations),
        "permanently_lost": permanently_lost,
        "wal": pers.stats() if pers is not None else None,
        "audit_checks": audit_checks,
        "metrics": metrics,
        "surface": surface,
        "created_count": watchlog.created_count,
        "forbid_violations": list(watchlog.violations),
        "final_sweep_writes": final_sweep_writes,
    }


def run_sharded_soak(
    seed: int,
    n_crons: int,
    rounds: int,
    shards: int,
    workers: int = 2,
    chaotic: bool = True,
    quiesce_timeout_s: float = 30.0,
) -> dict:
    """The sharded-control-plane soak (``--shards N``): the same fault
    storm driven against N hash-partitioned shards (runtime/shard.py),
    each with its own store, WAL dir, manager, leader lease and a
    WAL-shipping hot-standby follower.

    Kill rounds differ from the single-store soak in exactly the way
    the architecture intends: instead of restarting the process and
    REPLAYING the WAL from disk, the harness kills one PRF-chosen shard
    leader's durability layer and PROMOTES its follower. The per-shard
    I6 check runs inside the promotion (``promote_follower``): the
    follower's state must be byte-identical to an independent replay of
    the shard's on-disk WAL, BEFORE the promoted store rewrites the
    snapshot. Everything else — environment flips, quiesce discipline,
    the seven invariants — is the single-store soak verbatim, observed
    through the shard router."""
    from cron_operator_tpu.api.scheme import GVK_CRON, default_scheme
    from cron_operator_tpu.api.v1alpha1 import rfc3339
    from cron_operator_tpu.controller.cron_controller import CronReconciler
    from cron_operator_tpu.runtime.faults import (
        FaultInjector,
        FaultPlan,
        KillSwitch,
        seeded_fraction,
    )
    from cron_operator_tpu.runtime.kube import (
        AlreadyExistsError,
        ConflictError,
        NotFoundError,
        ServerTimeoutError,
    )
    from cron_operator_tpu.runtime.manager import Manager
    from cron_operator_tpu.runtime.persistence import SimulatedCrash
    from cron_operator_tpu.runtime.retry import with_conflict_retry
    from cron_operator_tpu.runtime.shard import (
        ShardedControlPlane,
        ShardRouter,
        shard_index,
    )
    from cron_operator_tpu.telemetry.audit import AuditJournal
    from cron_operator_tpu.utils.clock import FakeClock

    storm_plan = FaultPlan.default_chaos(seed)
    storm_plan = replace(storm_plan, kill_prob=KILL_PROB)
    schedule = storm_plan.schedule(rounds)
    by_round: dict = {}
    for ev in schedule:
        by_round.setdefault(ev["round"], set()).add(ev["fault"])

    def _plan_for(si: int):
        # Decorrelated per-shard fault streams under one round schedule.
        base = seed * 1000 + si
        return (
            replace(FaultPlan.default_chaos(base), kill_prob=KILL_PROB)
            if chaotic else FaultPlan.quiet(base)
        )

    data_dir = tempfile.mkdtemp(prefix="chaos-soak-shards-")
    clock = FakeClock()
    start_epoch = int(clock.now().timestamp())
    # flush_interval_s=0: like the single-store soak, the harness owns
    # every flush point, so WAL suffix loss (and therefore follower lag
    # at the kill instant) is a pure function of the seed.
    # One shared journal; the plane hands each shard's store a shard
    # view, so every record carries its shard index and the audit≡WAL
    # continuity check (I9) runs per shard.
    journal = AuditJournal()
    audit_checks: list = []
    lag_samples = {"total": 0, "with_lag": 0, "max_records": 0,
                   "max_bytes": 0, "not_drained": 0}
    plane = ShardedControlPlane(
        n_shards=shards, replicas=1, data_dir=data_dir,
        clock=clock, flush_interval_s=0, audit=journal,
    )
    injectors = [
        FaultInjector(s.store, _plan_for(s.index)) for s in plane.shards
    ]
    # Two router views: the RAW router (invariant evidence, environment
    # reads) and the FAULTY router (harness-driven writes).
    raw_router = plane.router
    faulty_router = ShardRouter(injectors)

    forbid = {
        f"chaos-{i}" for i in range(n_crons)
        if POLICIES[i % len(POLICIES)] == "Forbid"
    }
    watchlog = WatchLog(forbid)
    for s in plane.shards:
        s.store.add_watcher(watchlog)

    for i in range(n_crons):
        raw_router.create(_cron(i))
    for s in plane.shards:
        s.persistence.flush()  # Cron specs durable before any kill

    def _new_manager(si: int, recovering: bool):
        m = Manager(
            injectors[si],
            max_concurrent_reconciles=workers,
            leader_elect=True,
            identity=f"chaos-soak-shard-{si}",
            lease_duration_s=1.0,
            recovering=recovering,
            audit=journal.shard_view(si),
        )
        plane.shards[si].leader = m.identity
        r = CronReconciler(injectors[si], metrics=m.metrics,
                           audit=journal.shard_view(si))
        m.add_controller(
            "cron", r.reconcile, for_gvk=GVK_CRON,
            owns=default_scheme().workload_kinds(),
        )
        return m, r

    managers = []
    recs = []
    for si in range(shards):
        m, r = _new_manager(si, recovering=False)
        managers.append(m)
        recs.append(r)

    preempted: set = set()
    lost_flips = 0
    quiesce_timeouts = 0
    leadership_lost_seen = False
    readyz_degraded_seen = False
    kills: list = []
    failovers: list = []
    metric_gens: list = []
    fault_gens: list = []
    noise_filter = _CrashNoiseFilter()
    if chaotic:
        for h in logging.getLogger().handlers or [logging.lastResort]:
            h.addFilter(noise_filter)

    def _collect_metrics(m) -> dict:
        g = m.metrics.get
        return {
            "reconciles_ok": g(
                'controller_runtime_reconcile_total{controller="cron",'
                'result="success"}'
            ),
            "reconcile_errors": g(
                'controller_runtime_reconcile_errors_total'
                '{controller="cron"}'
            ),
            "ticks_fired": g("cron_ticks_fired_total"),
            "ticks_skipped": g(
                'cron_ticks_skipped_total{policy="Forbid"}'
            ),
            "ticks_skipped_deadline": g(
                'cron_ticks_skipped_total{policy="StartingDeadline"}'
            ),
            "missed_runs": g("cron_missed_runs_total"),
            "watch_resyncs": g("watch_resyncs_total"),
            "submit_retries": g("cron_submit_retries_total"),
        }

    def _birth_round(name: str) -> int:
        try:
            epoch = int(name.rsplit("-", 1)[1])
        except (IndexError, ValueError):
            return 0
        return max(0, (epoch - start_epoch) // 60 - 2)

    def _dur(name: str) -> int:
        return int(seeded_fraction(seed, "dur", name) * 3)

    def _terminal_for(name: str) -> str:
        return (
            "Succeeded"
            if seeded_fraction(seed, "term", name) < 0.8 else "Failed"
        )

    def _any_dead() -> bool:
        return any(
            s.persistence is not None and s.persistence.dead
            for s in plane.shards
        )

    def _flip(name: str, cond_type: str, reason: str) -> None:
        nonlocal lost_flips

        def _apply() -> None:
            obj = faulty_router.try_get(
                WORKLOAD_API_VERSION, WORKLOAD_KIND, NAMESPACE, name
            )
            if obj is None:
                return
            status = dict(obj.get("status") or {})
            conds = list(status.get("conditions") or [])
            now = rfc3339(clock.now())
            conds.append({
                "type": cond_type, "status": "True", "reason": reason,
                "lastUpdateTime": now, "lastTransitionTime": now,
            })
            status["conditions"] = conds
            status["completionTime"] = now
            faulty_router.patch_status(
                WORKLOAD_API_VERSION, WORKLOAD_KIND, NAMESPACE, name,
                status,
            )

        try:
            with_conflict_retry(_apply)
        except (ConflictError, ServerTimeoutError):
            lost_flips += 1
        except SimulatedCrash:
            pass
        except NotFoundError:
            pass

    def _environment_step(r: int) -> None:
        workloads = raw_router.list(
            WORKLOAD_API_VERSION, WORKLOAD_KIND, namespace=NAMESPACE
        )
        running = []
        for w in workloads:
            name = (w.get("metadata") or {}).get("name", "")
            if not _is_terminal(w):
                running.append(name)
        storm = "preempt_storm" in by_round.get(r, ())
        for name in sorted(running):
            if _any_dead():
                return  # crashed mid-step; the failover redo finishes it
            age = r - _birth_round(name)
            if (
                storm
                and age < _dur(name)
                and seeded_fraction(seed, "preempt", r, name)
                < storm_plan.preempt_frac
            ):
                preempted.add(name)
                _flip(name, "Failed", "TPUSlicePreempted")
            elif name not in preempted and age >= _dur(name):
                flip_to = _terminal_for(name)
                _flip(name, flip_to,
                      "JobSucceeded" if flip_to == "Succeeded"
                      else "JobFailed")

    def _quiesce_all() -> str:
        out = "idle"
        for si, m in enumerate(managers):
            s = plane.shards[si]
            q = _quiesce(m, s.store, quiesce_timeout_s, s.persistence)
            if q == "dead":
                return "dead"
            if q == "timeout":
                out = "timeout"
        return out

    def _failover(r: int, si: int) -> None:
        """A shard leader died: bury its manager generation and promote
        the WAL-shipping follower. Zero fake time passes, exactly like
        the single-store restart — recovery catch-up re-fires the
        crashed round's ticks under the same deterministic names."""
        nonlocal quiesce_timeouts
        shard = plane.shards[si]
        # Settle the SURVIVING shards first: the watchlog generation
        # rebase below snapshots the router-wide workload list, so no
        # live shard may be mid-write while it happens.
        for osi, om in enumerate(managers):
            if osi == si:
                continue
            s = plane.shards[osi]
            if _quiesce(om, s.store, quiesce_timeout_s,
                        s.persistence) == "timeout":
                quiesce_timeouts += 1
        managers[si].stop()
        metric_gens.append(_collect_metrics(managers[si]))
        fault_gens.append(
            (injectors[si].fault_counts(), injectors[si].dropped_events())
        )
        shard.store.close()  # drain the dispatcher into the watchlog
        kill_info = (
            dict(shard.persistence.kill_switch.describe())
            if shard.persistence.kill_switch else
            {"round": r, "point": "end_of_round", "fired": True}
        )
        if not kill_info.get("fired"):
            kill_info["point"] = "end_of_round"
        # I9, dead leader's verdict BEFORE promotion resets the shard's
        # WAL position aggregate (crash_tail=1: the kill can land
        # between the WAL append and the commit).
        audit_checks.append({
            "round": r,
            "shard": si,
            **journal.wal_check(
                shard.persistence.records_appended, shard=si, crash_tail=1
            ),
        })
        # Follower lag at the kill instant — the catch-up the promotion
        # must drain (records the dead leader appended but never flushed
        # to the shipping sink are LOST with the process, exactly like
        # the single-store suffix loss; the follower serves what was
        # durable).
        lag_at_kill = shard.lag()
        # Promote: I6 (follower == independent WAL replay) is checked
        # inside, before the promoted store rewrites the snapshot.
        report = plane.promote_follower(si)
        # The follower fired replication watch events into its own
        # dispatcher while it was a standby; drain any still-queued
        # delivery BEFORE the watchlog attaches, or a late ADDED for a
        # name the generation rebase already counted as survived would
        # be misread as a double fire.
        shard.store.flush(2.0)
        injectors[si] = FaultInjector(shard.store, _plan_for(si))
        faulty_router.replace(si, injectors[si])
        kills.append({
            **kill_info,
            "shard": si,
            "promoted_objects": report["objects"],
            "promoted_rv": report["rv"],
            "follower_records_applied": report["follower_records_applied"],
            "i6_recovery_equals_replay": report["i6_ok"],
            "failover_duration_s": report["duration_s"],
            "lag_at_kill": lag_at_kill,
            "lag_after_promotion": shard.lag(),
        })
        failovers.append(si)
        watchlog.begin_generation(
            raw_router.list(WORKLOAD_API_VERSION, WORKLOAD_KIND,
                            namespace=NAMESPACE),
            wal_deleted_names=[
                k[3] for k in report["wal_deleted_keys"]
                if k[1] == WORKLOAD_KIND
            ],
        )
        shard.store.add_watcher(watchlog)
        for i in range(n_crons):
            # Durable recovery already holds this shard's Crons; the
            # re-apply is a no-op AlreadyExists (same as a --load boot).
            if shard_index(NAMESPACE, f"chaos-{i}", shards) != si:
                continue
            try:
                shard.store.create(_cron(i))
            except AlreadyExistsError:
                pass
        managers[si], recs[si] = _new_manager(si, recovering=True)
        managers[si].start()
        if _quiesce_all() != "idle":
            quiesce_timeouts += 1
        _environment_step(r)
        for m in managers:
            m.resync()
        if _quiesce_all() != "idle":
            quiesce_timeouts += 1

    t0 = time.monotonic()
    try:
        for m in managers:
            m.start()
        if _quiesce_all() != "idle":
            quiesce_timeouts += 1

        for r in range(rounds):
            faults_now = by_round.get(r, set()) if chaotic else set()
            kill_round = chaotic and "kill" in faults_now
            victim = None
            if kill_round:
                victim = int(seeded_fraction(seed, "shardkill", r) * shards)
                plane.shards[victim].persistence.kill_switch = KillSwitch(
                    seed, r, max_appends=KILL_MAX_APPENDS
                )
            clock.advance(timedelta(seconds=60))
            if "watch_break" in faults_now:
                for inj in injectors:
                    inj.break_watches()
            if "leader_revoke" in faults_now:
                # Revoke ONE PRF-chosen shard's lease: per-shard leases
                # must fail independently, not in lockstep.
                rsi = int(seeded_fraction(seed, "shardlease", r) * shards)
                injectors[rsi].revoke_leader()
                deadline = time.monotonic() + 3.0
                while time.monotonic() < deadline:
                    if not managers[rsi]._is_leader.is_set():
                        leadership_lost_seen = True
                        break
                    time.sleep(0.02)
                injectors[rsi].expire_leader_lease()
            for m in managers:
                m.resync()
            if "watch_break" in faults_now and not all(
                m.readyz() for m in managers
            ):
                readyz_degraded_seen = True
            q = _quiesce_all()
            if q == "timeout":
                quiesce_timeouts += 1
            if q != "dead":
                _environment_step(r)
                if "watch_break" in faults_now:
                    for inj in injectors:
                        inj.repair_watches()
                q = _quiesce_all()
                if q == "timeout":
                    quiesce_timeouts += 1
            if kill_round:
                vpers = plane.shards[victim].persistence
                if not vpers.dead:
                    vpers.kill(f"end_of_round/{r}")
                _failover(r, victim)
            for s in plane.shards:
                if s.persistence is not None and not s.persistence.dead:
                    # Lag telemetry evidence (I9): before the round
                    # boundary flush a busy shard's follower trails the
                    # leader (appends buffer up to fsync_every); the
                    # flush ships the bytes and the lag must drain to
                    # exactly zero records.
                    pre = s.lag()
                    s.persistence.flush()
                    post = s.lag()
                    lag_samples["total"] += 1
                    if pre["records"] or pre["bytes"]:
                        lag_samples["with_lag"] += 1
                    lag_samples["max_records"] = max(
                        lag_samples["max_records"], pre["records"])
                    lag_samples["max_bytes"] = max(
                        lag_samples["max_bytes"], pre["bytes"])
                    if post["records"] or post["bytes"]:
                        lag_samples["not_drained"] += 1

        # ---- faults stop: convergence phase ------------------------------
        for inj in injectors:
            inj.disarm()
            inj.repair_watches()
        for m in managers:
            m.resync()
        if _quiesce_all() != "idle":
            quiesce_timeouts += 1

        surface = _surface(raw_router, watchlog)
        for si, m in enumerate(managers):
            metric_gens.append(_collect_metrics(m))
            fault_gens.append(
                (injectors[si].fault_counts(), injectors[si].dropped_events())
            )
        metrics = {
            k: sum(g[k] for g in metric_gens) for k in metric_gens[0]
        }
        faults_injected: dict = {}
        dropped_events = 0
        for counts, dropped in fault_gens:
            for k, v in counts.items():
                faults_injected[k] = faults_injected.get(k, 0) + v
            dropped_events += dropped
    finally:
        for m in managers:
            m.stop()
        if chaotic:
            for h in logging.getLogger().handlers or [logging.lastResort]:
                h.removeFilter(noise_filter)

    # ---- I4: converged state needs zero further writes -------------------
    rv_before = int(getattr(raw_router, "_rv"))
    for i in range(n_crons):
        name = f"chaos-{i}"
        recs[shard_index(NAMESPACE, name, shards)].reconcile(NAMESPACE, name)
    final_sweep_writes = int(getattr(raw_router, "_rv")) - rv_before

    # ---- I7b: nothing permanently lost across failovers ------------------
    final_names = {
        (w.get("metadata") or {}).get("name", "")
        for w in raw_router.list(
            WORKLOAD_API_VERSION, WORKLOAD_KIND, namespace=NAMESPACE
        )
    }
    wal_stats = [
        s.persistence.stats() for s in plane.shards
        if s.persistence is not None
    ]
    # I9, clean end: every surviving shard's WAL, record for record.
    for s in plane.shards:
        if s.persistence is not None:
            audit_checks.append({
                "round": rounds,
                "shard": s.index,
                **journal.wal_check(
                    s.persistence.records_appended, shard=s.index,
                    crash_tail=0,
                ),
            })
    debug_shards = plane.debug_shards()
    plane.close()
    shutil.rmtree(data_dir, ignore_errors=True)
    permanently_lost = sorted(
        n for n in watchlog.ever_created
        if n not in watchlog.deleted and n not in final_names
    )

    return {
        "seed": seed,
        "shards": shards,
        "chaotic": chaotic,
        "unhardened": False,
        "crash": True,
        "durability": True,
        "elapsed_s": round(time.monotonic() - t0, 2),
        "fault_schedule": schedule,
        "fault_trace_hash": storm_plan.trace_hash(rounds),
        "faults_injected": faults_injected,
        "dropped_watch_events": dropped_events,
        "lost_flips": lost_flips,
        "quiesce_timeouts": quiesce_timeouts,
        "readyz_degraded_seen": readyz_degraded_seen,
        "leadership_lost_seen": leadership_lost_seen,
        "kills": kills,
        "failovers": failovers,
        "generations": watchlog.generation + 1,
        "orphans": list(watchlog.orphans),
        "refires": list(watchlog.refires),
        "resurrections": list(watchlog.resurrections),
        "phantom_deletes": list(watchlog.phantom_deletes),
        "dup_violations": list(watchlog.dup_violations),
        "permanently_lost": permanently_lost,
        "wal": wal_stats,
        "audit_checks": audit_checks,
        "follower_lag": lag_samples,
        "debug_shards": debug_shards,
        "metrics": metrics,
        "surface": surface,
        "created_count": watchlog.created_count,
        "forbid_violations": list(watchlog.violations),
        "final_sweep_writes": final_sweep_writes,
    }


# ---------------------------------------------------------------------------
# Elastic leg: reshard-on-preemption storms over REAL CPU-mesh training (I8)
# ---------------------------------------------------------------------------

#: Checkpoint cadence of the elastic-leg training jobs; I8's "loses at most
#: one checkpoint interval" is measured against this.
ELASTIC_SAVE_EVERY = 4
#: Fraction of in-flight runs each storm round preempts (at least one is
#: always hit so every round drives the full path).
ELASTIC_PREEMPT_FRAC = 0.6


def _elastic_steps(rounds: int) -> int:
    """Total-step target per logical run — sized so runs are still in
    flight for every storm round and train a real remainder after the
    last resume."""
    return ELASTIC_SAVE_EVERY * (3 * rounds + 3)


def _elastic_cron(i: int, ckpt_root: str, steps: int, elastic: bool) -> dict:
    ann = {
        "tpu.kubedl.io/entrypoint": "mnist",
        "tpu.kubedl.io/param.steps": str(steps),
        "tpu.kubedl.io/param.batch_size": "8",
        "tpu.kubedl.io/param.platform": "cpu",
        # Paced steps: synthetic mnist trains in microseconds per step,
        # which loses the race against the storm every time — the pacing
        # keeps runs observably in flight so preemption lands MID-RUN
        # (that, not post-hoc status surgery, is what I8 exercises).
        "tpu.kubedl.io/param.step_delay_s": "0.05",
    }
    if elastic:
        ann.update({
            "tpu.kubedl.io/elastic-resume": "true",
            "tpu.kubedl.io/param.checkpoint": "1",
            "tpu.kubedl.io/param.checkpoint_dir": ckpt_root,
            "tpu.kubedl.io/param.save_every": str(ELASTIC_SAVE_EVERY),
        })
    else:
        # Counter-proof mode: recovery is an in-place restart with NO
        # checkpoint — the re-run starts over at step 0, violating I8's
        # "loses at most one checkpoint interval".
        ann["tpu.kubedl.io/restart-on-preemption"] = "true"
    return {
        "apiVersion": CRON_API_VERSION,
        "kind": "Cron",
        "metadata": {"name": f"elastic-{i}", "namespace": NAMESPACE},
        "spec": {
            "schedule": "*/1 * * * *",
            "concurrencyPolicy": "Forbid",
            "historyLimit": 3,
            "template": {"workload": {
                "apiVersion": WORKLOAD_API_VERSION,
                "kind": WORKLOAD_KIND,
                "metadata": {"annotations": ann},
                "spec": {},
            }},
        },
    }


def _progress(store, name: str) -> dict:
    obj = store.try_get(WORKLOAD_API_VERSION, WORKLOAD_KIND, NAMESPACE, name)
    if obj is None:
        return {}
    return (obj.get("status") or {}).get("trainingProgress") or {}


def run_preempt_soak(
    seed: int,
    n_jobs: int,
    rounds: int,
    elastic: bool = True,
    train_timeout_s: float = 300.0,
) -> dict:
    """The elastic leg: REAL CPU-mesh training jobs (LocalExecutor threads
    over ``--xla_force_host_platform_device_count`` host devices) driven by
    the REAL ``CronReconciler``, hit by PRF-scheduled preemption storms.

    Each round waits (wall-clock — training is real) for every in-flight
    run to progress past a checkpoint interval, preempts a PRF-chosen
    subset through :meth:`FaultInjector.inject_preempt` (recording
    pre-preemption step counts as I8 evidence), sweeps the reconciler so
    the resume attempts are submitted against the *degraded* capacity,
    then restores capacity (the cloud re-provisioned the slice). After the
    last round every run trains to completion and the end state is
    collected for :func:`check_i8`.

    ``elastic=False`` is the counter-proof: same storms, but the jobs use
    restart-on-preemption with no checkpointing — the restarted run starts
    over at step 0, which :func:`check_i8` flags.
    """
    from cron_operator_tpu.backends.local import LocalExecutor
    from cron_operator_tpu.controller.cron_controller import CronReconciler
    from cron_operator_tpu.runtime.faults import (
        FaultInjector,
        FaultPlan,
        seeded_fraction,
    )
    from cron_operator_tpu.runtime.kube import APIServer
    from cron_operator_tpu.runtime.manager import Metrics
    from cron_operator_tpu.utils.clock import FakeClock

    t0 = time.time()
    ckpt_root = tempfile.mkdtemp(prefix="chaos-elastic-ckpt-")
    clock = FakeClock()
    store = APIServer(clock=clock)
    metrics = Metrics()
    # Quiet injector: the elastic leg injects only preemptions (API/watch
    # faults are the classic leg's job) but routes them through the fault
    # layer so storms land in the trace + faults_injected_total.
    injector = FaultInjector(store, FaultPlan.quiet(seed))
    injector.instrument(metrics)
    # gang_slots=1: the leg's jobs all mesh over the SAME 8 virtual host
    # devices; concurrent sharded programs from different threads can
    # deadlock XLA collectives, so the local slice admits one gang at a
    # time (queued jobs wait, exactly like pods pending on a busy slice).
    ex = LocalExecutor(store, metrics=metrics, gang_slots=1)
    ex.start()
    rec = CronReconciler(store, metrics=metrics)

    steps_target = _elastic_steps(rounds)
    crons = [f"elastic-{i}" for i in range(n_jobs)]
    for i in range(n_jobs):
        store.create(_elastic_cron(i, ckpt_root, steps_target, elastic))

    def sweep():
        for name in crons:
            rec.reconcile(NAMESPACE, name)

    def latest_attempt(root: str) -> str:
        """Newest attempt name of a logical run (root, root-r1, ...)."""
        best, best_no = root, -1
        for w in store.list(
            WORKLOAD_API_VERSION, WORKLOAD_KIND, namespace=NAMESPACE
        ):
            meta = w.get("metadata") or {}
            ann = meta.get("annotations") or {}
            wroot = ann.get("tpu.kubedl.io/resume-of", meta.get("name", ""))
            if wroot != root:
                continue
            try:
                no = int(ann.get("tpu.kubedl.io/resume-attempt", 0))
            except (TypeError, ValueError):
                no = 0
            if no > best_no:
                best, best_no = meta.get("name", ""), no
        return best

    # Fire exactly one tick per cron: one fake minute, one sweep.
    clock.advance(timedelta(seconds=61))
    sweep()
    roots = {}
    for w in store.list(
        WORKLOAD_API_VERSION, WORKLOAD_KIND, namespace=NAMESPACE
    ):
        meta = w.get("metadata") or {}
        cron = (meta.get("labels") or {}).get(LABEL_CRON_NAME, "")
        if cron:
            roots[cron] = meta.get("name", "")
    timeouts: list = []

    def wait_progress(job: str, floor: int, deadline: float) -> dict:
        while time.time() < deadline:
            obj = store.try_get(
                WORKLOAD_API_VERSION, WORKLOAD_KIND, NAMESPACE, job
            )
            if obj is None:
                return {}
            if _is_terminal(obj):
                return _progress(store, job)
            prog = _progress(store, job)
            if int(prog.get("steps_done") or 0) >= floor:
                return prog
            time.sleep(0.1)
        timeouts.append({"job": job, "waiting_for_step": floor})
        return _progress(store, job)

    events: list = []
    for r in range(rounds):
        # Every in-flight run must clear another checkpoint interval
        # before the storm, so "loses at most one interval" is testable.
        floor = (ELASTIC_SAVE_EVERY + 2) * (r + 1)
        deadline = time.time() + train_timeout_s
        # PRF storm selection, decided up front; force at least one
        # victim per round so every round drives the full path.
        chosen = {
            cron: seeded_fraction(seed, "elastic", r, roots[cron])
            < ELASTIC_PREEMPT_FRAC
            for cron in crons if roots.get(cron)
        }
        if chosen and not any(chosen.values()):
            chosen[next(iter(chosen))] = True
        for cron in crons:
            root = roots.get(cron)
            if not root:
                continue
            job = latest_attempt(root)
            pre = wait_progress(job, min(floor, steps_target - 2), deadline)
            obj = store.try_get(
                WORKLOAD_API_VERSION, WORKLOAD_KIND, NAMESPACE, job
            )
            # Inject IMMEDIATELY after the liveness read — the jobs are
            # paced but real, so any gap is a window for the run to
            # finish underneath the storm.
            if obj is None or _is_terminal(obj) or not chosen.get(cron):
                continue
            prior = ex.capacity()
            if prior <= 1:
                ex.restore_capacity()
                prior = ex.capacity()
            # Halve the pool 1-3 times (PRF-chosen): survivors stay a
            # power of two, so the resharded data axis always divides the
            # batch and replan keeps clean factors.
            halvings = 1 + int(
                seeded_fraction(seed, "elastic-lost", r, root) * 3
            )
            surviving = max(prior >> halvings, 1)
            lost = prior - surviving
            record = injector.inject_preempt(
                ex, NAMESPACE, job, lost_devices=lost
            )
            if record.get("jobFinished"):
                # The run crossed the finish line between the liveness
                # read and the reclaim; the executor left its terminal
                # status untouched, so there is no successor to audit.
                continue
            events.append({
                "round": r,
                "cron": cron,
                "root": root,
                "job": job,
                "pre_steps": int(pre.get("steps_done") or 0),
                "record": record,
            })
        # Resume attempts are computed against the DEGRADED capacity the
        # preemption recorded; then the slice is re-provisioned.
        sweep()
        ex.restore_capacity()

    # Drain: every logical run trains to completion on its final mesh.
    deadline = time.time() + train_timeout_s
    for cron in crons:
        root = roots.get(cron)
        if not root:
            continue
        job = latest_attempt(root)
        while time.time() < deadline:
            obj = store.try_get(
                WORKLOAD_API_VERSION, WORKLOAD_KIND, NAMESPACE, job
            )
            if obj is None or _is_terminal(obj):
                nxt = latest_attempt(root)
                if nxt == job:
                    break
                job = nxt  # terminal-but-preempted: follow the chain
                continue
            time.sleep(0.1)
        else:
            timeouts.append({"job": job, "waiting_for": "terminal"})
    # Two sweeps: the first may submit a trailing resume / finish stamps,
    # the second collapses the settled history.
    sweep()
    ex.wait_idle(timeout=train_timeout_s)
    sweep()

    # ---- end-state evidence ------------------------------------------------
    runs: dict = {}
    for cron in crons:
        root = roots.get(cron, "")
        chain: list = []
        for w in store.list(
            WORKLOAD_API_VERSION, WORKLOAD_KIND, namespace=NAMESPACE
        ):
            meta = w.get("metadata") or {}
            ann = meta.get("annotations") or {}
            wroot = ann.get("tpu.kubedl.io/resume-of", meta.get("name", ""))
            if wroot != root:
                continue
            try:
                no = int(ann.get("tpu.kubedl.io/resume-attempt", 0))
            except (TypeError, ValueError):
                no = 0
            prog = (w.get("status") or {}).get("trainingProgress") or {}
            chain.append({
                "attempt": no,
                "name": meta.get("name", ""),
                "terminal": _is_terminal(w),
                "devices": (ann.get("tpu.kubedl.io/param.devices") or ""),
                "resumed_from_step": prog.get("resumed_from_step"),
                "steps_done": int(prog.get("steps_done") or 0),
            })
        chain.sort(key=lambda a: a["attempt"])
        cron_obj = store.get(CRON_API_VERSION, "Cron", NAMESPACE, cron)
        hist = (cron_obj.get("status") or {}).get("history") or []
        runs[cron] = {
            "root": root,
            "chain": chain,
            "history": [
                {
                    "name": (h.get("object") or {}).get("name", ""),
                    "status": h.get("status", ""),
                    "resumes": int(h.get("resumes") or 0),
                }
                for h in hist
            ],
        }

    ex.stop()
    shutil.rmtree(ckpt_root, ignore_errors=True)
    return {
        "elastic": elastic,
        "n_jobs": n_jobs,
        "rounds": rounds,
        "steps_target": steps_target,
        "save_every": ELASTIC_SAVE_EVERY,
        "preempt_events": events,
        "runs": runs,
        "timeouts": timeouts,
        "metrics": {
            "preemptions": metrics.get("cron_workload_preemptions_total"),
            "resumes": metrics.get("cron_workload_resumes_total"),
            "faults_preempt": metrics.get(
                'faults_injected_total{kind="preempt"}'
            ),
        },
        "elapsed_s": round(time.time() - t0, 1),
    }


def check_i8(ev: dict) -> dict:
    """I8 elastic_resume_integrity: after preempt storms every in-flight
    job (a) finishes, with a monotonically non-decreasing step count
    across its attempt chain, (b) loses at most one checkpoint interval
    per preemption (the successor's resume step is >= the pre-preemption
    step minus ``save_every``), and (c) appears exactly once in its
    Cron's history, with ``resumes`` matching the attempt chain."""
    problems: list = []
    save_every = ev["save_every"]
    target = ev["steps_target"]

    if ev["timeouts"]:
        problems.append({"kind": "did_not_finish", "jobs": ev["timeouts"][:5]})

    # (b) per-preemption: successor start step within one interval.
    for e in ev["preempt_events"]:
        run = ev["runs"].get(e["cron"]) or {}
        chain = run.get("chain") or []
        # The successor EXECUTION of this preemption: the next attempt in
        # the chain (elastic) or the restarted job itself, whose progress
        # the in-place re-run overwrote (no-elastic counter-proof).
        if ev["elastic"]:
            mine = next(
                (a["attempt"] for a in chain if a["name"] == e["job"]), 0
            )
            after = [a for a in chain if a["attempt"] > mine]
            nxt = after[0] if after else None
        else:
            nxt = next(
                (a for a in chain if a["name"] == e["job"]), None
            )
        if nxt is None:
            problems.append({"kind": "no_successor", "event": e})
            continue
        start = int(nxt.get("resumed_from_step") or 0)
        if start < e["pre_steps"] - save_every:
            problems.append({
                "kind": "lost_more_than_one_interval",
                "event": e,
                "successor": nxt["name"],
                "resumed_from_step": start,
                "pre_steps": e["pre_steps"],
                "save_every": save_every,
            })
        if start > target:
            problems.append({
                "kind": "non_monotonic_resume",
                "event": e,
                "resumed_from_step": start,
            })

    for cron, run in ev["runs"].items():
        chain = run.get("chain") or []
        if not chain:
            problems.append({"kind": "run_vanished", "cron": cron})
            continue
        # (a) finishes at the step target, monotonic across the chain.
        final = chain[-1]
        if final["terminal"] != "Succeeded" or final["steps_done"] != target:
            problems.append({
                "kind": "did_not_complete",
                "cron": cron,
                "final": final,
            })
        starts = [int(a.get("resumed_from_step") or 0) for a in chain]
        if any(b < a for a, b in zip(starts, starts[1:])):
            problems.append({
                "kind": "non_monotonic_chain",
                "cron": cron,
                "resume_steps": starts,
            })
        # (c) exactly once in history, resumes == successor attempts.
        hist = run.get("history") or []
        entries = [h for h in hist if h["name"] == run["root"]]
        if len(hist) != 1 or len(entries) != 1:
            problems.append({
                "kind": "history_not_exactly_once",
                "cron": cron,
                "history": hist,
            })
        else:
            want = max(a["attempt"] for a in chain)
            if entries[0]["resumes"] != want:
                problems.append({
                    "kind": "history_resume_count_wrong",
                    "cron": cron,
                    "entry": entries[0],
                    "expected_resumes": want,
                })

    n_preempts = len(ev["preempt_events"])
    ok = not problems and n_preempts > 0
    return {
        "ok": ok,
        "detail": problems[:6] if problems else (
            f"{n_preempts} preemption(s) across {ev['rounds']} round(s), "
            f"{int(ev['metrics']['resumes'])} resume(s): every run "
            f"finished at step {ev['steps_target']}, lost <= 1 checkpoint "
            f"interval per preemption, exactly one history entry each"
        ),
    }


def _surface(store, watchlog) -> dict:
    """Semantic end state, shorn of run-varying identifiers (uids,
    resourceVersions, timestamps): the I5 comparison surface. Fired-tick
    names are a SET — a crash-mode refire re-creates the same
    deterministic name, which is the same tick, not a new one."""
    out: dict = {}
    for cron in store.list(CRON_API_VERSION, "Cron", namespace=NAMESPACE):
        name = (cron.get("metadata") or {}).get("name", "")
        st = cron.get("status") or {}
        out[name] = {
            "active": sorted(
                (ref.get("name", "") for ref in st.get("active") or []),
            ),
            "history": sorted(
                (
                    (h.get("object") or {}).get("name", ""),
                    h.get("status", ""),
                )
                for h in st.get("history") or []
            ),
            "fired": sorted(set(watchlog.created.get(name, []))),
        }
    workloads: dict = {}
    for w in store.list(
        WORKLOAD_API_VERSION, WORKLOAD_KIND, namespace=NAMESPACE
    ):
        meta = w.get("metadata") or {}
        cron = (meta.get("labels") or {}).get(LABEL_CRON_NAME, "?")
        workloads.setdefault(cron, []).append(
            (meta.get("name", ""), _is_terminal(w) or "Running")
        )
    for cron, entries in workloads.items():
        out.setdefault(cron, {})["workloads"] = sorted(entries)
    return out


def check_invariants(chaotic: dict, replay: dict, history_limit: int) -> dict:
    """The invariants, each with a human-readable detail string. I6/I7
    are only meaningful (and only emitted) for crash-mode runs."""
    inv: dict = {}

    inv["I1_forbid_no_concurrent"] = {
        "ok": not chaotic["forbid_violations"],
        "detail": chaotic["forbid_violations"][:5] or "never exceeded 1",
    }

    over = [
        (name, len(state.get("history", [])))
        for name, state in chaotic["surface"].items()
        if len(state.get("history", [])) > history_limit
    ]
    inv["I2_history_bounded"] = {
        "ok": not over,
        "detail": over[:5] or f"all <= historyLimit={history_limit}",
    }

    fired = chaotic["metrics"]["ticks_fired"]
    created = chaotic["created_count"]
    orphans = len(chaotic.get("orphans") or [])
    inv["I3_tick_exactly_once"] = {
        "ok": created == fired + orphans,
        "detail": (
            f"workload_creates={created} == cron_ticks_fired_total={fired}"
            f" + recovery_orphans={orphans}"
        ),
    }

    inv["I4_converges_zero_writes"] = {
        "ok": chaotic["final_sweep_writes"] == 0,
        "detail": (
            f"{chaotic['final_sweep_writes']} store writes in the "
            "post-convergence sweep"
        ),
    }

    diffs = []
    crons = sorted(set(chaotic["surface"]) | set(replay["surface"]))
    for name in crons:
        a = chaotic["surface"].get(name)
        b = replay["surface"].get(name)
        if a != b:
            diffs.append({"cron": name, "chaotic": a, "replay": b})
    inv["I5_matches_fault_free_replay"] = {
        "ok": not diffs,
        "detail": diffs[:3] or "chaotic end state == replay end state",
    }

    if chaotic.get("crash"):
        bad_recoveries = [
            k for k in chaotic["kills"]
            if not k.get("i6_recovery_equals_replay")
        ]
        inv["I6_recovery_equals_replay"] = {
            "ok": not bad_recoveries,
            "detail": bad_recoveries[:3] or (
                f"{len(chaotic['kills'])} recovery(ies), each "
                "byte-identical to an independent WAL replay"
            ),
        }
        dups = chaotic["dup_violations"]
        lost = chaotic["permanently_lost"]
        inv["I7_restart_tick_integrity"] = {
            "ok": not dups and not lost,
            "detail": {
                "double_fired": dups[:5],
                "permanently_lost": lost[:5],
                "legit_refires": len(chaotic["refires"]),
                "recovery_orphans": len(chaotic["orphans"]),
            } if (dups or lost) else (
                f"no double fires, nothing lost "
                f"({len(chaotic['refires'])} catch-up refire(s), "
                f"{len(chaotic['orphans'])} recovered orphan(s), "
                f"{len(chaotic.get('phantom_deletes', []))} phantom "
                f"delete(s) across {len(chaotic['kills'])} kill(s))"
            ),
        }

        # I9, flight recorder: the audit journal is cross-checkable
        # against the WAL — every durable record audited, every audited
        # verb durable, per generation (single store) / per shard
        # (sharded), with at most one kill-stranded tail record. The
        # sharded soak adds the lag-telemetry leg: follower lag is
        # OBSERVED (>0 records before a round-boundary flush) and drains
        # to exactly zero after every flush.
        checks = chaotic.get("audit_checks") or []
        bad_checks = [c for c in checks if not c.get("ok")]
        i9 = {
            "ok": bool(checks) and not bad_checks,
            "detail": bad_checks[:3] or (
                f"{len(checks)} audit≡WAL check(s) across "
                f"{chaotic['generations']} generation(s), record for "
                "record (≤1 kill-stranded WAL tail record each)"
            ),
        }
        lag = chaotic.get("follower_lag")
        if lag is not None and lag.get("total"):
            drained = lag["not_drained"] == 0
            seen = lag["with_lag"] > 0
            i9["follower_lag"] = lag
            i9["ok"] = i9["ok"] and drained and seen
            if drained and seen and not bad_checks:
                i9["detail"] += (
                    f"; follower lag >0 on {lag['with_lag']}/"
                    f"{lag['total']} flush point(s) (max "
                    f"{lag['max_records']} records / {lag['max_bytes']} "
                    "bytes) and drained to zero after every flush"
                )
            else:
                i9["detail"] = {
                    "audit": i9["detail"],
                    "follower_lag": lag,
                }
        inv["I9_flight_recorder"] = i9
    return inv


#: Minimum training goodput (productive / total steps trained across the
#: attempt chains) the preempt-storm leg must clear — the I9 goodput leg.
GOODPUT_FLOOR = 0.5


def compute_goodput(ev: dict, floor: float = GOODPUT_FLOOR) -> dict:
    """Training goodput per attempt chain from the elastic-leg evidence:
    productive steps (the target, trained exactly once end to end) over
    TOTAL steps trained across the chain — every step re-trained between
    a resume point and the preempted attempt's last step is waste."""
    per_chain: dict = {}
    sum_productive = 0
    sum_trained = 0
    for cron, run in (ev.get("runs") or {}).items():
        chain = run.get("chain") or []
        if not chain:
            continue
        trained = sum(
            max(
                0,
                int(a.get("steps_done") or 0)
                - int(a.get("resumed_from_step") or 0),
            )
            for a in chain
        )
        target = int(ev["steps_target"])
        productive = min(target, int(chain[-1].get("steps_done") or 0))
        per_chain[cron] = {
            "attempts": len(chain),
            "productive_steps": productive,
            "total_steps_trained": trained,
            "wasted_steps": max(0, trained - productive),
            "goodput": round(productive / trained, 4) if trained else 0.0,
        }
        sum_productive += productive
        sum_trained += trained
    overall = sum_productive / sum_trained if sum_trained else 0.0
    return {
        "per_chain": per_chain,
        "overall": round(overall, 4),
        "floor": floor,
        "ok": bool(per_chain) and overall >= floor,
    }


# ---------------------------------------------------------------------------
# Fleet capacity-flap leg (ISSUE 10): the heterogeneity-aware scheduler
# under a shrinking/growing slice pool.
# ---------------------------------------------------------------------------

FLEET_POOL = "v5e-16=2,v4-8=3,cpu=3"
FLEET_QUOTAS = {"team-a": 40, "team-b": 24}


def _fleet_cron(i: int, duration_s: float, priority: str, tenant: str,
                wclass: str) -> dict:
    return {
        "apiVersion": CRON_API_VERSION,
        "kind": "Cron",
        "metadata": {"name": f"fleet-{i}", "namespace": NAMESPACE},
        "spec": {
            "schedule": "*/1 * * * *",
            "concurrencyPolicy": "Forbid",
            "historyLimit": 3,
            "template": {"workload": {
                "apiVersion": WORKLOAD_API_VERSION,
                "kind": WORKLOAD_KIND,
                "metadata": {"annotations": {
                    # Simulated run: cheap, but flows through the full
                    # condition/preemption machinery in the executor.
                    "tpu.kubedl.io/simulate-duration": f"{duration_s}s",
                    "tpu.kubedl.io/elastic-resume": "true",
                    "tpu.kubedl.io/priority": priority,
                    "tpu.kubedl.io/tenant": tenant,
                    "tpu.kubedl.io/workload-class": wclass,
                }},
                "spec": {},
            }},
        },
    }


def run_fleet_soak(seed: int, n_crons: int, rounds: int,
                   drain_timeout_s: float = 60.0) -> dict:
    """Capacity-flap rounds against the fleet scheduler: one fired tick
    per cron over a 3-type pool with tenant quotas, then per round a
    PRF-chosen slice type shrinks (free slices first, then preemption of
    the lowest-priority running gangs through the REAL executor) and
    grows back. Invariants checked by :func:`check_fleet_invariants`:
    no admitted job is permanently lost, tenant quotas are never
    exceeded, and every preempted run resumes via the elastic chain
    into a single logical history entry."""
    from cron_operator_tpu.backends.local import LocalExecutor
    from cron_operator_tpu.controller.cron_controller import CronReconciler
    from cron_operator_tpu.runtime.faults import seeded_fraction
    from cron_operator_tpu.runtime.fleet import (
        FleetScheduler,
        parse_pool,
    )
    from cron_operator_tpu.runtime.kube import APIServer
    from cron_operator_tpu.runtime.manager import Metrics
    from cron_operator_tpu.telemetry import AuditJournal
    from cron_operator_tpu.utils.clock import FakeClock

    t0 = time.time()
    clock = FakeClock()
    store = APIServer(clock=clock)
    metrics = Metrics()
    journal = AuditJournal()
    store.attach_audit(journal)
    ex = LocalExecutor(store, metrics=metrics)
    ex.start()
    fs = FleetScheduler(
        parse_pool(FLEET_POOL),
        api=store,
        backend=ex,
        quotas=dict(FLEET_QUOTAS),
        max_queue=n_crons * (rounds + 2),  # nothing sheds in this leg
        metrics=metrics,
        audit=journal,
    )
    store.add_watcher(fs._on_event, coalesce=True)
    rec = CronReconciler(store, metrics=metrics, audit=journal, fleet=fs)

    crons = []
    for i in range(n_crons):
        # PRF-derived mix: long runs span flap rounds (preemption lands
        # mid-run), short ones churn the queue; priorities make victim
        # selection meaningful; two tenants exercise the quotas.
        f = seeded_fraction(seed, "fleet-mix", 0, f"fleet-{i}")
        duration = 2.5 if f < 0.4 else 0.5
        priority = ("high", "normal", "batch")[i % 3]
        tenant = ("team-a", "team-b")[i % 2]
        wclass = ("train-large", "train-small", "eval")[i % 3]
        store.create(_fleet_cron(i, duration, priority, tenant, wclass))
        crons.append(f"fleet-{i}")

    def sweep():
        for name in crons:
            rec.reconcile(NAMESPACE, name)

    def churn(seconds: float):
        deadline = time.time() + seconds
        while time.time() < deadline:
            store.flush(1.0)
            fs.pump()
            sweep()
            time.sleep(0.05)

    # One fired tick per cron: one fake minute, one sweep. Some place
    # immediately, the rest queue against the saturated pool.
    clock.advance(timedelta(seconds=61))
    sweep()
    admitted = {}
    for w in store.list(WORKLOAD_API_VERSION, WORKLOAD_KIND,
                        namespace=NAMESPACE):
        meta = w.get("metadata") or {}
        cron = (meta.get("labels") or {}).get(LABEL_CRON_NAME, "")
        if cron:
            admitted[cron] = meta.get("name", "")
    # Queued ticks exist only in the fleet's books until dispatch; count
    # them admitted too (the invariant is about THEM above all).
    queued_at_fire = fs.stats()["queued"]

    type_names = [t.strip().split("=")[0] for t in FLEET_POOL.split(",")]
    flaps = []
    for r in range(rounds):
        churn(0.6)
        stype = type_names[
            int(seeded_fraction(seed, "fleet-flap", r, "type")
                * len(type_names)) % len(type_names)
        ]
        free_before = fs.stats()["free"][stype]
        preempted_before = fs.preempted_total
        # Shrink past the free slices so the flap must preempt whenever
        # anything is running on the chosen type.
        removed = fs.shrink_capacity(stype, free_before + 1)
        sweep()  # resume attempts submitted against the degraded pool
        fs.pump()
        churn(0.3)
        restored = fs.restore_capacity(stype)
        fs.pump()
        flaps.append({
            "round": r,
            "slice_type": stype,
            "free_before": free_before,
            "removed": removed,
            "restored": restored,
            "preempted": fs.preempted_total - preempted_before,
        })

    # Drain: every logical run must reach a Succeeded latest attempt.
    deadline = time.time() + drain_timeout_s
    def all_done():
        for cron in crons:
            root = admitted.get(cron)
            if root is None:
                return False
            latest = None
            best_no = -1
            for w in store.list(WORKLOAD_API_VERSION, WORKLOAD_KIND,
                                namespace=NAMESPACE):
                meta = w.get("metadata") or {}
                ann = meta.get("annotations") or {}
                wroot = ann.get("tpu.kubedl.io/resume-of",
                                meta.get("name", ""))
                if wroot != root:
                    continue
                try:
                    no = int(ann.get("tpu.kubedl.io/resume-attempt", 0))
                except (TypeError, ValueError):
                    no = 0
                if no > best_no:
                    best_no, latest = no, w
            if latest is None or _is_terminal(latest) != "Succeeded":
                return False
        return True

    while time.time() < deadline:
        churn(0.2)
        # A fired tick may still be waiting in the fleet queue: it only
        # appears in the store (and `admitted`) once dispatched.
        for w in store.list(WORKLOAD_API_VERSION, WORKLOAD_KIND,
                            namespace=NAMESPACE):
            meta = w.get("metadata") or {}
            cron = (meta.get("labels") or {}).get(LABEL_CRON_NAME, "")
            ann = meta.get("annotations") or {}
            if cron and "tpu.kubedl.io/resume-of" not in ann:
                admitted.setdefault(cron, meta.get("name", ""))
        if len(admitted) == len(crons) and all_done():
            break
    ex.wait_idle(timeout=drain_timeout_s)
    sweep()
    store.flush(2.0)
    fs.pump()
    sweep()

    # ---- end-state evidence ----------------------------------------------
    runs = {}
    preempted_roots = set()
    for cron in crons:
        root = admitted.get(cron, "")
        chain = []
        for w in store.list(WORKLOAD_API_VERSION, WORKLOAD_KIND,
                            namespace=NAMESPACE):
            meta = w.get("metadata") or {}
            ann = meta.get("annotations") or {}
            wroot = ann.get("tpu.kubedl.io/resume-of",
                            meta.get("name", ""))
            if wroot != root:
                continue
            conds = (w.get("status") or {}).get("conditions") or []
            was_preempted = any(
                c.get("type") == "Preempted" for c in conds
            )
            if was_preempted:
                preempted_roots.add(cron)
            try:
                no = int(ann.get("tpu.kubedl.io/resume-attempt", 0))
            except (TypeError, ValueError):
                no = 0
            chain.append({
                "attempt": no,
                "name": meta.get("name", ""),
                "terminal": _is_terminal(w),
                "preempted": was_preempted,
                "slice_type": ann.get("tpu.kubedl.io/fleet-slice-type"),
            })
        chain.sort(key=lambda a: a["attempt"])
        cron_obj = store.get(CRON_API_VERSION, "Cron", NAMESPACE, cron)
        hist = (cron_obj.get("status") or {}).get("history") or []
        runs[cron] = {
            "root": root,
            "chain": chain,
            "history": [
                {
                    "name": (h.get("object") or {}).get("name", ""),
                    "status": h.get("status", ""),
                    "resumes": int(h.get("resumes") or 0),
                }
                for h in hist
            ],
        }

    stats = fs.stats()
    fs.stop()
    ex.stop()
    store.close()
    return {
        "n_crons": n_crons,
        "rounds": rounds,
        "pool": FLEET_POOL,
        "quotas": dict(FLEET_QUOTAS),
        "queued_at_fire": queued_at_fire,
        "flaps": flaps,
        "runs": runs,
        "preempted_crons": sorted(preempted_roots),
        "fleet_stats": stats,
        "metrics": {
            "fleet_preemptions": metrics.get("fleet_preemptions_total"),
            "fleet_rejections": metrics.get("fleet_rejections_total"),
            "fleet_backfills": metrics.get("fleet_backfills_total"),
            "resumes": metrics.get("cron_workload_resumes_total"),
        },
        "elapsed_s": round(time.time() - t0, 1),
    }


def check_fleet_invariants(ev: dict) -> dict:
    """F1 no admitted job permanently lost, F2 quotas never exceeded,
    F3 every preempted run resumed via the elastic chain into a single
    logical history entry (and at least one preemption actually
    happened — a flap leg that never preempts proves nothing)."""
    lost = []
    for cron, run in ev["runs"].items():
        if not run["root"]:
            lost.append({"cron": cron, "reason": "tick never dispatched"})
            continue
        chain = run["chain"]
        if not chain or chain[-1]["terminal"] != "Succeeded":
            lost.append({
                "cron": cron,
                "reason": "latest attempt not Succeeded",
                "chain": chain,
            })
    f1 = {
        "ok": not lost,
        "detail": (f"all {len(ev['runs'])} admitted runs completed "
                   f"across {len(ev['flaps'])} capacity flaps"
                   if not lost else {"lost": lost}),
    }

    peaks = ev["fleet_stats"]["tenant_peak"]
    over = {
        t: {"peak": peaks.get(t, 0), "quota": q}
        for t, q in ev["quotas"].items()
        if peaks.get(t, 0) > q
    }
    f2 = {
        "ok": not over,
        "detail": (f"tenant peaks {peaks} within quotas {ev['quotas']}"
                   if not over else {"exceeded": over}),
    }

    bad = []
    n_preempted = len(ev["preempted_crons"])
    for cron in ev["preempted_crons"]:
        run = ev["runs"][cron]
        hist = run["history"]
        if len(hist) != 1 or hist[0]["status"] != "Succeeded" \
                or hist[0]["resumes"] < 1:
            bad.append({"cron": cron, "history": hist,
                        "chain": run["chain"]})
    f3 = {
        "ok": n_preempted >= 1 and not bad,
        "detail": (
            f"{n_preempted} preempted run(s) each collapsed to one "
            "Succeeded history entry with resumes >= 1"
            if n_preempted >= 1 and not bad
            else {"preempted": n_preempted, "bad": bad}
        ),
    }
    return {
        "F1_no_admitted_job_lost": f1,
        "F2_quotas_never_exceeded": f2,
        "F3_preempted_resume_single_history": f3,
    }


# ---------------------------------------------------------------------------
# Bidirectional-elasticity grow leg (ISSUE 14): checkpoint-and-regrow a
# running training job into sustained idle fleet capacity, shrink it back
# under priority pressure, and prove the goodput margin over shrink-only.
# ---------------------------------------------------------------------------

#: Width tiers for the grow leg: host-local slices of 2/4/8 virtual CPU
#: devices (the @chips pool syntax). The elastic job launches on the
#: narrow tier because the wider ones are busy; as they idle, the
#: GrowPlanner regrows it 2 → 4 → 8.
GROW_POOL = "cpu-small=1@2,cpu-mid=1@4,cpu-wide=1@8"
GROW_QUOTAS = {"team-grow": 8, "team-block": 64}
GROW_CRON = "growme"
#: Required goodput advantage of the grow-enabled leg over shrink-only.
GROW_MARGIN_FLOOR = 1.15
#: Counter-proof floor: shrink-only must leave at least this much idle
#: wider-slice capacity unreclaimed while the elastic gang trains narrow.
GROW_IDLE_GAP_FLOOR_CHIP_S = 2.0
#: Per-device batch of the grow entrypoint: tokens/step scale with mesh
#: width, so regrowing genuinely raises token throughput.
GROW_BATCH_PER_DEVICE = 8
GROW_STEPS_TARGET = ELASTIC_SAVE_EVERY * 40


def _register_grow_entrypoint() -> None:
    """A real training entrypoint whose GLOBAL batch scales with the
    mesh (``batch_per_device × n_devices``): a regrown job processes
    proportionally more samples per step, which is the throughput the
    goodput comparison measures. Steps are paced (``param.pace_s``) so
    the scenario's grows land mid-run."""
    from cron_operator_tpu.backends.registry import (
        register_entrypoint,
        resolve_entrypoint,
    )

    try:
        resolve_entrypoint("chaos-grow-paced")
        return  # both legs of one soak share the registration
    except Exception:  # noqa: BLE001 — not registered yet
        pass

    import jax
    import jax.numpy as jnp

    from cron_operator_tpu.workloads import entrypoints as eps
    from cron_operator_tpu.workloads.train import TrainConfig, Trainer

    dim, classes = 16, 10

    def _apply(p, x):
        return x @ p["w"] + p["b"]

    def _params0():
        k = jax.random.PRNGKey(7)
        return {
            "w": jax.random.normal(k, (dim, classes), jnp.float32) * 0.1,
            "b": jnp.zeros((classes,), jnp.float32),
        }

    @register_entrypoint("chaos-grow-paced")
    def grow_train(ctx):
        steps = int(ctx.params.get("steps", GROW_STEPS_TARGET))
        pace = float(ctx.params.get("pace_s", 0.05))
        devs = eps._devices(ctx)
        per_dev = int(
            ctx.params.get("batch_per_device", GROW_BATCH_PER_DEVICE)
        )
        batch = per_dev * max(1, len(devs))

        def _sample(key):
            kx, ky = jax.random.split(key)
            return {
                "x": jax.random.normal(kx, (batch, dim), jnp.float32),
                "y": jax.random.randint(ky, (batch,), 0, classes),
            }

        with jax.default_device(devs[0]):
            mesh = eps._mesh(ctx, devs)
            trainer = Trainer(
                _apply, _params0(), mesh,
                TrainConfig(**eps._train_kwargs(
                    ctx, steps, optimizer="sgd", learning_rate=0.05,
                    data_seed=3,
                )),
                checkpoint=eps._checkpoint_store(ctx),
                sample_fn=_sample,
            )

            def paced():
                while True:
                    time.sleep(pace)
                    yield {}

            eps._run(ctx, trainer, paced(), steps)


def _grow_cron(name: str, ann: dict) -> dict:
    return {
        "apiVersion": CRON_API_VERSION,
        "kind": "Cron",
        "metadata": {"name": name, "namespace": NAMESPACE},
        "spec": {
            "schedule": "*/1 * * * *",
            "concurrencyPolicy": "Forbid",
            "historyLimit": 3,
            "template": {"workload": {
                "apiVersion": WORKLOAD_API_VERSION,
                "kind": WORKLOAD_KIND,
                "metadata": {"annotations": ann},
                "spec": {},
            }},
        },
    }


def run_grow_soak(seed: int, grow: bool = True,
                  train_timeout_s: float = 240.0) -> dict:
    """The bidirectional-elasticity leg: ONE real paced training job
    (per-device batch) over a three-tier width pool, driven by the REAL
    fleet scheduler with the GrowPlanner on (``grow=True``) or off (the
    shrink-only baseline the goodput margin is measured against).

    Scripted scenario, phase-driven by observed state:

    1. Simulated blockers occupy the 8- and 4-chip slices; the elastic
       job launches on the 2-chip tier (``param.devices=2``).
    2. The 4-chip blocker finishes → sustained idle → the GrowPlanner
       checkpoint-and-regrows the job to width 4 (``-r1``).
    3. The 8-chip blocker finishes → second grow to width 8 (``-r2``).
    4. A high-priority aggressor pinned to the wide slice arrives → the
       grown gang shrinks BACK to its original width 2 via the planned
       reconfigure path (``-r3``, reason FleetShrink — not Preempted).
    5. The job trains to completion; history collapses to one entry
       carrying both ``resumes`` and ``grows`` counts.

    With ``grow=False`` the same timeline runs but the job stays at
    width 2 throughout; the loop additionally integrates the idle
    chip-seconds of wider slices the job COULD have used — the measured
    gap the counter-proof (``--no-grow --expect-violation``) asserts.
    """
    from cron_operator_tpu.backends.local import LocalExecutor
    from cron_operator_tpu.controller.cron_controller import CronReconciler
    from cron_operator_tpu.runtime.fleet import FleetScheduler, parse_pool
    from cron_operator_tpu.runtime.kube import APIServer
    from cron_operator_tpu.runtime.manager import Metrics
    from cron_operator_tpu.utils.clock import FakeClock

    _register_grow_entrypoint()
    t0 = time.time()
    ckpt_root = tempfile.mkdtemp(prefix="chaos-grow-ckpt-")
    clock = FakeClock()
    store = APIServer(clock=clock)
    metrics = Metrics()
    # gang_slots=1 serializes REAL training gangs on the shared virtual
    # device pool (simulated blockers bypass gang admission).
    ex = LocalExecutor(store, metrics=metrics, gang_slots=1)
    ex.start()
    fs = FleetScheduler(
        parse_pool(GROW_POOL), api=store, backend=ex,
        quotas=dict(GROW_QUOTAS), metrics=metrics,
        grow_enabled=grow, grow_idle_pumps=3,
    )
    store.add_watcher(fs._on_event, coalesce=True)
    rec = CronReconciler(store, metrics=metrics, fleet=fs)

    grow_ann = {
        "tpu.kubedl.io/entrypoint": "chaos-grow-paced",
        "tpu.kubedl.io/param.steps": str(GROW_STEPS_TARGET),
        "tpu.kubedl.io/param.pace_s": "0.15",
        "tpu.kubedl.io/param.batch_per_device": str(GROW_BATCH_PER_DEVICE),
        "tpu.kubedl.io/param.platform": "cpu",
        "tpu.kubedl.io/param.devices": "2",
        "tpu.kubedl.io/param.checkpoint": "1",
        "tpu.kubedl.io/param.checkpoint_dir": ckpt_root,
        "tpu.kubedl.io/param.save_every": str(ELASTIC_SAVE_EVERY),
        # Keep every step: F4 restores the exact width-boundary
        # checkpoints post-hoc; default retention (3) would GC them.
        "tpu.kubedl.io/param.checkpoint_keep": "64",
        "tpu.kubedl.io/elastic-resume": "true",
        "tpu.kubedl.io/min-reconfigure-interval": "0.2",
        "tpu.kubedl.io/priority": "batch",
        "tpu.kubedl.io/tenant": "team-grow",
        "tpu.kubedl.io/workload-class": "train",
    }
    blockers = [
        # Reconcile order decides placement: the first blocker takes the
        # widest free slice. Durations stagger the idle windows.
        ("block-wide", "5s"),
        ("block-mid", "2.5s"),
    ]
    for bname, dur in blockers:
        store.create(_grow_cron(bname, {
            "tpu.kubedl.io/simulate-duration": dur,
            "tpu.kubedl.io/priority": "high",
            "tpu.kubedl.io/tenant": "team-block",
        }))
    store.create(_grow_cron(GROW_CRON, grow_ann))
    crons = [b for b, _d in blockers] + [GROW_CRON]

    def sweep():
        for name in crons:
            rec.reconcile(NAMESPACE, name)

    def suspend(name):
        import copy as _copy

        obj = _copy.deepcopy(
            store.get(CRON_API_VERSION, "Cron", NAMESPACE, name)
        )
        obj["spec"]["suspend"] = True
        store.update(obj)

    # One fired tick per cron (fake minute), then park the blockers so
    # later clock advances don't re-fire them.
    clock.advance(timedelta(seconds=61))
    sweep()
    for bname, _d in blockers:
        suspend(bname)
    root = ""
    for w in store.list(WORKLOAD_API_VERSION, WORKLOAD_KIND,
                        namespace=NAMESPACE):
        meta = w.get("metadata") or {}
        if (meta.get("labels") or {}).get(LABEL_CRON_NAME) == GROW_CRON:
            root = meta.get("name", "")
    timeouts: list = []
    idle_gap_chip_s = 0.0
    train_started_at = None
    train_ended_at = None

    def latest_attempt():
        best, best_no = None, -1
        for w in store.list(WORKLOAD_API_VERSION, WORKLOAD_KIND,
                            namespace=NAMESPACE):
            meta = w.get("metadata") or {}
            ann = meta.get("annotations") or {}
            wroot = ann.get("tpu.kubedl.io/resume-of",
                            meta.get("name", ""))
            if wroot != root:
                continue
            try:
                no = int(ann.get("tpu.kubedl.io/resume-attempt", 0))
            except (TypeError, ValueError):
                no = 0
            if no > best_no:
                best, best_no = w, no
        return best

    def churn_until(cond, what, timeout_s=60.0):
        """Pump/sweep until cond(latest attempt) — integrating the idle
        gap of wider slices the elastic gang is not using."""
        nonlocal idle_gap_chip_s, train_started_at, train_ended_at
        pool = {t.name: t for t in parse_pool(GROW_POOL)}
        deadline = time.time() + timeout_s
        last = time.time()
        while time.time() < deadline:
            store.flush(0.05)
            fs.pump()
            sweep()
            now = time.time()
            dt, last = now - last, now
            w = latest_attempt()
            if w is not None:
                ann = (w.get("metadata") or {}).get("annotations") or {}
                terminal = _is_terminal(w)
                if train_started_at is None and (
                    (w.get("status") or {}).get("trainingProgress")
                ):
                    train_started_at = now
                if terminal == "Succeeded":
                    train_ended_at = train_ended_at or now
                try:
                    cur_width = int(
                        ann.get("tpu.kubedl.io/param.devices") or 0
                    )
                except (TypeError, ValueError):
                    cur_width = 0
                if not terminal and cur_width > 0:
                    free = fs.stats()["free"]
                    wider = [
                        pool[n].chips - cur_width
                        for n, k in free.items()
                        if k > 0 and pool[n].chips > cur_width
                    ]
                    if wider:
                        idle_gap_chip_s += max(wider) * dt
                if cond(w):
                    return w
            time.sleep(0.05)
        timeouts.append({"phase": what})
        return latest_attempt()

    def width_of(w):
        if w is None:
            return 0
        ann = (w.get("metadata") or {}).get("annotations") or {}
        try:
            return int(ann.get("tpu.kubedl.io/param.devices") or 0)
        except (TypeError, ValueError):
            return 0

    def steps_of(w):
        if w is None:
            return 0
        prog = (w.get("status") or {}).get("trainingProgress") or {}
        return int(prog.get("steps_done") or 0)

    if grow:
        # Phase 2/3: each blocker's exit opens a wider tier; the
        # GrowPlanner must regrow the job into it.
        churn_until(lambda w: width_of(w) >= 4, "grow-to-4",
                    train_timeout_s / 3)
        churn_until(lambda w: width_of(w) >= 8, "grow-to-8",
                    train_timeout_s / 3)
        # Train a little at full width before the pressure arrives.
        wide_floor = steps_of(latest_attempt()) + 2 * ELASTIC_SAVE_EVERY
        churn_until(lambda w: steps_of(w) >= wide_floor or _is_terminal(w),
                    "train-at-8", train_timeout_s / 3)
    else:
        # Shrink-only baseline: same timeline, no grows — wait out both
        # blockers, then let the job train past the half-way mark with
        # the wider slices sitting idle (the measured gap).
        churn_until(
            lambda w: steps_of(w) >= GROW_STEPS_TARGET // 2
            or _is_terminal(w),
            "train-narrow", train_timeout_s / 2,
        )

    # Phase 4: high-priority pressure on the wide slice. In the grow leg
    # the victim is the grown gang → planned shrink-back to width 2.
    # Submitted straight to the fleet (the controller's fire path does
    # the same) so no clock tick is needed — advancing the fake minute
    # here would re-fire the growme cron into a second logical run.
    aggressor_name = "aggressor-0"
    fs.submit({
        "apiVersion": WORKLOAD_API_VERSION,
        "kind": WORKLOAD_KIND,
        "metadata": {
            "name": aggressor_name,
            "namespace": NAMESPACE,
            "annotations": {
                "tpu.kubedl.io/simulate-duration": "2s",
                "tpu.kubedl.io/priority": "high",
                "tpu.kubedl.io/tenant": "team-block",
                "tpu.kubedl.io/fleet-slice-type": "cpu-wide",
            },
        },
        "spec": {},
    })
    if grow:
        churn_until(
            lambda w: width_of(w) == 2 and int(
                ((w.get("metadata") or {}).get("annotations") or {}).get(
                    "tpu.kubedl.io/resume-attempt", 0)
            ) >= 3 or _is_terminal(w) == "Succeeded",
            "shrink-back", train_timeout_s / 3,
        )

    # Phase 5: drain to completion.
    churn_until(lambda w: _is_terminal(w) == "Succeeded", "drain",
                train_timeout_s)
    ex.wait_idle(timeout=train_timeout_s)
    sweep()
    store.flush(2.0)
    fs.pump()
    sweep()

    # ---- end-state evidence ----------------------------------------------
    runs: dict = {}
    for cron in crons:
        chain: list = []
        croot = ""
        for w in store.list(WORKLOAD_API_VERSION, WORKLOAD_KIND,
                            namespace=NAMESPACE):
            meta = w.get("metadata") or {}
            if (meta.get("labels") or {}).get(LABEL_CRON_NAME) == cron \
                    and "tpu.kubedl.io/resume-of" not in (
                        meta.get("annotations") or {}):
                croot = meta.get("name", "")
        for w in store.list(WORKLOAD_API_VERSION, WORKLOAD_KIND,
                            namespace=NAMESPACE):
            meta = w.get("metadata") or {}
            ann = meta.get("annotations") or {}
            wroot = ann.get("tpu.kubedl.io/resume-of",
                            meta.get("name", ""))
            if wroot != croot or not croot:
                continue
            try:
                no = int(ann.get("tpu.kubedl.io/resume-attempt", 0))
            except (TypeError, ValueError):
                no = 0
            prog = (w.get("status") or {}).get("trainingProgress") or {}
            chain.append({
                "attempt": no,
                "name": meta.get("name", ""),
                "terminal": _is_terminal(w),
                "devices": ann.get("tpu.kubedl.io/param.devices") or "",
                "cause": ann.get("tpu.kubedl.io/resume-cause") or "",
                "slice_type": ann.get("tpu.kubedl.io/fleet-slice-type"),
                "resumed_from_step": prog.get("resumed_from_step"),
                "steps_done": int(prog.get("steps_done") or 0),
            })
        chain.sort(key=lambda a: a["attempt"])
        cron_obj = store.get(CRON_API_VERSION, "Cron", NAMESPACE, cron)
        hist = (cron_obj.get("status") or {}).get("history") or []
        runs[cron] = {
            "root": croot,
            "chain": chain,
            "history": [
                {
                    "name": (h.get("object") or {}).get("name", ""),
                    "status": h.get("status", ""),
                    "resumes": int(h.get("resumes") or 0),
                    "grows": int(h.get("grows") or 0),
                }
                for h in hist
            ],
        }

    agg = store.try_get(WORKLOAD_API_VERSION, WORKLOAD_KIND, NAMESPACE,
                        aggressor_name)
    runs["aggressor"] = {
        "root": aggressor_name,
        "chain": [{
            "attempt": 0,
            "name": aggressor_name,
            "terminal": _is_terminal(agg) if agg is not None else "",
            "devices": "",
            "cause": "",
            "slice_type": "cpu-wide",
            "resumed_from_step": None,
            "steps_done": 0,
        }],
        "history": [],
    }

    stats = fs.stats()
    fs.stop()
    ex.stop()
    store.close()
    elapsed_train = (
        round(train_ended_at - train_started_at, 2)
        if train_started_at and train_ended_at else None
    )
    return {
        "grow_enabled": grow,
        "pool": GROW_POOL,
        "quotas": dict(GROW_QUOTAS),
        "steps_target": GROW_STEPS_TARGET,
        "save_every": ELASTIC_SAVE_EVERY,
        "batch_per_device": GROW_BATCH_PER_DEVICE,
        "ckpt_root": ckpt_root,
        "runs": runs,
        "fleet_stats": stats,
        "idle_gap_chip_s": round(idle_gap_chip_s, 2),
        "train_elapsed_s": elapsed_train,
        "timeouts": timeouts,
        "metrics": {
            "fleet_grows": metrics.get("fleet_grows_total"),
            "fleet_shrinks": metrics.get("fleet_shrinks_total"),
            "resumes": metrics.get("cron_workload_resumes_total"),
        },
        "elapsed_s": round(time.time() - t0, 1),
    }


def compute_grow_goodput(ev: dict) -> dict:
    """Token goodput of the elastic job: tokens/step scale with the
    attempt's width (per-device batch), first-time steps count once,
    re-trained steps after a resume are waste."""
    run = (ev.get("runs") or {}).get(GROW_CRON) or {}
    chain = run.get("chain") or []
    per_dev = int(ev.get("batch_per_device") or GROW_BATCH_PER_DEVICE)
    tokens_useful = 0
    tokens_trained = 0
    prev_peak = 0
    for a in chain:
        devices = int(a.get("devices") or 0) or 1
        start = int(a.get("resumed_from_step") or 0)
        end = int(a.get("steps_done") or 0)
        trained = max(0, end - start)
        useful = max(0, end - max(start, prev_peak))
        tokens_trained += trained * devices * per_dev
        tokens_useful += useful * devices * per_dev
        prev_peak = max(prev_peak, end)
    elapsed = ev.get("train_elapsed_s") or 0.0
    return {
        "attempts": len(chain),
        "tokens_useful": tokens_useful,
        "tokens_trained": tokens_trained,
        "wasted_tokens": max(0, tokens_trained - tokens_useful),
        "train_elapsed_s": elapsed,
        "tokens_per_s": (
            round(tokens_useful / elapsed, 2) if elapsed else 0.0
        ),
    }


def check_f4(ev: dict) -> dict:
    """F4 grow_bit_exact: at EVERY width-change boundary of the grown
    job's chain, the checkpoint written at the old width restores
    bit-for-bit onto a mesh of the new width (``restore_resharded``
    against the actual soak checkpoints — resharding moves bytes, never
    rounds them)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from cron_operator_tpu.parallel.mesh import mesh_for_devices
    from cron_operator_tpu.workloads.checkpoint import CheckpointStore

    run = (ev.get("runs") or {}).get(GROW_CRON) or {}
    chain = run.get("chain") or []
    root = run.get("root") or ""
    boundaries: list = []
    problems: list = []
    if not root or len(chain) < 2:
        return {"ok": False,
                "detail": {"error": "no attempt chain to check",
                           "chain": chain}}
    store = CheckpointStore(NAMESPACE, root, root=ev["ckpt_root"])
    try:
        for prev, cur in zip(chain, chain[1:]):
            try:
                w_prev = int(prev.get("devices") or 0)
                w_new = int(cur.get("devices") or 0)
            except (TypeError, ValueError):
                continue
            if w_new == w_prev or w_new <= 0:
                continue
            step = cur.get("resumed_from_step")
            if step is None:
                problems.append({
                    "attempt": cur["attempt"],
                    "error": "no resumed_from_step recorded",
                })
                continue
            step = int(step)
            raw = store.restore_params(step)  # host bytes, old layout
            mesh = mesh_for_devices(jax.devices()[:w_new])
            spec = jax.sharding.NamedSharding(
                mesh, jax.sharding.PartitionSpec()
            )
            like = {"params": {
                k: jax.device_put(
                    jnp.zeros(np.shape(v), np.asarray(v).dtype), spec
                )
                for k, v in raw.items()
            }}
            out = store.restore_resharded(step, like)["params"]
            exact = all(
                np.array_equal(np.asarray(out[k]), np.asarray(raw[k]))
                for k in raw
            )
            boundaries.append({
                "step": step, "from_devices": w_prev,
                "to_devices": w_new, "cause": cur.get("cause"),
                "bit_exact": exact,
            })
            if not exact:
                problems.append({"attempt": cur["attempt"],
                                 "step": step, "error": "bytes differ"})
    finally:
        store.close()
    ok = bool(boundaries) and not problems
    return {
        "ok": ok,
        "detail": (
            f"{len(boundaries)} width change(s) each restored bit-exact"
            if ok else {"boundaries": boundaries, "problems": problems}
        ),
        "boundaries": boundaries,
    }


def check_grow_invariants(ev: dict) -> dict:
    """F1 no admitted job lost, F2 quotas never exceeded, F3 the grown
    run collapses to ONE history entry (Succeeded, grows >= 2, a
    shrink-back returned it to the launch width), F4 params bit-exact
    across every width change."""
    lost = []
    for cron, run in ev["runs"].items():
        chain = run["chain"]
        if not run["root"] or not chain \
                or chain[-1]["terminal"] != "Succeeded":
            lost.append({"cron": cron, "chain": chain})
    f1 = {
        "ok": not lost,
        "detail": (f"all {len(ev['runs'])} admitted runs completed"
                   if not lost else {"lost": lost}),
    }

    peaks = ev["fleet_stats"]["tenant_peak"]
    over = {
        t: {"peak": peaks.get(t, 0), "quota": q}
        for t, q in ev["quotas"].items()
        if peaks.get(t, 0) > q
    }
    f2 = {
        "ok": not over,
        "detail": (f"tenant peaks {peaks} within quotas {ev['quotas']}"
                   if not over else {"exceeded": over}),
    }

    run = ev["runs"].get(GROW_CRON) or {}
    chain = run.get("chain") or []
    hist = run.get("history") or []
    grows = sum(1 for a in chain if a.get("cause") == "grow")
    shrinks = sum(1 for a in chain if a.get("cause") == "shrink")
    # Every shrink-back attempt must return to the LAUNCH width (the
    # loaned chips go back whole). The chain may keep going after that —
    # the planner legitimately re-grows once the aggressor drains — so
    # the final width is not asserted, only the shrink semantics.
    shrink_widths = [
        int(a["devices"] or 0) for a in chain if a.get("cause") == "shrink"
    ]
    f3_ok = (
        len(hist) == 1
        and hist[0]["status"] == "Succeeded"
        and hist[0]["grows"] == grows >= 2
        and hist[0]["resumes"] == len(chain) - 1
        and shrinks >= 1
        and all(w == 2 for w in shrink_widths)
    )
    f3 = {
        "ok": f3_ok,
        "detail": (
            f"one Succeeded history entry: resumes={hist[0]['resumes']} "
            f"grows={hist[0]['grows']} shrinks={shrinks}, shrink-back "
            f"widths {shrink_widths}" if f3_ok
            else {"history": hist, "chain": chain}
        ),
    }

    return {
        "F1_no_admitted_job_lost": f1,
        "F2_quotas_never_exceeded": f2,
        "F3_grown_run_single_history": f3,
        "F4_bit_exact_across_width_changes": check_f4(ev),
    }


# ---------------------------------------------------------------------------
# multi-PROCESS leg: real OS processes, literal SIGKILL, lease failover
# ---------------------------------------------------------------------------


def _proc_cron(i: int) -> dict:
    # Far-future schedule: the process leg proves durability + failover
    # of the CONTROL PLANE; cron firings would make the expected surface
    # a moving target across kills (fired workloads have their own legs).
    return {
        "apiVersion": CRON_API_VERSION,
        "kind": "Cron",
        "metadata": {"name": f"proc-{i}", "namespace": NAMESPACE},
        "spec": {
            "schedule": "0 0 1 1 *",
            "concurrencyPolicy": POLICIES[i % len(POLICIES)],
            "template": {"workload": {
                "apiVersion": WORKLOAD_API_VERSION,
                "kind": WORKLOAD_KIND,
                "metadata": {},
                "spec": {"replicaSpecs": {"Worker": {"replicas": 1}}},
            }},
        },
    }


def run_process_soak(seed: int, n_crons: int, rounds: int, shards: int,
                     lease_ttl_s: float = 1.0,
                     failover_timeout_s: float = 30.0) -> dict:
    """SIGKILL a shard *process* mid-storm, every round.

    Spawns the real topology — one leader + one standby process per
    shard, one router process — then drives a CRUD storm through the
    router while a PRF-chosen victim shard's serving process gets a
    literal ``kill -9`` each round. The standby must self-promote on
    lease expiry (I6 checked against an independent on-disk WAL replay
    before it serves, from its ``promotion-*.json``), the storm's writes
    must survive via retry, and every generation that shuts down
    gracefully must prove I9 (audit ≡ WAL) in its ``audit-check`` file.

    Every standby also binds a follower read door (``--serve-reads``):
    it must keep serving bounded-stale lists through the dark window
    between ``kill -9`` and promotion, and after promotion the same
    door — now fronting the leader store in the promoted process — must
    agree exactly with the promoted front door.
    """
    import random
    import signal as _signal
    import subprocess
    import urllib.request

    from cron_operator_tpu.runtime.kube import (
        AlreadyExistsError,
        ApiError,
        ConflictError,
        NotFoundError,
    )
    from cron_operator_tpu.runtime.transport import ShardClient
    from cron_operator_tpu.runtime.shard import shard_index

    rng = random.Random(0x9E3779B9 ^ seed)
    data_dir = tempfile.mkdtemp(prefix="chaos-processes-")
    log_dir = os.path.join(data_dir, "logs")
    os.makedirs(log_dir)
    base = 21840 + (seed % 17) * 128
    t_start = time.time()

    def spawn(role_args: list, tag: str) -> subprocess.Popen:
        log = open(os.path.join(log_dir, f"{tag}.log"), "ab")
        return subprocess.Popen(
            [sys.executable, "-m", "cron_operator_tpu.cli.main", "start",
             "--health-probe-bind-address", "0",
             "--lease-ttl", str(lease_ttl_s)] + role_args,
            stdout=log, stderr=subprocess.STDOUT,
        )

    def spawn_leader(si: int) -> subprocess.Popen:
        return spawn([
            "--shard-role", "shard", "--shard-index", str(si),
            "--data-dir", data_dir,
            "--serve-api", f"127.0.0.1:{base + 1 + si}",
            "--ship-port", str(base + 64 + si),
        ], f"shard-{si}-leader")

    def read_door_port(si: int, gen: int) -> int:
        # A promoted standby keeps its read door bound for the rest of
        # the soak, so each generation needs its own door port; 16 per
        # shard covers any sane --rounds.
        return base + 96 + si * 16 + (gen % 16)

    def spawn_standby(si: int, gen: int) -> subprocess.Popen:
        return spawn([
            "--shard-role", "standby", "--shard-index", str(si),
            "--data-dir", data_dir,
            "--serve-api", f"127.0.0.1:{base + 1 + si}",
            "--ship-port", str(base + 64 + si),
            "--serve-reads", str(read_door_port(si, gen)),
        ], f"shard-{si}-standby-{gen}")

    def debug_doc(port: int, timeout: float = 1.0):
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/debug/shards",
                    timeout=timeout) as r:
                return json.loads(r.read())
        except Exception:
            return None

    def wait_serving(port: int, deadline_s: float):
        deadline = time.monotonic() + deadline_s
        while time.monotonic() < deadline:
            doc = debug_doc(port)
            if doc is not None:
                return doc
            time.sleep(0.05)
        return None

    serving: dict = {}   # shard -> its current serving Popen
    standbys: dict = {}  # shard -> its current standby Popen
    doors: dict = {}     # shard -> current standby's read-door port
    everyone: list = []
    for si in range(shards):
        serving[si] = spawn_leader(si)
        everyone.append(serving[si])
    for si in range(shards):
        doc = wait_serving(base + 1 + si, 30.0)
        assert doc is not None, f"shard {si} never served"
    for si in range(shards):
        standbys[si] = spawn_standby(si, 0)
        doors[si] = read_door_port(si, 0)
        everyone.append(standbys[si])
    router = spawn([
        "--shard-role", "router",
        "--serve-api", f"127.0.0.1:{base}",
        "--peers", ",".join(f"127.0.0.1:{base + 1 + si}"
                            for si in range(shards)),
    ], "router")
    everyone.append(router)
    assert wait_serving(base, 30.0) is not None, "router never served"

    for si in range(shards):
        assert wait_serving(doors[si], 30.0) is not None, (
            f"shard {si} standby read door never served")

    def door_names(port: int):
        """LIST at a follower read door; the door serves from its
        WAL-shipped replica with no leader round-trip."""
        c = ShardClient(f"http://127.0.0.1:{port}")
        try:
            return {o["metadata"]["name"]
                    for o in c.list(CRON_API_VERSION, "Cron")}
        except Exception:
            return None
        finally:
            c.close()

    client = ShardClient(f"http://127.0.0.1:{base}")
    expected: dict = {}  # name -> True (live crons by the storm's book)
    retried_ops = 0

    def storm_op(op: str, name: str) -> None:
        """One storm verb through the router, retried across the
        failover window. A retried CREATE observing AlreadyExists (or a
        retried DELETE observing NotFound) means the first attempt
        committed before the kill — success, not an error."""
        nonlocal retried_ops
        deadline = time.monotonic() + failover_timeout_s
        attempt = 0
        while True:
            try:
                if op == "create":
                    client.create(_proc_cron_named(name))
                elif op == "delete":
                    client.delete(CRON_API_VERSION, "Cron", NAMESPACE, name)
                else:  # update
                    cur = client.get(CRON_API_VERSION, "Cron", NAMESPACE,
                                     name)
                    labels = dict((cur["metadata"].get("labels") or {}))
                    labels["chaos"] = f"round-{attempt}"
                    cur["metadata"]["labels"] = labels
                    client.update(cur)
                return
            except AlreadyExistsError:
                if op == "create":
                    return  # first attempt committed before the kill
                raise
            except NotFoundError:
                if op in ("delete", "update"):
                    return  # delete committed / update target deleted
                raise
            except ConflictError:
                pass  # re-read and retry
            except (ApiError, OSError):
                if time.monotonic() >= deadline:
                    raise
            attempt += 1
            retried_ops += 1
            time.sleep(0.1)

    def _proc_cron_named(name: str) -> dict:
        doc = _proc_cron(0)
        doc["metadata"]["name"] = name
        return doc

    for i in range(n_crons):
        name = f"proc-{i}"
        storm_op("create", name)
        expected[name] = True

    next_id = n_crons
    kills = []
    try:
        for r in range(rounds):
            victim = rng.randrange(shards)
            ops = []
            for _ in range(24):
                verb = rng.random()
                live = [n for n, ok in expected.items() if ok]
                if verb < 0.5 or not live:
                    ops.append(("create", f"proc-{next_id}"))
                    next_id += 1
                elif verb < 0.75:
                    ops.append(("delete", rng.choice(live)))
                    # mirror the book immediately so later ops this
                    # round don't double-delete
                    expected[ops[-1][1]] = False
                else:
                    ops.append(("update", rng.choice(live)))
            for op, name in ops[:12]:
                storm_op(op, name)
                if op == "create":
                    expected[name] = True

            # Mid-storm: SIGKILL the victim shard's serving process.
            doc = debug_doc(base + 1 + victim, timeout=2.0)
            assert doc is not None, f"round {r}: victim {victim} not up"
            victim_pid = doc["pid"]
            os.kill(victim_pid, _signal.SIGKILL)
            t_kill = time.monotonic()
            serving[victim].wait(timeout=10)

            # Dark window: the leader is gone, promotion has not landed
            # yet — the victim's follower read door must keep serving
            # (bounded-stale) lists from its replica the whole time.
            dark_reads = door_names(doors[victim])

            # The storm keeps going while the standby promotes: writes
            # to other shards proceed; victim-shard writes retry.
            for op, name in ops[12:]:
                storm_op(op, name)
                if op == "create":
                    expected[name] = True

            doc = wait_serving(base + 1 + victim, failover_timeout_s)
            failover_s = time.monotonic() - t_kill
            assert doc is not None, (
                f"round {r}: shard {victim} never failed over")
            promoted_pid = doc["pid"]
            assert promoted_pid == standbys[victim].pid, (
                f"round {r}: serving pid {promoted_pid} is not the "
                f"standby {standbys[victim].pid}")

            # The standby's I6 verdict, written before it served.
            prom_path = os.path.join(
                data_dir, f"shard-{victim}",
                f"promotion-{promoted_pid}.json")
            with open(prom_path) as f:
                promotion = json.load(f)

            # The read door the promoted standby brought with it now
            # fronts the LEADER store (same process, same port) — it
            # must still serve, and must agree exactly with the
            # promoted front door at this quiet instant.
            promoted_door = doors[victim]
            door_after = door_names(promoted_door)
            leader_after = door_names(base + 1 + victim)
            door = {
                "port": promoted_door,
                "dark_window_reads": (len(dark_reads)
                                      if dark_reads is not None else None),
                "dark_window_ok": dark_reads is not None,
                "survived_promotion": door_after is not None,
                "matches_promoted_leader": (
                    door_after is not None and door_after == leader_after),
            }

            # The promoted process is the new leader; arm a fresh
            # standby behind it (spawned only now — two armed standbys
            # would race each other to the same ports).
            serving[victim] = standbys[victim]
            standbys[victim] = spawn_standby(victim, r + 1)
            doors[victim] = read_door_port(victim, r + 1)
            everyone.append(standbys[victim])
            assert wait_serving(doors[victim], 30.0) is not None, (
                f"round {r}: fresh standby read door never served")

            kills.append({
                "round": r,
                "shard": victim,
                "victim_pid": victim_pid,
                "promoted_pid": promoted_pid,
                "failover_s": round(failover_s, 3),
                "promotion_s": round(promotion["duration_s"], 3),
                "i6_ok": bool(promotion["i6_ok"]),
                "replica_matched_socket": bool(
                    promotion["replica_matched_socket"]),
                "objects": promotion["objects"],
                "rv": promotion["rv"],
                "read_door": door,
            })
            print(
                f"  round {r}: SIGKILL shard {victim} pid {victim_pid} "
                f"-> promoted pid {promoted_pid} in {failover_s:.2f}s "
                f"(i6_ok={promotion['i6_ok']}, "
                f"door dark_ok={door['dark_window_ok']} "
                f"post_ok={door['matches_promoted_leader']})",
                flush=True,
            )

        # Surface check: the storm's book vs the store, through the
        # router, after every kill (retries make writes exactly-once at
        # this surface, so the sets must match exactly).
        want = {n for n, ok in expected.items() if ok}
        got = {o["metadata"]["name"]
               for o in client.list(CRON_API_VERSION, "Cron")}
        surface = {
            "expected": len(want),
            "found": len(got),
            "missing": sorted(want - got)[:10],
            "extra": sorted(got - want)[:10],
            "ok": got == want,
        }

        # Per-shard split (each shard's own front door), for the report.
        split = {}
        for si in range(shards):
            c = ShardClient(f"http://127.0.0.1:{base + 1 + si}")
            try:
                split[si] = len(c.list(CRON_API_VERSION, "Cron"))
            finally:
                c.close()
        routed = {n: shard_index(NAMESPACE, n, shards) for n in want}
        split_ok = all(
            split[si] == sum(1 for s in routed.values() if s == si)
            for si in range(shards)
        )
    finally:
        client.close()
        # Graceful SIGTERM for everything still alive: each serving
        # generation writes its audit-check (I9) file on the way out.
        for p in everyone:
            if p.poll() is None:
                p.terminate()
        deadline = time.monotonic() + 20.0
        for p in everyone:
            try:
                p.wait(timeout=max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                p.kill()

    audit_checks = []
    for si in range(shards):
        sdir = os.path.join(data_dir, f"shard-{si}")
        for fn in sorted(os.listdir(sdir)):
            if fn.startswith("audit-check-"):
                with open(os.path.join(sdir, fn)) as f:
                    doc = json.load(f)
                audit_checks.append({
                    "shard": si, "file": fn, "ok": bool(doc["ok"]),
                    "audited_records": doc["audited_records"],
                    "wal_records_appended": doc["wal_records_appended"],
                })

    shutil.rmtree(data_dir, ignore_errors=True)
    return {
        "mode": "processes",
        "shards": shards,
        "lease_ttl_s": lease_ttl_s,
        "port_base": base,
        "kills": kills,
        "retried_ops": retried_ops,
        "surface": surface,
        "per_shard_objects": split,
        "shard_split_matches_hash": split_ok,
        "audit_checks": audit_checks,
        "elapsed_s": round(time.time() - t_start, 1),
    }


def check_process_invariants(ev: dict) -> dict:
    """I6/I9 and the storm-surface checks for the process leg."""
    kills = ev["kills"]
    bad_i6 = [k for k in kills if not k["i6_ok"]]
    i6 = {
        "ok": bool(kills) and not bad_i6,
        "detail": (
            f"{len(kills)} SIGKILL round(s): every promoted standby "
            "matched an independent replay of the on-disk WAL before "
            "serving" if kills and not bad_i6
            else {"kill_rounds": len(kills), "failed": bad_i6}
        ),
    }
    checks = ev["audit_checks"]
    bad_i9 = [c for c in checks if not c["ok"]]
    i9 = {
        "ok": bool(checks) and not bad_i9,
        "detail": (
            f"{len(checks)} surviving generation(s) proved audit ≡ WAL "
            "at graceful shutdown (SIGKILLed generations die with their "
            "journals, by design)" if checks and not bad_i9
            else {"checks": len(checks), "failed": bad_i9}
        ),
    }
    surface = {
        "ok": ev["surface"]["ok"] and ev["shard_split_matches_hash"],
        "detail": (
            f"storm book == routed surface ({ev['surface']['found']} "
            "cron(s)) and per-shard split matches the hash"
            if ev["surface"]["ok"] and ev["shard_split_matches_hash"]
            else {"surface": ev["surface"],
                  "split": ev["per_shard_objects"]}
        ),
    }
    failovers = [k["failover_s"] for k in kills]
    bounded = {
        "ok": bool(failovers) and max(failovers) < 15.0,
        "detail": {
            "failover_s": failovers,
            "max_s": max(failovers) if failovers else None,
            "bound_s": 15.0,
        },
    }
    door_rounds = [k.get("read_door") or {} for k in kills]
    bad_doors = [
        {"round": k["round"], "door": d}
        for k, d in zip(kills, door_rounds)
        if not (d.get("dark_window_ok") and d.get("survived_promotion")
                and d.get("matches_promoted_leader"))
    ]
    follower_reads = {
        "ok": bool(kills) and not bad_doors,
        "detail": (
            f"{len(kills)} round(s): every standby read door served "
            "through the kill->promotion dark window and, post-"
            "promotion, agreed exactly with the promoted front door"
            if kills and not bad_doors
            else {"kill_rounds": len(kills), "failed": bad_doors}
        ),
    }
    return {
        "I6_recovered_equals_wal_replay": i6,
        "I9_audit_equals_wal": i9,
        "surface_consistent": surface,
        "failover_bounded": bounded,
        "follower_reads_across_promotion": follower_reads,
    }


# ---------------------------------------------------------------------------
# live-split leg: keyspace splits under a write storm (I6/I9/I10 + S1/S2)
# ---------------------------------------------------------------------------

def run_split_soak(seed: int, n_crons: int, rounds: int,
                   fencing: bool = True) -> dict:
    """Live shard splits under a write storm (``--split``): start at ONE
    boot shard, split the hottest shard every round while closed-loop
    writer threads keep creating and patching through the router, and
    prove the handoff invariants each time:

    - **I6** (split edition): at cutover the child store must equal an
      independent *filtered* replay of the parent's WAL (checked inside
      ``split_shard``).
    - **I9**: audit ≡ WAL record-for-record per shard, including the
      shard whose persistence is SIGKILLed mid-split.
    - **I10**: a byte-level scan of every shard dir for
      stale-generation records (the fence bumps the parent's
      generation; no demoted-range write may land after it).
    - **S1 exactly-one-owner**: after every round — and after a
      parent-kill-mid-split crash restart — every acked key is readable
      on the shard the ownership map names and NOWHERE else.
    - **S2 no-acked-write-lost**: the storm goes through the router
      (which retries ``WrongShardError`` refusals), so zero
      client-visible errors and zero acked-then-vanished writes.

    One PRF-chosen round kills the parent's durability layer INSIDE the
    dark window: the split must abort cleanly and a full restart from
    disk must resolve to exactly one owner per key (the map on disk is
    the commit point — whichever side of the rename the crash landed
    on, no key may be served twice or not at all).

    ``fencing=False`` is the counter-proof: the dark window writes one
    poison record straight at the demoted parent; without the range
    fence the parent ACKS it, the detached child never sees it, and the
    eviction erases it — an acked write demonstrably lost from the
    routed surface (use with ``--expect-violation``).
    """
    from cron_operator_tpu.runtime.kube import AlreadyExistsError
    from cron_operator_tpu.runtime.faults import seeded_fraction
    from cron_operator_tpu.runtime.shard import ShardedControlPlane
    from cron_operator_tpu.telemetry.audit import AuditJournal

    data_dir = tempfile.mkdtemp(prefix="chaos-soak-split-")
    t0 = time.monotonic()
    journal = AuditJournal()
    plane = ShardedControlPlane(n_shards=1, data_dir=data_dir,
                                flush_interval_s=0, audit=journal)
    gvk = (CRON_API_VERSION, "Cron")
    acked: list = []
    storm_errors: list = []
    splits: list = []
    ownership_checks: list = []
    audit_checks: list = []
    kill_evidence: dict = {}
    poison: dict = {}
    kill_round = int(seeded_fraction(seed, "splitkill") * rounds)

    for i in range(n_crons):
        plane.router.create(_cron(i))
        acked.append(f"chaos-{i}")
    for s in plane.shards:
        s.persistence.flush()

    def _storm(r: int, t: int, stop: threading.Event) -> None:
        i = 0
        while not stop.is_set():
            name = f"storm-{r}-{t}-{i}"
            try:
                plane.router.create(_cron(0) | {
                    "metadata": {"name": name, "namespace": NAMESPACE},
                })
                acked.append(name)
                # every third write also exercises the update path on a
                # key that may be mid-move
                if i % 3 == 0:
                    plane.router.patch_status(
                        *gvk, NAMESPACE, name, {"round": r})
            except Exception as exc:
                # Client-visible failure. Expected ONLY in the kill
                # round, where the parent's durability layer is dead by
                # design — everywhere else this is an S2 violation.
                storm_errors.append({"round": r, "name": name,
                                     "error": repr(exc)})
            i += 1
            time.sleep(0.001)

    def _check_ownership(tag: str) -> dict:
        lost, doubled = [], []
        for name in acked:
            owner = plane.ownership.owner(NAMESPACE, name)
            if plane.shards[owner].store.get_frozen(
                    *gvk, NAMESPACE, name) is None:
                lost.append(name)
            for s in plane.shards:
                if s.index != owner and s.store.get_frozen(
                        *gvk, NAMESPACE, name) is not None:
                    doubled.append(name)
        check = {"tag": tag, "n_shards": plane.n_shards,
                 "keys": len(acked), "lost": lost[:5],
                 "lost_total": len(lost), "doubled": doubled[:5],
                 "doubled_total": len(doubled)}
        ownership_checks.append(check)
        return check

    def _hottest() -> int:
        return max(plane.shards, key=lambda s: len(s.store)).index

    try:
        for r in range(rounds):
            stop = threading.Event()
            threads = [
                threading.Thread(target=_storm, args=(r, t, stop),
                                 daemon=True)
                for t in range(2)
            ]
            for t in threads:
                t.start()
            time.sleep(0.3)
            parent = _hottest()
            if r == kill_round:
                # SIGKILL-the-parent analog: the durability layer dies
                # inside the dark window, after the fence is armed.
                def _kill(plan):
                    plane.shards[parent].persistence.kill(
                        f"mid-split/{r}")

                err = None
                try:
                    plane.split_shard(parent, fence=fencing,
                                      dark_window_hook=_kill)
                except Exception as exc:
                    err = repr(exc)
                stop.set()
                for t in threads:
                    t.join(timeout=30.0)
                # Dying generation's I9 verdict (crash_tail covers a
                # record on disk whose verb never committed).
                for s in plane.shards:
                    if s.persistence is not None:
                        audit_checks.append({
                            "round": r, "shard": s.index,
                            **journal.wal_check(
                                s.persistence.records_appended,
                                shard=s.index, crash_tail=1),
                        })
                n_before = plane.n_shards
                plane.close()
                # Full restart from disk: whichever side of the commit
                # rename the crash landed on, the map decides ownership.
                journal = AuditJournal()
                plane = ShardedControlPlane(
                    n_shards=1, data_dir=data_dir,
                    flush_interval_s=0, audit=journal)
                # The storm races the kill, so writes acked after the
                # last flush may not be durable — drop those from the
                # acked book (the single-store soak's suffix-loss
                # semantics), then require exactly-one-owner for all
                # DURABLE acks.
                durable = [
                    n for n in acked
                    if any(s.store.get_frozen(*gvk, NAMESPACE, n)
                           is not None for s in plane.shards)
                ]
                suffix_lost = len(acked) - len(durable)
                acked[:] = durable
                check = _check_ownership(f"restart-after-kill/{r}")
                kill_evidence = {
                    "round": r,
                    "parent": parent,
                    "split_error": err,
                    "aborted_cleanly": err is not None,
                    "n_shards_before_restart": n_before,
                    "n_shards_after_restart": plane.n_shards,
                    "map_epoch_after_restart": plane.ownership.epoch,
                    "storm_suffix_lost": suffix_lost,
                    "one_owner_after_restart":
                        check["lost_total"] == 0
                        and check["doubled_total"] == 0,
                }
                continue
            if not fencing and not poison:
                name = None

                def _poison(plan):
                    # find a moved-range name and write it straight at
                    # the demoted parent — no fence, so it ACKS
                    from cron_operator_tpu.runtime.shard import (
                        split_pred,
                    )
                    nonlocal name
                    pred = split_pred(plan)
                    j = 0
                    while not pred(NAMESPACE, f"poison-{j}"):
                        j += 1
                    name = f"poison-{j}"
                    plane.shards[plan["parent"]].store.create(_cron(0) | {
                        "metadata": {"name": name,
                                     "namespace": NAMESPACE},
                    })

                report = plane.split_shard(parent, fence=False,
                                           dark_window_hook=_poison)
                stop.set()
                for t in threads:
                    t.join(timeout=30.0)
                poison.update({
                    "round": r,
                    "name": name,
                    "acked": True,
                    "visible_after": plane.router.try_get(
                        *gvk, NAMESPACE, name) is not None,
                })
            else:
                report = plane.split_shard(parent, fence=fencing)
                stop.set()
                for t in threads:
                    t.join(timeout=30.0)
            for s in plane.shards:
                s.persistence.flush()
            splits.append({
                "round": r,
                "parent": report["parent"],
                "child": report["child"],
                "epoch": report["epoch"],
                "moved": report["moved"],
                "i6_child_equals_filtered_replay": report["i6_ok"],
                "fenced": report["fenced"],
                "dark_window_s": round(report["dark_window_s"], 4),
                "records_shipped": report["records_shipped"],
                "records_filtered": report["records_filtered"],
                "wrong_shard_retries": plane.router.wrong_shard_retries,
            })
            _check_ownership(f"post-split/{r}")

        # clean end: I9 per surviving shard, I10 scan per shard dir
        for s in plane.shards:
            if s.persistence is not None:
                audit_checks.append({
                    "round": rounds, "shard": s.index,
                    **journal.wal_check(
                        s.persistence.records_appended, shard=s.index,
                        crash_tail=0),
                })
        for s in plane.shards:
            s.persistence.flush()
        wal_scans = {
            str(s.index): _scan_stale_generations(s.data_dir)
            for s in plane.shards
        }
        debug = plane.debug_shards()
    finally:
        plane.close()
        shutil.rmtree(data_dir, ignore_errors=True)

    return {
        "seed": seed,
        "fencing": fencing,
        "rounds": rounds,
        "kill_round": kill_round,
        "elapsed_s": round(time.monotonic() - t0, 2),
        "n_shards_final": debug["n_shards"],
        "map_epoch_final": debug["ownership"]["epoch"],
        "acked_writes": len(acked),
        "storm_errors": storm_errors[:5],
        "storm_errors_total": len(storm_errors),
        "storm_errors_outside_kill_round": len(
            [e for e in storm_errors if e["round"] != kill_round]),
        "splits": splits,
        "ownership_checks": ownership_checks,
        "kill_mid_split": kill_evidence,
        "poison": poison,
        "audit_checks": audit_checks,
        "wal_scans": wal_scans,
        "debug_shards": debug,
    }


def check_split_invariants(ev: dict) -> dict:
    """I6/I9/I10 plus the split-specific S1/S2 for the live-split leg."""
    splits = ev.get("splits") or []
    i6_bad = [s["round"] for s in splits
              if not s["i6_child_equals_filtered_replay"]]
    i6 = {
        "ok": not i6_bad and bool(splits),
        "detail": (f"{len(splits)} live splits, child ≡ filtered WAL "
                   f"replay at every cutover"
                   if splits and not i6_bad else
                   f"violations in rounds {i6_bad}" if i6_bad else
                   "no splits ran"),
    }
    bad_audit = [a for a in ev.get("audit_checks", []) if not a["ok"]]
    i9 = {
        "ok": not bad_audit and bool(ev.get("audit_checks")),
        "detail": (f"{len(ev.get('audit_checks', []))} audit≡WAL checks "
                   f"across split handoffs and the mid-split kill"
                   if not bad_audit else f"failed: {bad_audit[:2]}"),
    }
    scans = ev.get("wal_scans") or {}
    stale = {si: s for si, s in scans.items()
             if s["stale_records"] or s["corrupt_lines"]}
    i10 = {
        "ok": not stale and bool(scans),
        "detail": (f"{len(scans)} shard dirs scanned, zero "
                   f"stale-generation bytes"
                   if not stale else f"stale bytes: {stale}"),
    }
    bad_own = [c for c in ev.get("ownership_checks", [])
               if c["lost_total"] or c["doubled_total"]]
    kill = ev.get("kill_mid_split") or {}
    s1 = {
        "ok": (not bad_own and bool(ev.get("ownership_checks"))
               and kill.get("one_owner_after_restart", False)),
        "detail": (f"{len(ev.get('ownership_checks', []))} "
                   f"exactly-one-owner sweeps over "
                   f"{ev.get('acked_writes')} keys (incl. restart after "
                   f"the round-{kill.get('round')} mid-split kill)"
                   if not bad_own and kill.get("one_owner_after_restart")
                   else f"violations: {bad_own[:2]} kill={kill}"),
    }
    poison = ev.get("poison") or {}
    poison_lost = bool(poison) and not poison.get("visible_after", True)
    errs = ev.get("storm_errors_outside_kill_round", 1)
    s2 = {
        "ok": errs == 0 and not poison_lost,
        "detail": (f"{ev.get('acked_writes')} storm-acked writes, zero "
                   f"client-visible errors outside the kill round, zero "
                   f"acked-then-lost"
                   if errs == 0 and not poison_lost else
                   f"errors={ev.get('storm_errors')} "
                   f"poison_lost={poison_lost} ({poison.get('name')})"),
    }
    invariants = {
        "I6_child_equals_filtered_replay": i6,
        "I9_audit_equals_wal": i9,
        "I10_no_stale_generation_writes": i10,
        "S1_exactly_one_owner": s1,
        "S2_no_acked_write_lost": s2,
    }
    return {
        "invariants": invariants,
        "ok": all(v["ok"] for v in invariants.values()),
    }


# ---------------------------------------------------------------------------
# gray-failure leg: SIGSTOP zombies, fencing (I10), breakers, hangs (I11)
# ---------------------------------------------------------------------------

#: Healthy-shard p99 bound while a neighbor shard is SIGSTOPped behind an
#: OPEN breaker — the fail-fast guarantee the breaker exists to give.
GRAY_P99_BOUND_S = 1.0
#: Once the breaker is open, a request to the wedged shard must fail in
#: well under the wire timeout (no connection is even attempted).
GRAY_FAILFAST_BOUND_S = 0.25
#: Hang-leg watchdog floor: tight so the soak proves detection latency,
#: wide enough that paced-but-healthy steps (0.05s) never false-trip.
GRAY_WATCHDOG_FLOOR_S = 2.0
#: PRF fraction of in-flight runs wedged per hang round.
GRAY_HANG_FRAC = 0.6
#: Detection-latency slack over the watchdog budget (poll quantum + the
#: entrypoint reaching its next step boundary + status write).
GRAY_DETECT_SLACK_S = 1.5


def _scan_stale_generations(sdir: str) -> dict:
    """Independent on-disk evidence for I10: read the shard's snapshot +
    WAL and count records stamped with a generation OLDER than the
    highest generation the dir has seen. With fencing there must be
    zero; the --no-fencing counter-proof expects the zombie's poison
    write to show up here."""
    snap_gen = 0
    try:
        with open(os.path.join(sdir, "snapshot.json")) as f:
            snap_gen = int((json.load(f) or {}).get("generation") or 0)
    except (OSError, ValueError):
        pass
    gens: list = []
    recs: list = []
    corrupt = 0
    try:
        with open(os.path.join(sdir, "wal.jsonl"), "rb") as f:
            for raw in f.read().split(b"\n"):
                # A demoted writer without O_APPEND lands bytes at its own
                # stale offset: the kernel zero-fills the gap, so the
                # foreign record hides behind a NUL run on the same line.
                line = raw.replace(b"\x00", b" ").strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    corrupt += 1  # overwritten/interleaved bytes — also
                    continue      # evidence of a non-owner writer
                g = int(rec.get("gen") or 0)
                gens.append(g)
                recs.append(rec)
    except OSError:
        pass
    max_gen = max([snap_gen] + gens) if (gens or snap_gen) else 0
    stale = [
        {"gen": g, "op": r.get("op"), "rv": r.get("rv")}
        for g, r in zip(gens, recs) if g < max_gen
    ]
    return {
        "snapshot_generation": snap_gen,
        "wal_generations": sorted(set(gens)),
        "max_generation": max_gen,
        "stale_records": len(stale),
        "corrupt_lines": corrupt,
        "stale_sample": stale[:3],
    }


def run_gray_soak(seed: int, rounds: int, fencing: bool = True,
                  lease_ttl_s: float = 1.0,
                  hang_jobs: int = 2, hang_rounds: int = 4) -> dict:
    """The gray-failure leg: failures that leave the process ALIVE.

    Three scenarios, one report:

    A. **Fencing (I10)** — per round, spawn one shard leader + standby,
       ``SIGSTOP`` the leader past its lease TTL so the standby promotes
       (onto alternate ports — the zombie still holds its sockets), then
       ``SIGCONT`` the zombie and send it a poison write. With fencing
       the demoted zombie's persistence is fenced before the write
       arrives, so the write fails closed and an independent disk scan
       finds ZERO stale-generation records. ``fencing=False`` is the
       counter-proof: the poison write lands in the shared WAL inode.

    B. **Breakers** — two shard leaders behind a breaker-enabled router
       with a tight wire timeout; SIGSTOP one shard mid-traffic. The
       victim's breaker must trip open (fail-fast), healthy-shard p99
       must stay bounded, and after SIGCONT the half-open probe must
       close the breaker again.

    C. **Hangs (I11)** — in-process elastic training runs get their step
       loop cooperatively wedged (``FaultInjector.inject_hang``); the
       executor's watchdog must detect each within its budget and route
       the gang through the preempt → elastic-resume chain so every run
       still finishes at its step target in ONE history entry.
    """
    import signal as _signal
    import subprocess
    import urllib.request

    from cron_operator_tpu.runtime.transport import ShardClient

    t_start = time.time()
    base = 24480 + (seed % 17) * 64

    def debug_doc(port: int, timeout: float = 1.0):
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/debug/shards",
                    timeout=timeout) as r:
                return json.loads(r.read())
        except Exception:
            return None

    def shard0_doc(port: int, timeout: float = 1.0):
        doc = debug_doc(port, timeout)
        if doc is None:
            return None
        shards = doc.get("shards") or []
        return shards[0] if shards else None

    def wait_serving(port: int, deadline_s: float):
        deadline = time.monotonic() + deadline_s
        while time.monotonic() < deadline:
            doc = shard0_doc(port)
            if doc is not None:
                return doc
            time.sleep(0.05)
        return None

    def terminate_all(procs) -> None:
        for p in procs:
            if p.poll() is None:
                try:
                    p.send_signal(_signal.SIGCONT)  # never TERM a STOPPED pid
                except OSError:
                    pass
                p.terminate()
        deadline = time.monotonic() + 20.0
        for p in procs:
            try:
                p.wait(timeout=max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                p.kill()

    # ---- scenario A: SIGSTOP the leader, fence the zombie (I10) ----------
    fence_flag = [] if fencing else ["--no-fencing"]
    fencing_rounds: list = []
    for r in range(rounds):
        data_dir = tempfile.mkdtemp(prefix=f"chaos-gray-fence-{r}-")
        log_dir = os.path.join(data_dir, "logs")
        os.makedirs(log_dir)
        api = base + r * 4
        ship = api + 1
        papi = api + 2
        pship = api + 3

        def spawn(role_args: list, tag: str) -> subprocess.Popen:
            log = open(os.path.join(log_dir, f"{tag}.log"), "ab")
            return subprocess.Popen(
                [sys.executable, "-m", "cron_operator_tpu.cli.main",
                 "start", "--health-probe-bind-address", "0",
                 "--lease-ttl", str(lease_ttl_s)] + role_args,
                stdout=log, stderr=subprocess.STDOUT,
            )

        procs: list = []
        round_ev: dict = {"round": r}
        try:
            leader = spawn([
                "--shard-role", "shard", "--shard-index", "0",
                "--data-dir", data_dir,
                "--serve-api", f"127.0.0.1:{api}",
                "--ship-port", str(ship),
            ] + fence_flag, "leader")
            procs.append(leader)
            doc = wait_serving(api, 30.0)
            assert doc is not None, f"gray round {r}: leader never served"
            leader_pid = doc["pid"]
            round_ev["leader_generation"] = doc.get("generation")

            client = ShardClient(f"http://127.0.0.1:{api}")
            try:
                for i in range(6):
                    c = _proc_cron(0)
                    c["metadata"]["name"] = f"gray-{r}-{i}"
                    client.create(c)
            finally:
                client.close()

            standby = spawn([
                "--shard-role", "standby", "--shard-index", "0",
                "--data-dir", data_dir,
                "--serve-api", f"127.0.0.1:{api}",
                "--ship-port", str(ship),
                "--promote-api-port", str(papi),
                "--promote-ship-port", str(pship),
            ] + fence_flag, "standby")
            procs.append(standby)
            time.sleep(max(0.5, lease_ttl_s / 2))  # let it bootstrap

            # The gray failure: the leader is STOPPED, not killed. Its
            # sockets stay bound, its lease goes stale, and — crucially —
            # it will wake up later believing it is still the leader.
            os.kill(leader_pid, _signal.SIGSTOP)
            t_stop = time.monotonic()
            pdoc = wait_serving(papi, 30.0)
            failover_s = time.monotonic() - t_stop
            assert pdoc is not None, (
                f"gray round {r}: standby never promoted")
            promoted_gen = int(pdoc.get("generation") or 0)
            round_ev.update({
                "failover_s": round(failover_s, 3),
                "promoted_generation": promoted_gen,
                "promoted_pid": pdoc.get("pid"),
            })

            # New-epoch writes through the promoted leader, so the WAL
            # scan has generation-N records to compare the zombie's
            # stale-epoch bytes against.
            pclient = ShardClient(f"http://127.0.0.1:{papi}")
            try:
                for i in range(2):
                    c = _proc_cron(0)
                    c["metadata"]["name"] = f"gray-{r}-post-{i}"
                    pclient.create(c)
            finally:
                pclient.close()

            # Wake the zombie. Its heartbeat deadline lapsed during the
            # STOP, so the next renew observes the promoted generation
            # and self-demotes (and, with fencing, fences persistence).
            t_cont = time.monotonic()
            os.kill(leader_pid, _signal.SIGCONT)
            want_key = "fenced" if fencing else "lease_lost"
            zdoc = None
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                zdoc = shard0_doc(api)
                if zdoc is not None and zdoc.get(want_key):
                    break
                time.sleep(0.05)
            assert zdoc is not None and zdoc.get(want_key), (
                f"gray round {r}: zombie never observed demotion "
                f"({want_key}); doc={zdoc}")
            round_ev["demote_latency_s"] = round(
                time.monotonic() - t_cont, 3)
            round_ev["zombie_fenced"] = bool(zdoc.get("fenced"))

            # The poison write: the zombie's front door is still up on
            # the OLD port. Fenced, the append dies before the commit;
            # unfenced, it lands in the WAL inode the promoted leader
            # now owns — the split-brain byte I10 forbids.
            zc = ShardClient(f"http://127.0.0.1:{api}")
            poison_error = None
            try:
                c = _proc_cron(0)
                c["metadata"]["name"] = f"poison-{r}"
                zc.create(c)
            except Exception as err:  # noqa: BLE001 — the refusal IS data
                poison_error = f"{type(err).__name__}: {err}"
            finally:
                zc.close()
            round_ev["poison_refused"] = poison_error is not None
            round_ev["poison_error"] = poison_error
            zdoc = shard0_doc(api)
            round_ev["zombie_fenced_appends"] = int(
                (zdoc or {}).get("fenced_appends") or 0)

            # Disk scan BEFORE teardown: the promoted leader's graceful
            # close would compact the WAL and destroy the counter-proof
            # evidence.
            round_ev["wal_scan"] = _scan_stale_generations(
                os.path.join(data_dir, "shard-0"))
            print(
                f"  gray round {r}: SIGSTOP pid {leader_pid} -> promoted "
                f"gen {promoted_gen} in {failover_s:.2f}s; zombie "
                f"{'FENCED' if round_ev['zombie_fenced'] else 'unfenced'}, "
                f"poison {'refused' if round_ev['poison_refused'] else 'LANDED'}, "
                f"stale_records={round_ev['wal_scan']['stale_records']} "
                f"corrupt_lines={round_ev['wal_scan']['corrupt_lines']}",
                flush=True,
            )
        finally:
            terminate_all(procs)
            shutil.rmtree(data_dir, ignore_errors=True)
        fencing_rounds.append(round_ev)

    # ---- scenario B: SIGSTOP one shard behind a breaker router -----------
    breaker_ev: dict = {}
    if fencing:
        from cron_operator_tpu.runtime.shard import shard_index

        data_dir = tempfile.mkdtemp(prefix="chaos-gray-breaker-")
        log_dir = os.path.join(data_dir, "logs")
        os.makedirs(log_dir)
        b = base + 40
        api = {0: b, 1: b + 1}
        ships = {0: b + 2, 1: b + 3}
        rport = b + 4

        def spawn_b(role_args: list, tag: str) -> subprocess.Popen:
            log = open(os.path.join(log_dir, f"{tag}.log"), "ab")
            return subprocess.Popen(
                [sys.executable, "-m", "cron_operator_tpu.cli.main",
                 "start", "--health-probe-bind-address", "0",
                 "--lease-ttl", str(lease_ttl_s)] + role_args,
                stdout=log, stderr=subprocess.STDOUT,
            )

        procs = []
        try:
            for si in (0, 1):
                procs.append(spawn_b([
                    "--shard-role", "shard", "--shard-index", str(si),
                    "--data-dir", data_dir,
                    "--serve-api", f"127.0.0.1:{api[si]}",
                    "--ship-port", str(ships[si]),
                ], f"shard-{si}"))
            for si in (0, 1):
                assert wait_serving(api[si], 30.0) is not None, (
                    f"breaker leg: shard {si} never served")
            procs.append(spawn_b([
                "--shard-role", "router",
                "--serve-api", f"127.0.0.1:{rport}",
                "--peers", f"127.0.0.1:{api[0]},127.0.0.1:{api[1]}",
                "--router-timeout", "0.5",
            ], "router"))
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                if debug_doc(rport) is not None:
                    break
                time.sleep(0.05)
            assert debug_doc(rport) is not None, "router never served"

            client = ShardClient(f"http://127.0.0.1:{rport}")
            names = [f"gray-b-{i}" for i in range(24)]
            for n in names:
                c = _proc_cron(0)
                c["metadata"]["name"] = n
                client.create(c)
            by_shard = {0: [], 1: []}
            for n in names:
                by_shard[shard_index(NAMESPACE, n, 2)].append(n)
            assert by_shard[0] and by_shard[1], "hash put all on one shard"

            vdoc = shard0_doc(api[1])
            victim_pid = vdoc["pid"]
            os.kill(victim_pid, _signal.SIGSTOP)

            def router_breaker(si: int):
                doc = debug_doc(rport, timeout=3.0) or {}
                for entry in doc.get("shards") or []:
                    if entry.get("shard") == si:
                        return entry.get("breaker") or {}
                return {}

            # Trip it: requests to the wedged shard time out at the wire
            # (0.5s each) until the rolling error rate crosses the
            # threshold and the breaker opens.
            trip_latencies = []
            opened = False
            for n in (by_shard[1] * 3)[:20]:
                t0 = time.monotonic()
                try:
                    client.get(CRON_API_VERSION, "Cron", NAMESPACE, n)
                except Exception:  # noqa: BLE001 — timeouts are the point
                    pass
                trip_latencies.append(time.monotonic() - t0)
                if router_breaker(1).get("state") == "open":
                    opened = True
                    break
            breaker_ev["opened"] = opened
            breaker_ev["requests_to_open"] = len(trip_latencies)

            # Fail-fast + healthy-shard latency while the zombie shard
            # is still STOPPED behind the open breaker.
            healthy_lat = []
            for n in (by_shard[0] * 5)[:40]:
                t0 = time.monotonic()
                client.get(CRON_API_VERSION, "Cron", NAMESPACE, n)
                healthy_lat.append(time.monotonic() - t0)
            fast_lat = []
            for n in (by_shard[1] * 2)[:8]:
                t0 = time.monotonic()
                try:
                    client.get(CRON_API_VERSION, "Cron", NAMESPACE, n)
                except Exception:  # noqa: BLE001
                    pass
                fast_lat.append(time.monotonic() - t0)
            healthy_lat.sort()
            p99 = healthy_lat[int(0.99 * (len(healthy_lat) - 1))]
            breaker_ev.update({
                "healthy_p99_s": round(p99, 4),
                "healthy_p99_bound_s": GRAY_P99_BOUND_S,
                "failfast_max_s": round(max(fast_lat), 4) if fast_lat
                else None,
                "failfast_bound_s": GRAY_FAILFAST_BOUND_S,
                "open_breaker": router_breaker(1),
            })

            # Recovery: SIGCONT, cooldown passes, the half-open probe
            # succeeds and the breaker closes again.
            os.kill(victim_pid, _signal.SIGCONT)
            t_cont = time.monotonic()
            recovered = False
            deadline = time.monotonic() + 15.0
            while time.monotonic() < deadline:
                try:
                    client.get(CRON_API_VERSION, "Cron", NAMESPACE,
                               by_shard[1][0])
                    recovered = True
                    break
                except Exception:  # noqa: BLE001
                    time.sleep(0.2)
            breaker_ev["recovered"] = recovered
            breaker_ev["recovery_s"] = round(time.monotonic() - t_cont, 3)
            deadline = time.monotonic() + 10.0
            closed = False
            while time.monotonic() < deadline:
                if router_breaker(1).get("state") == "closed":
                    closed = True
                    break
                try:
                    client.get(CRON_API_VERSION, "Cron", NAMESPACE,
                               by_shard[1][0])
                except Exception:  # noqa: BLE001
                    pass
                time.sleep(0.1)
            breaker_ev["closed_after_recovery"] = closed
            client.close()
            print(
                f"  gray breaker: opened={opened} healthy_p99="
                f"{breaker_ev['healthy_p99_s']}s failfast_max="
                f"{breaker_ev['failfast_max_s']}s recovered={recovered} "
                f"closed={closed}",
                flush=True,
            )
        finally:
            terminate_all(procs)
            shutil.rmtree(data_dir, ignore_errors=True)

    # ---- scenario C: cooperative hangs vs the step watchdog (I11) --------
    hang_ev: dict = {}
    if fencing:
        hang_ev = run_hang_soak(seed, hang_jobs, hang_rounds)
        print(
            f"  gray hang leg: {len(hang_ev['hang_events'])} hang(s), "
            f"detected={sum(1 for e in hang_ev['hang_events'] if e['detected'])}, "
            f"latencies={[e['detection_latency_s'] for e in hang_ev['hang_events']]}",
            flush=True,
        )

    return {
        "mode": "gray",
        "fencing": fencing,
        "lease_ttl_s": lease_ttl_s,
        "port_base": base,
        "fencing_rounds": fencing_rounds,
        "breaker": breaker_ev,
        "hang": hang_ev,
        "elapsed_s": round(time.time() - t_start, 1),
    }


def run_hang_soak(seed: int, n_jobs: int, rounds: int,
                  train_timeout_s: float = 300.0) -> dict:
    """Scenario C of the gray leg: real in-process elastic training runs
    get their step loop cooperatively wedged — alive thread, silent step
    counter — and ONLY the executor's step watchdog may rescue them
    (``HangDetected`` → preempt → elastic resume). Scaffold mirrors
    :func:`run_preempt_soak`; the storm verb is ``inject_hang``."""
    from cron_operator_tpu.backends.local import LocalExecutor
    from cron_operator_tpu.controller.cron_controller import CronReconciler
    from cron_operator_tpu.runtime.faults import (
        FaultInjector,
        FaultPlan,
        seeded_fraction,
    )
    from cron_operator_tpu.runtime.kube import APIServer
    from cron_operator_tpu.runtime.manager import Metrics
    from cron_operator_tpu.utils.clock import FakeClock

    t0 = time.time()
    ckpt_root = tempfile.mkdtemp(prefix="chaos-gray-hang-ckpt-")
    clock = FakeClock()
    store = APIServer(clock=clock)
    metrics = Metrics()
    injector = FaultInjector(store, FaultPlan.quiet(seed))
    injector.instrument(metrics)
    ex = LocalExecutor(
        store, metrics=metrics, gang_slots=1,
        watchdog_floor_s=GRAY_WATCHDOG_FLOOR_S,
        watchdog_poll_s=0.1,
    )
    ex.start()
    rec = CronReconciler(store, metrics=metrics)

    steps_target = _elastic_steps(rounds)
    crons = [f"elastic-{i}" for i in range(n_jobs)]
    for i in range(n_jobs):
        store.create(_elastic_cron(i, ckpt_root, steps_target, True))

    def sweep():
        for name in crons:
            rec.reconcile(NAMESPACE, name)

    def latest_attempt(root: str) -> str:
        best, best_no = root, -1
        for w in store.list(
            WORKLOAD_API_VERSION, WORKLOAD_KIND, namespace=NAMESPACE
        ):
            meta = w.get("metadata") or {}
            ann = meta.get("annotations") or {}
            wroot = ann.get("tpu.kubedl.io/resume-of", meta.get("name", ""))
            if wroot != root:
                continue
            try:
                no = int(ann.get("tpu.kubedl.io/resume-attempt", 0))
            except (TypeError, ValueError):
                no = 0
            if no > best_no:
                best, best_no = meta.get("name", ""), no
        return best

    clock.advance(timedelta(seconds=61))
    sweep()
    roots = {}
    for w in store.list(
        WORKLOAD_API_VERSION, WORKLOAD_KIND, namespace=NAMESPACE
    ):
        meta = w.get("metadata") or {}
        cron = (meta.get("labels") or {}).get(LABEL_CRON_NAME, "")
        if cron:
            roots[cron] = meta.get("name", "")
    timeouts: list = []

    def wait_progress(job: str, floor: int, deadline: float) -> dict:
        while time.time() < deadline:
            obj = store.try_get(
                WORKLOAD_API_VERSION, WORKLOAD_KIND, NAMESPACE, job
            )
            if obj is None:
                return {}
            if _is_terminal(obj):
                return _progress(store, job)
            prog = _progress(store, job)
            if int(prog.get("steps_done") or 0) >= floor:
                return prog
            time.sleep(0.1)
        timeouts.append({"job": job, "waiting_for_step": floor})
        return _progress(store, job)

    events: list = []
    for r in range(rounds):
        floor = (ELASTIC_SAVE_EVERY + 2) * (r + 1)
        deadline = time.time() + train_timeout_s
        chosen = {
            cron: seeded_fraction(seed, "gray-hang", r, roots[cron])
            < GRAY_HANG_FRAC
            for cron in crons if roots.get(cron)
        }
        if chosen and not any(chosen.values()):
            chosen[next(iter(chosen))] = True
        for cron in crons:
            root = roots.get(cron)
            if not root:
                continue
            job = latest_attempt(root)
            pre = wait_progress(job, min(floor, steps_target - 2), deadline)
            obj = store.try_get(
                WORKLOAD_API_VERSION, WORKLOAD_KIND, NAMESPACE, job
            )
            if obj is None or _is_terminal(obj) or not chosen.get(cron):
                continue
            t_inject = time.time()
            if not injector.inject_hang(ex, NAMESPACE, job):
                continue  # finished under the injector — nothing to wedge
            # The ONLY exit is detection: wait for the watchdog's verdict
            # to land in status (the HangDetected extra), then for the
            # remediation preemption to make the attempt terminal.
            detect_deadline = time.time() + GRAY_WATCHDOG_FLOOR_S * 8 + 20
            hang_doc: dict = {}
            while time.time() < detect_deadline:
                obj = store.try_get(
                    WORKLOAD_API_VERSION, WORKLOAD_KIND, NAMESPACE, job
                )
                if obj is None:
                    break
                hang_doc = (obj.get("status") or {}).get("hang") or {}
                if hang_doc:
                    break
                time.sleep(0.05)
            while time.time() < detect_deadline:
                obj = store.try_get(
                    WORKLOAD_API_VERSION, WORKLOAD_KIND, NAMESPACE, job
                )
                if obj is None or _is_terminal(obj):
                    break
                time.sleep(0.05)
            events.append({
                "round": r,
                "cron": cron,
                "root": root,
                "job": job,
                "pre_steps": int(pre.get("steps_done") or 0),
                "detected": bool(hang_doc),
                "detection_latency_s": hang_doc.get(
                    "detectionLatencySeconds"),
                "budget_s": hang_doc.get("budgetSeconds"),
                "staleness_s": hang_doc.get("stalenessSeconds"),
                "wall_latency_s": round(time.time() - t_inject, 3),
            })
        sweep()
        ex.restore_capacity()

    deadline = time.time() + train_timeout_s
    for cron in crons:
        root = roots.get(cron)
        if not root:
            continue
        job = latest_attempt(root)
        while time.time() < deadline:
            obj = store.try_get(
                WORKLOAD_API_VERSION, WORKLOAD_KIND, NAMESPACE, job
            )
            if obj is None or _is_terminal(obj):
                nxt = latest_attempt(root)
                if nxt == job:
                    break
                job = nxt
                continue
            time.sleep(0.1)
        else:
            timeouts.append({"job": job, "waiting_for": "terminal"})
    sweep()
    ex.wait_idle(timeout=train_timeout_s)
    sweep()

    runs: dict = {}
    for cron in crons:
        root = roots.get(cron, "")
        chain: list = []
        for w in store.list(
            WORKLOAD_API_VERSION, WORKLOAD_KIND, namespace=NAMESPACE
        ):
            meta = w.get("metadata") or {}
            ann = meta.get("annotations") or {}
            wroot = ann.get("tpu.kubedl.io/resume-of", meta.get("name", ""))
            if wroot != root:
                continue
            try:
                no = int(ann.get("tpu.kubedl.io/resume-attempt", 0))
            except (TypeError, ValueError):
                no = 0
            prog = (w.get("status") or {}).get("trainingProgress") or {}
            chain.append({
                "attempt": no,
                "name": meta.get("name", ""),
                "terminal": _is_terminal(w),
                "resumed_from_step": prog.get("resumed_from_step"),
                "steps_done": int(prog.get("steps_done") or 0),
            })
        chain.sort(key=lambda a: a["attempt"])
        cron_obj = store.get(CRON_API_VERSION, "Cron", NAMESPACE, cron)
        hist = (cron_obj.get("status") or {}).get("history") or []
        runs[cron] = {
            "root": root,
            "chain": chain,
            "history": [
                {
                    "name": (h.get("object") or {}).get("name", ""),
                    "status": h.get("status", ""),
                    "resumes": int(h.get("resumes") or 0),
                }
                for h in hist
            ],
        }

    ex.stop()
    shutil.rmtree(ckpt_root, ignore_errors=True)
    return {
        "n_jobs": n_jobs,
        "rounds": rounds,
        "steps_target": steps_target,
        "save_every": ELASTIC_SAVE_EVERY,
        "watchdog_floor_s": GRAY_WATCHDOG_FLOOR_S,
        "hang_events": events,
        "runs": runs,
        "timeouts": timeouts,
        "metrics": {
            "hangs_detected": metrics.get("watchdog_hangs_detected_total"),
            "preemptions": metrics.get("cron_workload_preemptions_total"),
            "resumes": metrics.get("cron_workload_resumes_total"),
            "faults_hang": metrics.get('faults_injected_total{kind="hang"}'),
        },
        "elapsed_s": round(time.time() - t0, 1),
    }


def check_gray_invariants(ev: dict) -> dict:
    """I10/I11 plus the breaker fail-fast bound for the gray leg."""
    rounds = ev["fencing_rounds"]
    bad_i10 = [
        r for r in rounds
        if not r.get("zombie_fenced")
        or not r.get("poison_refused")
        or int((r.get("wal_scan") or {}).get("stale_records") or 0) > 0
        or int((r.get("wal_scan") or {}).get("corrupt_lines") or 0) > 0
    ]
    i10 = {
        "ok": bool(rounds) and not bad_i10,
        "detail": (
            f"{len(rounds)} SIGSTOP round(s): every woken zombie fenced "
            "itself, every stale-epoch write failed closed, and the "
            "disk scan found zero stale-generation records in any "
            "WAL/snapshot" if rounds and not bad_i10
            else {"rounds": len(rounds), "failed": bad_i10[:3]}
        ),
    }

    hang = ev.get("hang") or {}
    events = hang.get("hang_events") or []
    problems: list = []
    if hang.get("timeouts"):
        problems.append({"kind": "did_not_finish",
                         "jobs": hang["timeouts"][:5]})
    for e in events:
        if not e["detected"]:
            problems.append({"kind": "hang_not_detected", "event": e})
            continue
        budget = float(e.get("budget_s") or 0.0)
        lat = e.get("detection_latency_s")
        if lat is None or float(lat) > budget + GRAY_DETECT_SLACK_S:
            problems.append({"kind": "detection_over_budget", "event": e})
    target = hang.get("steps_target")
    for cron, run in (hang.get("runs") or {}).items():
        chain = run.get("chain") or []
        if not chain:
            problems.append({"kind": "run_vanished", "cron": cron})
            continue
        final = chain[-1]
        if final["terminal"] != "Succeeded" or final["steps_done"] != target:
            problems.append({
                "kind": "did_not_complete", "cron": cron, "final": final,
            })
        hist = run.get("history") or []
        entries = [h for h in hist if h["name"] == run["root"]]
        if len(hist) != 1 or len(entries) != 1:
            problems.append({
                "kind": "history_not_exactly_once",
                "cron": cron,
                "history": hist,
            })
    lats = [e["detection_latency_s"] for e in events if e["detected"]]
    i11 = {
        "ok": bool(events) and not problems,
        "detail": problems[:6] if problems else (
            f"{len(events)} injected hang(s): every one detected within "
            f"budget (latencies {[round(float(x), 2) for x in lats]}s) "
            f"and every run finished at step {target} in exactly one "
            "history entry"
        ),
    }

    br = ev.get("breaker") or {}
    breaker_ok = bool(
        br.get("opened")
        and br.get("recovered")
        and br.get("closed_after_recovery")
        and br.get("healthy_p99_s") is not None
        and br.get("healthy_p99_s") <= GRAY_P99_BOUND_S
        and br.get("failfast_max_s") is not None
        and br.get("failfast_max_s") <= GRAY_FAILFAST_BOUND_S
    )
    breaker = {
        "ok": breaker_ok,
        "detail": (
            f"breaker opened on the SIGSTOPped shard; healthy-shard p99 "
            f"{br.get('healthy_p99_s')}s <= {GRAY_P99_BOUND_S}s; "
            f"fail-fast max {br.get('failfast_max_s')}s <= "
            f"{GRAY_FAILFAST_BOUND_S}s; closed again "
            f"{br.get('recovery_s')}s after SIGCONT" if breaker_ok
            else br
        ),
    }
    return {
        "I10_no_stale_generation_writes": i10,
        "I11_hangs_detected_within_budget": i11,
        "breaker_failfast_bounded": breaker,
    }


# ---------------------------------------------------------------------------
# Disk-fault leg (--disk): end-to-end storage integrity, invariant I12
# ---------------------------------------------------------------------------

#: Acked creates per soak round — enough that offline damage always has a
#: verifiable prefix before it and acked records after it.
DISK_BATCH = 12


def _disk_obj(r: int, i: int) -> dict:
    # Digit-dense payload on purpose: the bit-flip fault targets value
    # digits, and a flipped payload digit is exactly the silent-corruption
    # case only a checksum catches.
    return {
        "apiVersion": "v1",
        "kind": "ConfigMap",
        "metadata": {"name": f"disk-{r}-{i}", "namespace": NAMESPACE},
        "data": {"round": r, "seq": i, "payload": 1000000 + r * 1000 + i},
    }


def _canon(obj) -> dict:
    """JSON-roundtrip an object (frozen or thawed) into plain comparable
    containers — the same normalization a WAL record goes through."""
    return json.loads(json.dumps(obj, sort_keys=True, default=str))


def _disk_book_check(store, acked: dict) -> dict:
    """I12a: the recovered store must be exactly a replay of an ACKED
    prefix of history.

    ``acked`` is the client-side ledger: name -> the canonicalized object
    the store RETURNED from a successful create. Two checks:

    * membership — every live object must be byte-equal to the acked
      commit of the same name (a silently applied bit-flip, or a record
      that was never acknowledged, fails here);
    * prefix completeness — every acked write at or below the surviving
      rv high-water mark must still be present (recovery may drop an
      acked SUFFIX to quarantine, never punch holes).
    """
    live = {}
    for obj in store.all_objects():
        live[(obj.get("metadata") or {}).get("name")] = _canon(obj)
    # resourceVersion is stringly-typed on the wire — compare numerically.
    cut = max(
        (int(o["metadata"]["resourceVersion"]) for o in live.values()),
        default=0,
    )
    mismatched = []
    for name, obj in sorted(live.items()):
        entry = acked.get(name)
        if entry is None:
            mismatched.append(
                {"name": name, "why": "applied but never acknowledged"}
            )
        elif entry != obj:
            mismatched.append(
                {"name": name,
                 "why": "applied bytes differ from the acked commit"}
            )
    missing = sorted(
        name for name, entry in acked.items()
        if int(entry["metadata"]["resourceVersion"]) <= cut
        and name not in live
    )
    return {
        "cut_rv": cut,
        "live_objects": len(live),
        "mismatched": mismatched,
        "missing": missing,
        "ok": not mismatched and not missing,
    }


def run_disk_soak(seed: int, rounds: int, checksums: bool = True) -> dict:
    """Cycle every disk-fault kind against ONE store + persistence dir.

    Offline kinds (bit_flip, torn_midfile) damage the closed WAL between
    generations and reboot through recovery; online kinds (eio/enospc on
    append, eio on fsync/rename) are injected mid-storm through the
    syscall seam and must trip read-only degraded mode fail-closed, then
    heal on a probe append. The acked ledger is carried across every
    generation for the I12a prefix check."""
    import errno

    from cron_operator_tpu.runtime.faults import (
        DISK_FAULT_KINDS,
        DiskFaultInjector,
    )
    from cron_operator_tpu.runtime.kube import APIServer
    from cron_operator_tpu.runtime.manager import Metrics
    from cron_operator_tpu.runtime.persistence import (
        QUARANTINE_DIR,
        WAL_NAME,
        WAL_PREV_NAME,
        Persistence,
        Scrubber,
        StorageDegradedError,
    )
    from cron_operator_tpu.telemetry.audit import AuditJournal
    from cron_operator_tpu.utils.clock import FakeClock

    data_dir = tempfile.mkdtemp(prefix="chaos-disk-")
    wal_path = os.path.join(data_dir, WAL_NAME)
    wal_prev_path = os.path.join(data_dir, WAL_PREV_NAME)
    qdir = os.path.join(data_dir, QUARANTINE_DIR)
    metrics = Metrics()
    journal = AuditJournal()
    acked: dict = {}  # name -> canonical acked object (the ledger)
    ev: dict = {
        "checksums": checksums,
        "rounds": [],
        "acked_total": 0,
        "refused_verified_absent": 0,
        "lost_to_quarantine": 0,
        "book_violation_rounds": [],
    }

    def _boot(round_idx: int):
        store = APIServer(clock=FakeClock())
        pers = Persistence(
            data_dir,
            fsync_every=1,
            snapshot_every=10_000,  # rotations are explicit in this soak
            flush_interval_s=0,
            checksums=checksums,
            disk_faults=DiskFaultInjector(seed, round_idx=round_idx),
            # Heals are explicit probe() calls — the throttled inline
            # probe must not race the refused-write assertions.
            degraded_probe_interval_s=3600.0,
        )
        pers.instrument(metrics)
        pers.attach_audit(journal)
        rec = pers.start(store)
        return store, pers, rec

    def _ack(obj) -> None:
        acked[obj["metadata"]["name"]] = _canon(obj)
        ev["acked_total"] += 1

    def _qfiles():
        try:
            return sorted(os.listdir(qdir))
        except OSError:
            return []

    try:
        store, pers, _rec = _boot(0)
        for r in range(rounds):
            # Deterministic coverage: the kind cycles (all six within one
            # default soak); the PRF offsets inside flip/tear stay a pure
            # function of (seed, round).
            kind = DISK_FAULT_KINDS[r % len(DISK_FAULT_KINDS)]
            inj = DiskFaultInjector(seed, round_idx=r)
            pers.disk_faults = inj
            round_ev: dict = {"round": r, "kind": kind}

            if kind in ("bit_flip", "torn_midfile"):
                # ---- offline damage: write, close, damage, recover ----
                round_ev["mode"] = "offline"
                for i in range(DISK_BATCH):
                    _ack(store.create(_disk_obj(r, i)))
                pers.close()
                q_before = _qfiles()
                if kind == "bit_flip":
                    dmg_off = inj.flip_value_digit(wal_path)
                else:
                    dmg_off = inj.tear_midfile(wal_path)
                store, pers, rec = _boot(r + 1000)
                check = _disk_book_check(store, acked)
                new_q = [f for f in _qfiles() if f not in q_before]
                forensics = None
                for f in new_q:
                    if f.endswith(".json"):
                        try:
                            with open(os.path.join(qdir, f)) as fh:
                                forensics = json.load(fh)
                        except (OSError, ValueError):
                            pass
                # Acked records past the surviving rv were legitimately
                # lost to the quarantined suffix (prefix semantics) —
                # retire them from the ledger.
                lost = [
                    n for n, e in acked.items()
                    if int(e["metadata"]["resourceVersion"])
                    > check["cut_rv"]
                ]
                for n in lost:
                    del acked[n]
                ev["lost_to_quarantine"] += len(lost)
                round_ev.update({
                    "damage_offset": dmg_off,
                    "verdict": rec.integrity.get("verdict"),
                    "integrity": rec.integrity,
                    "book_check": check,
                    "quarantine_files_added": new_q,
                    "forensics": forensics,
                    "acked_lost_past_cut": len(lost),
                })
                if not check["ok"]:
                    ev["book_violation_rounds"].append(r)
            else:
                # ---- online errno fault: trip, refuse, probe, heal ----
                round_ev["mode"] = "online"
                for i in range(DISK_BATCH // 2):
                    _ack(store.create(_disk_obj(r, i)))
                victim = _disk_obj(r, 900)
                tripped_by_refusal = False
                if kind in ("eio_append", "enospc_append"):
                    err_no = (errno.EIO if kind == "eio_append"
                              else errno.ENOSPC)
                    inj.arm_errno("append", err_no)
                    # The armed errno fires inside _append BEFORE the
                    # in-memory commit: the very first write is refused.
                    tripped_by_refusal = True
                elif kind == "eio_fsync":
                    inj.arm_errno("fsync", errno.EIO)
                    # The append reaches the OS file before the group
                    # fsync dies: THIS write is acked and durable, the
                    # layer degrades for everyone after it.
                    _ack(store.create(_disk_obj(r, 900)))
                    victim = _disk_obj(r, 901)
                else:  # eio_rename — dies inside snapshot rotation
                    inj.arm_errno("rename", errno.EIO)
                    # Rotation aborts, pre-rotation chain stays
                    # authoritative, no acked write fails.
                    pers.write_snapshot(
                        store.all_objects(), int(getattr(store, "_rv", 0))
                    )
                refused = None
                try:
                    store.create(dict(victim))
                except StorageDegradedError as e:
                    refused = str(e)
                round_ev["tripped_degraded"] = pers.degraded
                round_ev["degraded_reason"] = pers.degraded_reason
                round_ev["gauge_during"] = metrics.gauge("storage_degraded")
                name = victim["metadata"]["name"]
                absent = (
                    store.try_get("v1", "ConfigMap", NAMESPACE, name) is None
                )
                if absent:
                    ev["refused_verified_absent"] += 1
                healed = pers.probe()
                # The refused write existed NOWHERE, so the same name
                # creates cleanly once the device answers again.
                _ack(store.create(dict(victim)))
                for i in range(1000, 1000 + DISK_BATCH // 2):
                    _ack(store.create(_disk_obj(r, i)))
                round_ev.update({
                    "tripped_by_refusal": tripped_by_refusal,
                    "refused": refused,
                    "refused_absent": absent,
                    "healed": healed,
                    "gauge_after_heal": metrics.gauge("storage_degraded"),
                    "degraded_entries": pers.degraded_entries,
                    "degraded_exits": pers.degraded_exits,
                    "degraded_refused": pers.degraded_refused,
                })
            ev["rounds"].append(round_ev)

        # ---- scrubber leg: latent corruption in COLD sealed bytes ----
        scrub = None
        if checksums:
            for i in range(4):
                _ack(store.create(_disk_obj(rounds, i)))
            pers.disk_faults = None
            pers.write_snapshot(
                store.all_objects(), int(getattr(store, "_rv", 0))
            )
            inj = DiskFaultInjector(seed, round_idx=rounds + 7)
            flip_off = inj.flip_value_digit(wal_prev_path)
            scrubber = Scrubber(pers, interval_s=3600.0)
            scrubber.instrument(metrics)
            summary = scrubber.scrub_once()
            scrub = {
                "flip_offset": flip_off,
                "summary": summary,
                "found_kinds": sorted(
                    {f["kind"] for f in summary["findings"]}
                ),
            }
        ev["scrub"] = scrub

        # ---- final generation: a clean close must lose nothing ----
        pers.close()
        store, pers, rec = _boot(rounds + 2000)
        final_check = _disk_book_check(store, acked)
        ev["final"] = {
            "verdict": rec.integrity.get("verdict"),
            "integrity": rec.integrity,
            "book_check": final_check,
            "acked_past_cut": sum(
                1 for e in acked.values()
                if int(e["metadata"]["resourceVersion"])
                > final_check["cut_rv"]
            ),
            "objects": len(store.all_objects()),
        }
        if not final_check["ok"]:
            ev["book_violation_rounds"].append("final")
        pers.close()
    finally:
        shutil.rmtree(data_dir, ignore_errors=True)

    ev["metrics"] = {
        "storage_degraded": metrics.gauge("storage_degraded"),
        "wal_degraded_refused_total": metrics.get(
            "wal_degraded_refused_total"
        ),
        "wal_records_quarantined_total": metrics.get(
            "wal_records_quarantined_total"
        ),
        "wal_crc_failures_recovery": metrics.get(
            'wal_crc_failures_total{site="recovery"}'
        ),
        "wal_crc_failures_scrub": metrics.get(
            'wal_crc_failures_total{site="scrub"}'
        ),
        "scrub_corruptions_found_total": metrics.get(
            "scrub_corruptions_found_total"
        ),
    }
    ev["audit"] = {
        "corruption_detected": len(
            journal.records(event="corruption_detected")
        ),
        "degraded_mode_entered": len(
            journal.records(event="degraded_mode_entered")
        ),
        "degraded_mode_exited": len(
            journal.records(event="degraded_mode_exited")
        ),
    }
    return ev


def check_disk_invariants(ev: dict) -> dict:
    """I12 verdicts over one ``run_disk_soak`` evidence dict."""
    rounds = ev["rounds"]
    offline = [r for r in rounds if r["mode"] == "offline"]
    online = [r for r in rounds if r["mode"] == "online"]
    final = ev.get("final") or {}

    book_ok = (
        not ev["book_violation_rounds"]
        and bool((final.get("book_check") or {}).get("ok"))
        and final.get("acked_past_cut") == 0
    )
    i12a = {
        "ok": book_ok,
        "detail": (
            f"every recovery (after {len(offline)} damage round(s) and a "
            f"clean final close) applied only acknowledged bytes and "
            f"landed on an acked prefix; {ev['acked_total']} acked "
            f"writes, {ev['lost_to_quarantine']} retired to quarantined "
            f"suffixes" if book_ok
            else {"violation_rounds": ev["book_violation_rounds"],
                  "final": final.get("book_check")}
        ),
    }

    # Detection: every offline damage round must end in a non-clean
    # verdict; a quarantined verdict must come with on-disk forensics;
    # the scrubber must find the latent sealed-segment flip.
    detected = bool(offline) and all(
        r.get("verdict") in ("quarantined", "torn_tail", "snapshot_fallback")
        for r in offline
    )
    forensics_ok = all(
        r.get("forensics") is not None
        for r in offline if r.get("verdict") == "quarantined"
    )
    quarantined_rounds = [
        r["round"] for r in offline if r.get("verdict") == "quarantined"
    ]
    scrub = ev.get("scrub") or {}
    scrub_ok = "wal_crc_mismatch" in (scrub.get("found_kinds") or [])
    audit_ok = (ev.get("audit") or {}).get("corruption_detected", 0) > 0
    i12b_ok = detected and forensics_ok and scrub_ok and audit_ok
    i12b = {
        "ok": i12b_ok,
        "detail": (
            f"all {len(offline)} damage rounds detected "
            f"(verdicts: {[r.get('verdict') for r in offline]}), "
            f"quarantine forensics written in rounds "
            f"{quarantined_rounds}, scrubber found the latent "
            f"sealed-segment flip, "
            f"{ev['audit']['corruption_detected']} corruption_detected "
            f"audit event(s)" if i12b_ok
            else {"detected": detected, "forensics_ok": forensics_ok,
                  "scrub": scrub, "audit": ev.get("audit")}
        ),
    }

    closed = bool(online) and all(
        r.get("tripped_degraded")
        and r.get("refused")
        and r.get("refused_absent")
        and r.get("healed")
        and r.get("gauge_during") == 1.0
        and r.get("gauge_after_heal") == 0.0
        for r in online
    )
    i12c = {
        "ok": closed,
        "detail": (
            f"all {len(online)} injected errno round(s) "
            f"({[r['kind'] for r in online]}) refused the write BEFORE "
            f"any commit (refused object verified absent "
            f"{ev['refused_verified_absent']} time(s)), degraded gauge "
            f"visible during and clear after the probe heal" if closed
            else [
                {k: r.get(k) for k in
                 ("round", "kind", "tripped_degraded", "refused",
                  "refused_absent", "healed", "gauge_during",
                  "gauge_after_heal")}
                for r in online
            ]
        ),
    }
    return {
        "I12a_no_corrupt_record_applied": i12a,
        "I12b_damage_detected_and_quarantined": i12b,
        "I12c_disk_errors_fail_closed": i12c,
    }


# ---------------------------------------------------------------------------
# partition leg (--partition): lying networks, invariant I13
# ---------------------------------------------------------------------------

#: One scheduled partition must detect + heal within this wall bound —
#: generous against CI jitter; the measured numbers land in CHAOS.json.
PARTITION_HEAL_BOUND_S = 20.0
#: Ship-link heartbeat cadence for the soak (tight so half-open windows
#: are detected in hundreds of ms, not the production 5 s).
NET_HB_INTERVAL_S = 0.1
NET_HB_TIMEOUT_S = 0.6


def _net_obj(tag: str, i: int) -> dict:
    return {
        "apiVersion": "v1",
        "kind": "ConfigMap",
        "metadata": {"name": f"net-{tag}-{i}", "namespace": NAMESPACE},
        "data": {"tag": tag, "seq": i, "payload": 2000000 + i},
    }


def _run_ship_leg(inj, metrics, rounds: int, net_heartbeats: bool) -> dict:
    """Scenario A: one leader's WAL ship stream through a lying link.

    A seeded :class:`LinkPlan` keeps delay/duplicate/reorder/slow-drip/
    RST faults flowing on EVERY frame window, while ``inj.schedule``
    expands the deterministic partition storm: each round writes acked
    objects, goes dark in a PRF-chosen direction, keeps writing into
    the darkness, heals, and measures time-to-reconverge.  The acked
    ledger is carried to the end for the I13a book check (the exact
    I12a check, aimed at the replica instead of a recovered store).

    With ``net_heartbeats=False`` this is the counter-proof: the first
    s2c/both window wedges the follower's blocking recv forever — no
    deadline, no PING to miss — and the evidence records the silently
    growing lag instead of a heal time."""
    from cron_operator_tpu.runtime.kube import APIServer
    from cron_operator_tpu.runtime.netfaults import LinkPlan
    from cron_operator_tpu.runtime.persistence import Persistence
    from cron_operator_tpu.runtime.shard import (
        FollowerReplica,
        canonical_state,
    )
    from cron_operator_tpu.runtime.transport import (
        ShipFollower,
        WALShipServer,
    )
    from cron_operator_tpu.utils.clock import FakeClock, RealClock

    data_dir = tempfile.mkdtemp(prefix="chaos-net-ship-")
    store = APIServer(clock=FakeClock())
    pers = Persistence(data_dir, fsync_every=1)
    pers.start(store)
    server = WALShipServer(
        pers,
        heartbeats=net_heartbeats,
        heartbeat_interval_s=NET_HB_INTERVAL_S,
        heartbeat_timeout_s=NET_HB_TIMEOUT_S,
        metrics=metrics,
    )
    plan = LinkPlan(
        p_delay=0.05, p_duplicate=0.08, p_reorder=0.04, p_slowdrip=0.04,
        p_rst=0.02, delay_s=0.01, drip_bytes=16, drip_pause_s=0.0005,
    )
    proxy = inj.proxy("ship", "127.0.0.1", server.port, framed=True,
                      plan=plan)
    replica = FollowerReplica(RealClock(), name="partition-soak")
    follower = ShipFollower(
        "127.0.0.1", proxy.port, replica, metrics=metrics,
        heartbeats=net_heartbeats, heartbeat_timeout_s=NET_HB_TIMEOUT_S,
    )

    acked: dict = {}
    seq = [0]

    def _write(n: int) -> None:
        for _ in range(n):
            obj = store.create(_net_obj("ship", seq[0]))
            acked[obj["metadata"]["name"]] = _canon(obj)
            seq[0] += 1
        pers.flush()

    def _leader_state() -> str:
        return canonical_state(store.all_objects(), store._rv)

    def _converged() -> bool:
        return replica.state() == _leader_state()

    def _wait_converged(timeout: float) -> bool:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if _converged():
                return True
            time.sleep(0.02)
        return _converged()

    ev: dict = {
        "rounds": [],
        "acked_total": 0,
        "plan": asdict(plan),
        "connected": False,
    }
    try:
        ev["connected"] = follower.wait_connected(10.0)
        sched = inj.schedule(rounds, ["ship"])
        for entry in sched:
            reconnects0 = follower.reconnects
            hb0 = follower.heartbeat_timeouts + int(
                metrics.counters.get(
                    'transport_heartbeat_timeouts_total{side="leader"}', 0
                )
            )
            _write(15)
            inj.partition("ship", entry["direction"])
            _write(10)  # acked into the darkness — must survive the heal
            time.sleep(entry["hold_s"])
            inj.heal("ship")
            t0 = time.monotonic()
            if not net_heartbeats and entry["direction"] in ("s2c", "both"):
                # Counter-proof: the darkened conn is sticky and nothing
                # wakes the blocking recv — give the system a window a
                # heartbeat stack would have healed in, then record the
                # wedge instead of a heal.
                time.sleep(NET_HB_TIMEOUT_S * 3 + 2.0)
                _write(5)
                time.sleep(0.5)
                ev["wedge"] = {
                    "round": entry["round"],
                    "direction": entry["direction"],
                    "converged": _converged(),
                    "reconnects_after_heal":
                        follower.reconnects - reconnects0,
                    "heartbeat_timeouts": follower.heartbeat_timeouts,
                    "replica_lag": len(acked) - len(replica.store),
                    # Wedged = still diverged after a window a heartbeat
                    # stack heals in <1 s.  (Reconnect COUNT is evidence,
                    # not the gate: a plan-injected reorder/RST can
                    # legally resync once, but the conn that then went
                    # dark stays half-open forever.)
                    "wedged": not _converged(),
                }
                break
            healed = _wait_converged(PARTITION_HEAL_BOUND_S)
            ev["rounds"].append({
                "round": entry["round"],
                "direction": entry["direction"],
                "hold_s": round(entry["hold_s"], 3),
                "healed": healed,
                "heal_s": round(time.monotonic() - t0, 3),
                "reconnects_delta": follower.reconnects - reconnects0,
                "heartbeat_timeouts_delta":
                    follower.heartbeat_timeouts + int(
                        metrics.counters.get(
                            'transport_heartbeat_timeouts_total'
                            '{side="leader"}', 0
                        )
                    ) - hb0,
            })
        if not net_heartbeats and "wedge" not in ev:
            # The seeded schedule drew only c2s windows — force the one
            # direction the counter-proof is about (a half-open conn the
            # follower is blocked reading) so the violation is
            # deterministic for any seed.
            reconnects0 = follower.reconnects
            inj.partition("ship", "s2c")
            _write(10)
            time.sleep(0.5)
            inj.heal("ship")
            time.sleep(NET_HB_TIMEOUT_S * 3 + 2.0)
            _write(5)
            time.sleep(0.5)
            ev["wedge"] = {
                "round": "forced-s2c",
                "direction": "s2c",
                "converged": _converged(),
                "reconnects_after_heal": follower.reconnects - reconnects0,
                "heartbeat_timeouts": follower.heartbeat_timeouts,
                "replica_lag": len(acked) - len(replica.store),
                "wedged": not _converged(),
            }
        ev["acked_total"] = len(acked)
        if net_heartbeats:
            ev["final_converged"] = _wait_converged(PARTITION_HEAL_BOUND_S)
            ev["book_check"] = _disk_book_check(replica.store, acked)
        ev["follower"] = {
            "reconnects": follower.reconnects,
            "bootstraps": follower.bootstraps,
            "heartbeat_timeouts": follower.heartbeat_timeouts,
            "duplicate_frames": follower.duplicate_frames,
            "frames_rejected": follower.frames_rejected,
        }
    finally:
        follower.stop()
        server.close()
        pers.close()
        shutil.rmtree(data_dir, ignore_errors=True)
    return ev


def _run_lease_leg(inj, metrics) -> dict:
    """Scenario B — the nastiest interaction: the leader is socket-
    partitioned from the ROUTER, but its lease heartbeat rides the
    local shard dir, which the partition cannot touch.  The standby
    (whose ship stream is also untouched) must NOT promote — the
    generation stays put — while the router's breaker converts the
    partition into fast failures instead of a timeout storm.  Healing
    the link restores writes with no operator action, and the WAL scan
    proves I10 (zero stale-generation bytes) held throughout."""
    from cron_operator_tpu.runtime.transport import (
        BREAKER_OPEN,
        RouterServer,
        ShardClient,
        ShardServing,
        StandbyServer,
    )

    data_dir = tempfile.mkdtemp(prefix="chaos-net-lease-")
    request_timeout_s = 1.0
    serving = ShardServing(0, data_dir=data_dir, lease_ttl_s=1.0,
                           metrics=metrics)
    standby = StandbyServer(
        0, data_dir=data_dir, ship_port=serving.ship_port,
        api_port=serving.api_port, lease_ttl_s=1.0,
        promote_api_port=0, promote_ship_port=0, metrics=metrics,
    )
    stop = threading.Event()
    standby_thread = threading.Thread(
        target=standby.run, args=(stop,), daemon=True
    )
    standby_thread.start()
    proxy = inj.proxy("api", "127.0.0.1", serving.api_port)
    router = RouterServer(
        peers=[f"127.0.0.1:{proxy.port}"], metrics=metrics,
        request_timeout_s=request_timeout_s,
        breaker_kwargs={"window": 8, "min_samples": 2,
                        "error_threshold": 0.5, "cooldown_s": 0.5},
    )
    front = ShardClient(f"http://127.0.0.1:{router.port}")

    ev: dict = {}
    try:
        for i in range(5):
            front.create(_net_obj("lease-base", i))
        gen_before = serving.lease.generation
        inj.partition("api", "both")
        t_dark = time.monotonic()
        attempts = []
        for i in range(8):
            t0 = time.monotonic()
            try:
                front.create(_net_obj("lease-dark", i))
                ok = True
            except Exception:  # noqa: BLE001 — the partition IS the test
                ok = False
            attempts.append({"ok": ok,
                             "latency_s": round(time.monotonic() - t0, 3)})
        breaker = router.clients[0].breaker
        ev["breaker_open_during"] = breaker.state == BREAKER_OPEN
        ev["breaker_fast_failures"] = breaker.fast_failures
        # Fast-fail once tripped: the rolling window starts with the
        # baseline successes, so the trip lands a few timeouts in — but
        # the LAST attempts must all refuse without paying the timeout.
        tail = attempts[-3:]
        ev["fast_fail_ok"] = (
            breaker.fast_failures > 0
            and all(a["latency_s"] < request_timeout_s / 2 for a in tail)
        )
        # Hold the partition past three full lease TTLs measured from
        # darkness onset — the false-failover window.
        time.sleep(max(0.0, 3.5 - (time.monotonic() - t_dark)))
        ev["dark_attempts"] = attempts
        ev["promoted_during_partition"] = standby.serving is not None
        ev["generation_before"] = gen_before
        ev["generation_during"] = serving.lease.generation
        inj.heal("api")
        t0 = time.monotonic()
        healed = False
        while time.monotonic() - t0 < PARTITION_HEAL_BOUND_S:
            try:
                front.create(_net_obj("lease-heal", int(t0)))
                healed = True
                break
            except Exception:  # noqa: BLE001 — breaker still cooling
                time.sleep(0.1)
        ev["healed_without_operator"] = healed
        ev["heal_s"] = round(time.monotonic() - t0, 3)
        ev["promoted_after_heal"] = standby.serving is not None
        ev["generation_after"] = serving.lease.generation
        ev["audit_check"] = serving.audit_check()
        ev["wal_scan"] = _scan_stale_generations(serving.sdir)
        ev["retry_budget_denials"] = int(
            metrics.counters.get("router_retry_budget_exhausted_total", 0)
        )
    finally:
        stop.set()
        router.close()
        standby.follower.stop()
        standby_thread.join(timeout=5.0)
        if standby.serving is not None:
            standby.serving.close(write_report=False)
        serving.close(write_report=False)
        shutil.rmtree(data_dir, ignore_errors=True)
    return ev


def _run_budget_leg(inj, metrics) -> dict:
    """Scenario C — retry-storm containment: two shards behind the
    router, one partitioned, a storm of writes aimed at the dark shard.
    The breaker + shared retry budget must keep the HEALTHY shard's
    write p99 within 1.2x its pre-partition baseline (absolute floor
    50 ms so an in-process microbenchmark blip can't flake the gate)."""
    from cron_operator_tpu.runtime.transport import (
        RouterServer,
        ShardClient,
        ShardServing,
    )

    dirs = [tempfile.mkdtemp(prefix=f"chaos-net-budget{i}-")
            for i in range(2)]
    servings = [ShardServing(i, data_dir=dirs[i], metrics=metrics)
                for i in range(2)]
    proxy = inj.proxy("shard0", "127.0.0.1", servings[0].api_port)
    router = RouterServer(
        peers=[f"127.0.0.1:{proxy.port}",
               f"127.0.0.1:{servings[1].api_port}"],
        metrics=metrics,
        request_timeout_s=0.5,
        breaker_kwargs={"window": 8, "min_samples": 2,
                        "error_threshold": 0.5, "cooldown_s": 0.5},
        retry_budget_kwargs={"max_tokens": 4.0, "token_ratio": 0.1},
    )
    front = ShardClient(f"http://127.0.0.1:{router.port}")
    shard_of = router.router.shard_for

    healthy, victim = [], []
    i = 0
    while len(healthy) < 160 or len(victim) < 60:
        name = f"net-budget-{i}"
        (healthy if shard_of(NAMESPACE, name) == 1 else victim).append(name)
        i += 1

    def _create(name: str) -> float:
        t0 = time.monotonic()
        front.create({
            "apiVersion": "v1", "kind": "ConfigMap",
            "metadata": {"name": name, "namespace": NAMESPACE},
            "data": {"leg": "budget"},
        })
        return time.monotonic() - t0

    def _p99(samples) -> float:
        s = sorted(samples)
        return s[min(len(s) - 1, int(round(0.99 * (len(s) - 1))))]

    ev: dict = {}
    storm_stop = threading.Event()

    def _storm() -> None:
        # Wraps around the victim pool: once the breaker trips the
        # refusals are ~free, so the storm keeps hammering the dark
        # shard for the WHOLE measurement window (duplicate names after
        # the wrap still exercise allow()).
        j = 0
        while not storm_stop.is_set():
            try:
                _create(victim[j % len(victim)])
            except Exception:  # noqa: BLE001 — the dark shard IS dark
                pass
            j += 1

    try:
        base = [_create(n) for n in healthy[:70]]
        denials0 = int(
            metrics.counters.get("router_retry_budget_exhausted_total", 0)
        )
        breaker = router.clients[0].breaker
        inj.partition("shard0", "both")
        storm = threading.Thread(target=_storm, daemon=True)
        storm.start()
        # The rolling window opens with baseline-era successes, so the
        # trip costs a handful of request timeouts — wait for it, THEN
        # measure the healthy shard under a tripped-breaker storm.
        deadline = time.monotonic() + 15.0
        while breaker.trips == 0 and time.monotonic() < deadline:
            time.sleep(0.05)
        during = [_create(n) for n in healthy[70:140]]
        # The storm's cooldown probes legally flip OPEN -> HALF_OPEN and
        # back, so gate on the trip having happened, not a state
        # snapshot.
        ev["victim_breaker_open"] = breaker.trips >= 1
        ev["victim_breaker_trips"] = breaker.trips
        storm_stop.set()
        storm.join(timeout=10.0)
        inj.heal("shard0")
        k = 0
        while shard_of(NAMESPACE, f"net-budget-heal-{k}") != 0:
            k += 1
        t0 = time.monotonic()
        healed = False
        while time.monotonic() - t0 < PARTITION_HEAL_BOUND_S:
            try:
                _create(f"net-budget-heal-{k}")
                healed = True
                break
            except Exception:  # noqa: BLE001 — breaker still cooling
                time.sleep(0.1)
        ev["victim_healed"] = healed
        ev["victim_heal_s"] = round(time.monotonic() - t0, 3)
        p99_base, p99_during = _p99(base), _p99(during)
        ev["p99_baseline_s"] = round(p99_base, 4)
        ev["p99_during_partition_s"] = round(p99_during, 4)
        ev["p99_bound_s"] = round(max(1.2 * p99_base, 0.05), 4)
        ev["p99_contained"] = p99_during <= max(1.2 * p99_base, 0.05)
        ev["retry_budget_denials_delta"] = int(
            metrics.counters.get("router_retry_budget_exhausted_total", 0)
        ) - denials0
        ev["retry_budget_depleted"] = bool(
            router.retry_budget is not None and router.retry_budget.depleted
        )
    finally:
        storm_stop.set()
        router.close()
        for s in servings:
            s.close(write_report=False)
        for d in dirs:
            shutil.rmtree(d, ignore_errors=True)
    return ev


def run_partition_soak(seed: int, rounds: int,
                       net_heartbeats: bool = True) -> dict:
    """The I13 partition soak: three in-process legs against one seeded
    :class:`NetworkFaultInjector` (ship stream under a lying link; the
    router-partitioned-but-lease-fresh leader; the retry-storm p99
    gate).  ``net_heartbeats=False`` runs only the ship leg and records
    the half-open wedge the heartbeat stack exists to prevent."""
    from cron_operator_tpu.runtime.manager import Metrics
    from cron_operator_tpu.runtime.netfaults import NetworkFaultInjector

    metrics = Metrics()
    inj = NetworkFaultInjector(seed, metrics=metrics)
    ev: dict = {"net_heartbeats": net_heartbeats, "seed": seed}
    try:
        ev["ship_leg"] = _run_ship_leg(inj, metrics, rounds, net_heartbeats)
        if "wedge" in ev["ship_leg"]:
            ev["wedge"] = ev["ship_leg"]["wedge"]
        if net_heartbeats:
            ev["lease_leg"] = _run_lease_leg(inj, metrics)
            ev["budget_leg"] = _run_budget_leg(inj, metrics)
        ev["injector"] = inj.stats()
        ev["metrics"] = {
            k: v for k, v in sorted(metrics.counters.items())
            if k.startswith(("net_faults_injected_total",
                             "transport_heartbeat_timeouts_total",
                             "transport_duplicate_frames_total",
                             "router_retry_budget_exhausted_total",
                             "shard_follower_reconnects_total"))
        }
    finally:
        inj.close()
    return ev


def check_partition_invariants(ev: dict) -> dict:
    """I13 verdicts over one ``run_partition_soak`` evidence dict."""
    ship = ev.get("ship_leg") or {}
    lease = ev.get("lease_leg") or {}
    budget = ev.get("budget_leg") or {}

    book = ship.get("book_check") or {}
    fol = ship.get("follower") or {}
    inj = ev.get("injector") or {}
    injected = inj.get("injected") or {}
    a_ok = (
        bool(ship.get("connected"))
        and bool(ship.get("final_converged"))
        and bool(book.get("ok"))
        and fol.get("duplicate_frames", 0) > 0
        and injected.get("duplicate", 0) > 0
    )
    i13a = {
        "ok": a_ok,
        "detail": (
            f"{ship.get('acked_total')} acked writes (many into dark "
            f"windows) all present exactly once on the replica; "
            f"{fol.get('duplicate_frames')} duplicated frames absorbed "
            f"as counted no-ops, {fol.get('frames_rejected')} frames "
            f"rejected, injector landed {dict(injected)}" if a_ok
            else {"connected": ship.get("connected"),
                  "final_converged": ship.get("final_converged"),
                  "book_check": book, "follower": fol,
                  "injected": dict(injected)}
        ),
    }

    gen_stable = (
        lease.get("generation_before") is not None
        and lease.get("generation_before")
        == lease.get("generation_during")
        == lease.get("generation_after")
    )
    wal_scan = lease.get("wal_scan") or {}
    audit = lease.get("audit_check") or {}
    b_ok = (
        not lease.get("promoted_during_partition", True)
        and not lease.get("promoted_after_heal", True)
        and gen_stable
        and bool(lease.get("breaker_open_during"))
        and bool(lease.get("fast_fail_ok"))
        and bool(lease.get("healed_without_operator"))
        and wal_scan.get("stale_records", 1) == 0
        and bool(audit.get("ok"))
    )
    i13b = {
        "ok": b_ok,
        "detail": (
            f"leader partitioned from the router for >3 lease TTLs: "
            f"standby never promoted, generation pinned at "
            f"{lease.get('generation_after')}, breaker failed fast "
            f"({lease.get('breaker_fast_failures')} refusals), link "
            f"healed in {lease.get('heal_s')}s with no operator action; "
            f"audit≡WAL ok, {wal_scan.get('stale_records')} "
            f"stale-generation records" if b_ok
            else {k: lease.get(k) for k in
                  ("promoted_during_partition", "promoted_after_heal",
                   "generation_before", "generation_during",
                   "generation_after", "breaker_open_during",
                   "fast_fail_ok", "healed_without_operator",
                   "heal_s", "wal_scan", "audit_check")}
        ),
    }

    rounds = ship.get("rounds") or []
    heal_times = [r["heal_s"] for r in rounds]
    detected = sum(
        r["reconnects_delta"] + r["heartbeat_timeouts_delta"]
        for r in rounds
    )
    c_ok = (
        bool(rounds)
        and all(r["healed"] for r in rounds)
        and max(heal_times, default=PARTITION_HEAL_BOUND_S)
        <= PARTITION_HEAL_BOUND_S
        and detected > 0
        and bool(lease.get("healed_without_operator"))
        and lease.get("heal_s", PARTITION_HEAL_BOUND_S + 1)
        <= PARTITION_HEAL_BOUND_S
    )
    i13c = {
        "ok": c_ok,
        "detail": (
            f"all {len(rounds)} scheduled partitions "
            f"({[r['direction'] for r in rounds]}) detected "
            f"({detected} reconnects/heartbeat-timeouts) and healed; "
            f"heal times {heal_times}s, max "
            f"{max(heal_times, default=0)}s <= "
            f"{PARTITION_HEAL_BOUND_S}s bound" if c_ok
            else {"rounds": rounds, "detected": detected,
                  "lease_heal_s": lease.get("heal_s")}
        ),
    }

    d_ok = (
        bool(budget.get("p99_contained"))
        and bool(budget.get("victim_breaker_open"))
        and bool(budget.get("victim_healed"))
    )
    i13d = {
        "ok": d_ok,
        "detail": (
            f"healthy-shard write p99 {budget.get('p99_during_partition_s')}s "
            f"during the storm vs {budget.get('p99_baseline_s')}s baseline "
            f"(bound {budget.get('p99_bound_s')}s); victim breaker open "
            f"({budget.get('victim_breaker_trips')} trip(s)), "
            f"{budget.get('retry_budget_denials_delta')} retry-budget "
            f"denial(s), victim healed in {budget.get('victim_heal_s')}s"
            if d_ok
            else dict(budget)
        ),
    }
    return {
        "I13a_no_acked_write_lost_or_doubled": i13a,
        "I13b_partition_cannot_cause_false_failover": i13b,
        "I13c_detection_and_heal_bounded": i13c,
        "I13d_retry_storm_contained": i13d,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--crons", type=int, default=200)
    ap.add_argument("--rounds", type=int, default=6)
    ap.add_argument("--workers", type=int, default=4)
    ap.add_argument("--quiesce-timeout", type=float, default=30.0)
    ap.add_argument("--unhardened", action="store_true", default=False,
                    help="pre-hardening mode: single-attempt writes, no "
                         "watch resync — demonstrates the invariant "
                         "violations the hardening prevents")
    ap.add_argument("--no-crash", action="store_true", default=False,
                    help="disable crash-restart rounds (PR4-era soak: "
                         "bad-RPC faults only)")
    ap.add_argument("--no-durability", action="store_true", default=False,
                    help="crash rounds restart from an EMPTY data dir "
                         "(unset --data-dir semantics) — demonstrates "
                         "the I7 violations persistence prevents")
    ap.add_argument("--data-dir", default=None,
                    help="persistence dir for crash-restart rounds "
                         "(default: a private tempdir, removed at exit)")
    ap.add_argument("--expect-violation", action="store_true", default=False,
                    help="exit 0 iff at least one invariant is violated "
                         "(with --no-durability: I7 specifically) — for "
                         "asserting the violation demonstrations")
    ap.add_argument("--shards", type=int, default=0,
                    help="soak a SHARDED control plane (runtime/shard.py) "
                         "with N shards, each with a WAL-shipping hot "
                         "standby: kill rounds promote the victim shard's "
                         "follower instead of replaying from disk (I6 is "
                         "checked per shard at promotion time)")
    ap.add_argument("--preempt-storm", action="store_true", default=False,
                    help="also run the ELASTIC leg: real CPU-mesh training "
                         "jobs hit by preemption storms, resumed by the "
                         "controller on the surviving devices (invariant "
                         "I8)")
    ap.add_argument("--no-elastic", action="store_true", default=False,
                    help="run ONLY the elastic leg with elastic resume "
                         "disabled (restart-on-preemption, no checkpoint) "
                         "— the I8 counter-proof: restarted runs start "
                         "over at step 0")
    ap.add_argument("--elastic-jobs", type=int, default=3,
                    help="logical training runs in the elastic leg")
    ap.add_argument("--fleet-flap", action="store_true", default=False,
                    help="run ONLY the fleet capacity-flap leg: a mixed "
                         "slice pool with tenant quotas shrinks/grows "
                         "mid-storm; no admitted job may be lost, quotas "
                         "never exceeded, preempted runs resume into one "
                         "history entry (invariants F1-F3)")
    ap.add_argument("--grow", action="store_true", default=False,
                    help="also run the bidirectional-elasticity leg: a "
                         "REAL training job is checkpoint-and-regrown "
                         "into idle width tiers by the GrowPlanner, "
                         "shrunk back under priority pressure, and its "
                         "goodput compared against a shrink-only "
                         "baseline (margin >= 1.15x, invariants F1-F4)")
    ap.add_argument("--no-grow", action="store_true", default=False,
                    help="run ONLY the grow scenario with the "
                         "GrowPlanner disabled — the counter-proof: "
                         "shrink-only measurably leaves the idle "
                         "wider-slice capacity on the table")
    ap.add_argument("--processes", action="store_true", default=False,
                    help="run ONLY the multi-PROCESS leg: spawn the real "
                         "topology (per-shard leader + standby processes "
                         "behind a router process), drive a CRUD storm "
                         "through the router, and SIGKILL a PRF-chosen "
                         "shard's serving process every round — the "
                         "standby must self-promote on lease-file expiry "
                         "with I6 (promoted ≡ on-disk WAL replay) checked "
                         "before serving and I9 (audit ≡ WAL) proved at "
                         "each graceful shutdown; --shards sets the "
                         "topology width (default 2)")
    ap.add_argument("--lease-ttl", type=float, default=1.0,
                    help="processes leg: leader lease TTL in seconds "
                         "(bounds failover detection)")
    ap.add_argument("--gray", action="store_true", default=False,
                    help="run ONLY the gray-failure leg: SIGSTOP a shard "
                         "leader past its lease TTL, promote the standby, "
                         "SIGCONT the zombie and prove its stale-epoch "
                         "writes fail closed (I10, fencing tokens); wedge "
                         "real training step loops and prove the watchdog "
                         "detects each hang within budget and the run "
                         "still finishes (I11); SIGSTOP one shard behind "
                         "the breaker router and prove healthy-shard p99 "
                         "stays bounded while the victim fails fast")
    ap.add_argument("--no-fencing", action="store_true", default=False,
                    help="run ONLY the gray fencing rounds with lease "
                         "fencing disabled — the I10 counter-proof: the "
                         "woken zombie's write lands in the WAL inode the "
                         "promoted leader now owns (use with "
                         "--expect-violation)")
    ap.add_argument("--split", action="store_true", default=False,
                    help="run ONLY the live-split leg: start at one boot "
                         "shard and split the hottest shard every round "
                         "while a write storm runs through the router — "
                         "I6 (child ≡ filtered WAL replay at cutover), "
                         "I9, I10, exactly-one-owner after every round "
                         "AND after a parent kill inside the dark "
                         "window, zero acked writes lost; with "
                         "--no-fencing the dark-window poison write is "
                         "ACKED then erased — the counter-proof (use "
                         "with --expect-violation)")
    ap.add_argument("--disk", action="store_true", default=False,
                    help="run ONLY the disk-fault leg: cycle every "
                         "DISK_FAULT_KINDS kind (seeded bit-flips, "
                         "mid-file torn writes, EIO/ENOSPC from "
                         "append/fsync/rename) against the checksummed "
                         "store — no corrupted record is ever applied, "
                         "damage is detected and quarantined with "
                         "forensics, injected errors fail closed into "
                         "probe-healed degraded mode (invariant I12)")
    ap.add_argument("--no-checksums", action="store_true", default=False,
                    help="run the disk leg against the LEGACY format "
                         "(record CRCs and snapshot digests disabled) — "
                         "the I12 counter-proof: the same seeded "
                         "bit-flip applies silently (use with "
                         "--expect-violation)")
    ap.add_argument("--partition", action="store_true", default=False,
                    help="run ONLY the lying-network leg: seeded "
                         "in-process socket proxies inject one-way "
                         "blackholes, delay, reordering, duplicates, "
                         "slow-drip partial frames and mid-stream RSTs "
                         "on every transport seam — no acked write lost "
                         "or doubled, a router-partitioned leader with "
                         "a fresh lease never false-fails-over, every "
                         "partition detects and heals within a bound, "
                         "and a retry storm at a dark shard leaves the "
                         "healthy shard's p99 intact (invariant I13)")
    ap.add_argument("--no-net-heartbeats", action="store_true",
                    default=False,
                    help="run the partition leg WITHOUT app-level "
                         "ping/pong heartbeats or read deadlines — the "
                         "I13 counter-proof: a one-way s2c blackhole "
                         "wedges the ship connection half-open and the "
                         "follower's lag grows silently (use with "
                         "--expect-violation)")
    ap.add_argument("--out", default=os.path.join(REPO_ROOT, "CHAOS.json"))
    args = ap.parse_args(argv)

    if (args.preempt_storm or args.no_elastic or args.grow or args.no_grow
            or args.gray):
        # The elastic leg shards real arrays over host devices; the flag
        # must be set before ANY jax import in this process.
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()

    from cron_operator_tpu.runtime.faults import FaultPlan

    crash = not args.no_crash
    # Determinism of the fault trace: the schedule expansion is a pure
    # function of the plan — expand twice from fresh objects and compare.
    plan_a = FaultPlan.default_chaos(args.seed)
    plan_b = FaultPlan.default_chaos(args.seed)
    if crash:
        plan_a = replace(plan_a, kill_prob=KILL_PROB)
        plan_b = replace(plan_b, kill_prob=KILL_PROB)
    deterministic = (
        plan_a.schedule(args.rounds) == plan_b.schedule(args.rounds)
        and plan_a.trace_hash(args.rounds) == plan_b.trace_hash(args.rounds)
    )

    if args.disk:
        checksums = not args.no_checksums
        # At least one full cycle through the six fault kinds.
        rounds = max(6, args.rounds)
        mode = ("disk" if checksums
                else "disk counter-proof (checksums OFF)")
        print(
            f"chaos soak ({mode}): seed={args.seed} rounds={rounds} — "
            "bit-flips, torn writes, EIO/ENOSPC through the syscall seam",
            flush=True,
        )
        ev = run_disk_soak(args.seed, rounds, checksums=checksums)
        if not checksums:
            violated = bool(ev["book_violation_rounds"])
            report = {
                "seed": args.seed,
                "mode": "disk-no-checksums",
                "rounds": rounds,
                "disk_leg": ev,
                "violation_rounds": ev["book_violation_rounds"],
                "violation_observed": violated,
                "ok": not violated,
            }
            with open(args.out, "w") as f:
                json.dump(report, f, indent=2, default=str)
                f.write("\n")
            print(
                f"  I12a book check violated in round(s) "
                f"{ev['book_violation_rounds']} of {rounds}"
            )
            print(f"wrote {args.out}")
            if args.expect_violation:
                if violated:
                    print("expected violation observed (I12a) — without "
                          "record CRCs the seeded bit-flip was applied "
                          "SILENTLY: the recovered store no longer "
                          "matches the acknowledged history")
                    return 0
                print("ERROR: expected an I12a violation but every "
                      "recovery matched the acked ledger")
                return 1
            return 0 if not violated else 1
        invariants = check_disk_invariants(ev)
        ok = all(v["ok"] for v in invariants.values())
        report = {
            "seed": args.seed,
            "mode": "disk",
            "rounds": rounds,
            "disk_leg": ev,
            "invariants": invariants,
            "ok": ok,
        }
        # Fold into an existing CHAOS.json from another leg (the
        # processes/gray-leg idiom) so the report carries every proof.
        out_doc = report
        try:
            with open(args.out) as f:
                existing = json.load(f)
            if (isinstance(existing, dict)
                    and existing.get("mode") != "disk"
                    and "invariants" in existing):
                existing["disk"] = report
                existing["ok"] = bool(existing.get("ok")) and ok
                out_doc = existing
        except (OSError, ValueError):
            pass
        with open(args.out, "w") as f:
            json.dump(out_doc, f, indent=2, default=str)
            f.write("\n")
        for name, v in invariants.items():
            mark = "PASS" if v["ok"] else "FAIL"
            print(f"  [{mark}] {name}: {v['detail']}")
        print(f"wrote {args.out} (ok={ok})")
        return 0 if ok else 1

    if args.partition:
        hb = not args.no_net_heartbeats
        rounds = max(4, min(args.rounds, 8))  # bounded wall time per run
        mode = ("partition" if hb
                else "partition counter-proof (net heartbeats OFF)")
        print(
            f"chaos soak ({mode}): seed={args.seed} rounds={rounds} — "
            "one-way blackholes, delay, reorder, duplicates, slow-drip, "
            "RSTs through in-process socket proxies",
            flush=True,
        )
        ev = run_partition_soak(args.seed, rounds, net_heartbeats=hb)
        if not hb:
            wedge = ev.get("wedge") or {}
            violated = bool(wedge.get("wedged"))
            report = {
                "seed": args.seed,
                "mode": "partition-no-heartbeats",
                "rounds": rounds,
                "partition_leg": ev,
                "wedge": wedge,
                "violation_observed": violated,
                "ok": not violated,
            }
            with open(args.out, "w") as f:
                json.dump(report, f, indent=2, default=str)
                f.write("\n")
            print(
                f"  half-open wedge: round={wedge.get('round')} "
                f"direction={wedge.get('direction')} "
                f"reconnects={wedge.get('reconnects_after_heal')} "
                f"replica_lag={wedge.get('replica_lag')} "
                f"converged={wedge.get('converged')}"
            )
            print(f"wrote {args.out}")
            if args.expect_violation:
                if violated:
                    print("expected violation observed (I13c) — without "
                          "heartbeats/read deadlines the one-way "
                          "blackhole left the ship connection half-open "
                          "FOREVER: the follower never re-dialed after "
                          "the heal and its lag grew silently")
                    return 0
                print("ERROR: expected a half-open wedge but the "
                      "follower detected the partition anyway")
                return 1
            return 0 if not violated else 1
        invariants = check_partition_invariants(ev)
        ok = all(v["ok"] for v in invariants.values())
        report = {
            "seed": args.seed,
            "mode": "partition",
            "rounds": rounds,
            "partition_leg": ev,
            "invariants": invariants,
            "ok": ok,
        }
        # Fold into an existing CHAOS.json from another leg (the
        # disk/processes/gray-leg idiom) so one report carries every
        # proof.
        out_doc = report
        try:
            with open(args.out) as f:
                existing = json.load(f)
            if (isinstance(existing, dict)
                    and existing.get("mode") != "partition"
                    and "invariants" in existing):
                existing["partition"] = report
                existing["ok"] = bool(existing.get("ok")) and ok
                out_doc = existing
        except (OSError, ValueError):
            pass
        with open(args.out, "w") as f:
            json.dump(out_doc, f, indent=2, default=str)
            f.write("\n")
        for name, v in invariants.items():
            mark = "PASS" if v["ok"] else "FAIL"
            print(f"  [{mark}] {name}: {v['detail']}")
        print(f"wrote {args.out} (ok={ok})")
        return 0 if ok else 1

    if args.processes:
        shards = args.shards if args.shards > 0 else 2
        n_crons = min(args.crons, 120)  # wire CRUD, not an HTTP bench
        print(
            f"chaos soak (processes): seed={args.seed} crons={n_crons} "
            f"rounds={args.rounds} shards={shards} "
            f"lease_ttl={args.lease_ttl}s — literal SIGKILL per round",
            flush=True,
        )
        ev = run_process_soak(args.seed, n_crons, args.rounds, shards,
                              lease_ttl_s=args.lease_ttl)
        invariants = check_process_invariants(ev)
        ok = all(v["ok"] for v in invariants.values())
        report = {
            "seed": args.seed,
            "mode": "processes",
            "rounds": args.rounds,
            "shards": shards,
            "processes_leg": ev,
            "invariants": invariants,
            "ok": ok,
        }
        # If --out already holds a classic single-process soak report
        # (make chaos-soak writes that leg first), fold this one in
        # under "processes" so CHAOS.json carries both, with a combined
        # top-level ok.
        out_doc = report
        try:
            with open(args.out) as f:
                existing = json.load(f)
            if (isinstance(existing, dict)
                    and existing.get("mode") != "processes"):
                existing["processes"] = report
                existing["ok"] = bool(existing.get("ok")) and ok
                out_doc = existing
        except (OSError, ValueError):
            pass
        with open(args.out, "w") as f:
            json.dump(out_doc, f, indent=2, default=str)
            f.write("\n")
        for name, v in invariants.items():
            mark = "PASS" if v["ok"] else "FAIL"
            print(f"  [{mark}] {name}: {v['detail']}")
        print(f"wrote {args.out} (ok={ok})")
        return 0 if ok else 1

    if args.split:
        fencing = not args.no_fencing
        rounds = max(2, args.rounds)
        mode = "split" if fencing else "split counter-proof (fencing OFF)"
        print(
            f"chaos soak ({mode}): seed={args.seed} crons={args.crons} "
            f"rounds={rounds}",
            flush=True,
        )
        ev = run_split_soak(args.seed, args.crons, rounds, fencing=fencing)
        check = check_split_invariants(ev)
        invariants = check["invariants"]
        ok = check["ok"]
        report = {
            "seed": args.seed,
            "mode": "split" if fencing else "split-no-fencing",
            "rounds": rounds,
            "fencing": fencing,
            "split_leg": ev,
            "invariants": invariants,
            "ok": ok,
        }
        with open(args.out, "w") as f:
            json.dump(report, f, indent=2, default=str)
            f.write("\n")
        print(
            f"  {len(ev['splits'])} live splits -> "
            f"{ev['n_shards_final']} shards at map epoch "
            f"{ev['map_epoch_final']}; {ev['acked_writes']} acked "
            f"writes; mid-split kill in round "
            f"{ev['kill_mid_split'].get('round')}"
        )
        for name, v in invariants.items():
            mark = "PASS" if v["ok"] else "FAIL"
            print(f"  [{mark}] {name}: {v['detail']}")
        print(f"wrote {args.out} (ok={ok})")
        if args.expect_violation:
            poison = ev.get("poison") or {}
            lost = bool(poison) and not poison.get("visible_after", True)
            if lost:
                print("expected violation observed — without range "
                      f"fencing the demoted parent ACKED "
                      f"{poison.get('name')} during the dark window and "
                      "the split erased it from the routed surface")
                return 0
            print("ERROR: expected an acked-write-lost violation but "
                  "the poison write survived (or was refused)")
            return 1
        return 0 if ok else 1

    if args.no_fencing:
        # I10 counter-proof: the SAME SIGSTOP/promote/SIGCONT rounds with
        # fencing disabled. The woken zombie still notices its lost lease
        # (satellite demotion) but its persistence keeps accepting
        # appends — the poison write must land as a stale-generation
        # record in the WAL inode the promoted leader now owns.
        rounds = max(2, min(args.rounds, 4))
        print(
            f"chaos soak (fencing counter-proof): seed={args.seed} "
            f"rounds={rounds} lease_ttl={args.lease_ttl}s — fencing OFF",
            flush=True,
        )
        ev = run_gray_soak(args.seed, rounds, fencing=False,
                           lease_ttl_s=args.lease_ttl)
        landed = [
            r for r in ev["fencing_rounds"]
            if not r.get("poison_refused")
            and (int((r.get("wal_scan") or {}).get("stale_records") or 0) > 0
                 or int((r.get("wal_scan") or {}).get("corrupt_lines") or 0)
                 > 0)
        ]
        violated = bool(landed)
        report = {
            "seed": args.seed,
            "mode": "no-fencing",
            "rounds": rounds,
            "gray_leg": ev,
            "stale_write_rounds": [r["round"] for r in landed],
            "violation_observed": violated,
            "ok": not violated,
        }
        with open(args.out, "w") as f:
            json.dump(report, f, indent=2, default=str)
            f.write("\n")
        print(
            f"  stale-generation writes landed in "
            f"{len(landed)}/{len(ev['fencing_rounds'])} round(s)"
        )
        print(f"wrote {args.out}")
        if args.expect_violation:
            if violated:
                print("expected violation observed (I10) — without "
                      "fencing the zombie leader's post-demotion write "
                      "reached the shared WAL")
                return 0
            print("ERROR: expected an I10 violation but every poison "
                  "write missed the WAL")
            return 1
        return 0 if not violated else 1

    if args.gray:
        rounds = max(4, min(args.rounds, 8))
        print(
            f"chaos soak (gray failures): seed={args.seed} "
            f"rounds={rounds} lease_ttl={args.lease_ttl}s — "
            "SIGSTOP zombies, fencing, breakers, hang watchdogs",
            flush=True,
        )
        ev = run_gray_soak(args.seed, rounds, fencing=True,
                           lease_ttl_s=args.lease_ttl)
        invariants = check_gray_invariants(ev)
        ok = all(v["ok"] for v in invariants.values())
        report = {
            "seed": args.seed,
            "mode": "gray",
            "rounds": rounds,
            "gray_leg": ev,
            "invariants": invariants,
            "ok": ok,
        }
        # Fold into an existing CHAOS.json from another leg (the
        # processes-leg idiom) so the report carries every proof.
        out_doc = report
        try:
            with open(args.out) as f:
                existing = json.load(f)
            if (isinstance(existing, dict)
                    and existing.get("mode") != "gray"
                    and "invariants" in existing):
                existing["gray"] = report
                existing["ok"] = bool(existing.get("ok")) and ok
                out_doc = existing
        except (OSError, ValueError):
            pass
        with open(args.out, "w") as f:
            json.dump(out_doc, f, indent=2, default=str)
            f.write("\n")
        for name, v in invariants.items():
            mark = "PASS" if v["ok"] else "FAIL"
            print(f"  [{mark}] {name}: {v['detail']}")
        print(f"wrote {args.out} (ok={ok})")
        return 0 if ok else 1

    if args.fleet_flap:
        # Standalone fleet leg: the heterogeneity-aware scheduler under
        # capacity flaps. Simulated workloads (cheap) but the REAL store,
        # executor, reconciler, fleet books, and elastic-resume chain.
        print(
            f"chaos soak (fleet capacity-flap): seed={args.seed} "
            f"crons={args.crons} rounds={args.rounds}",
            flush=True,
        )
        ev = run_fleet_soak(args.seed, args.crons, args.rounds)
        invariants = check_fleet_invariants(ev)
        ok = all(v["ok"] for v in invariants.values())
        report = {
            "seed": args.seed,
            "mode": "fleet-flap",
            "rounds": args.rounds,
            "deterministic_trace": deterministic,
            "fleet_leg": ev,
            "invariants": invariants,
            "ok": ok,
        }
        if args.grow:
            # Bidirectional-elasticity pair: grow-enabled leg, then the
            # shrink-only baseline from the SAME seed/scenario. The
            # goodput margin is the perf claim; F1-F4 are correctness.
            print("  grow leg: GrowPlanner ON (real training)",
                  flush=True)
            grow_ev = run_grow_soak(args.seed, grow=True)
            print(
                f"    done in {grow_ev['elapsed_s']}s "
                f"grows={grow_ev['metrics']['fleet_grows']} "
                f"shrinks={grow_ev['metrics']['fleet_shrinks']}",
                flush=True,
            )
            print("  baseline leg: GrowPlanner OFF (shrink-only)",
                  flush=True)
            nogrow_ev = run_grow_soak(args.seed, grow=False)
            print(f"    done in {nogrow_ev['elapsed_s']}s", flush=True)
            grow_inv = check_grow_invariants(grow_ev)
            for e in (grow_ev, nogrow_ev):
                shutil.rmtree(e.pop("ckpt_root", ""), ignore_errors=True)
            gp = compute_grow_goodput(grow_ev)
            ngp = compute_grow_goodput(nogrow_ev)
            margin = (
                round(gp["tokens_per_s"] / ngp["tokens_per_s"], 3)
                if ngp["tokens_per_s"] else 0.0
            )
            goodput = {
                "grow": gp,
                "shrink_only": ngp,
                "margin": margin,
                "floor": GROW_MARGIN_FLOOR,
                "idle_gap_chip_s": {
                    "grow": grow_ev["idle_gap_chip_s"],
                    "shrink_only": nogrow_ev["idle_gap_chip_s"],
                },
                "ok": margin >= GROW_MARGIN_FLOOR,
            }
            grow_ok = (
                all(v["ok"] for v in grow_inv.values()) and goodput["ok"]
            )
            report["grow"] = {
                "grow_leg": grow_ev,
                "shrink_only_leg": nogrow_ev,
                "invariants": grow_inv,
                "goodput": goodput,
                "ok": grow_ok,
            }
            ok = ok and grow_ok
            report["ok"] = ok
            for name, v in grow_inv.items():
                mark = "PASS" if v["ok"] else "FAIL"
                print(f"  [{mark}] {name}: {v['detail']}")
            mark = "PASS" if goodput["ok"] else "FAIL"
            print(
                f"  [{mark}] goodput_margin: grow "
                f"{gp['tokens_per_s']} tok/s vs shrink-only "
                f"{ngp['tokens_per_s']} tok/s = {margin}x "
                f"(floor {GROW_MARGIN_FLOOR}x)"
            )
        # If --out already holds a classic soak report, fold this leg in
        # (the processes-leg idiom) so CHAOS.json carries both.
        out_doc = report
        try:
            with open(args.out) as f:
                existing = json.load(f)
            if (isinstance(existing, dict)
                    and existing.get("mode") != "fleet-flap"
                    and "invariants" in existing):
                existing["fleet"] = report
                existing["ok"] = bool(existing.get("ok")) and ok
                out_doc = existing
        except (OSError, ValueError):
            pass
        with open(args.out, "w") as f:
            json.dump(out_doc, f, indent=2, default=str)
            f.write("\n")
        for name, v in invariants.items():
            mark = "PASS" if v["ok"] else "FAIL"
            print(f"  [{mark}] {name}: {v['detail']}")
        print(f"wrote {args.out} (ok={ok})")
        return 0 if ok else 1

    if args.no_grow:
        # Counter-proof: the SAME grow scenario with the GrowPlanner off.
        # The elastic gang trains at its launch width while wider slices
        # sit idle — the integrated idle gap must be measurably large,
        # the capacity a grow would have reclaimed.
        print(
            f"chaos soak (grow counter-proof): seed={args.seed} "
            "GrowPlanner disabled",
            flush=True,
        )
        ev = run_grow_soak(args.seed, grow=False)
        shutil.rmtree(ev.pop("ckpt_root", ""), ignore_errors=True)
        gap = ev["idle_gap_chip_s"]
        run = ev["runs"].get(GROW_CRON) or {}
        chain = run.get("chain") or []
        finished = bool(chain) and chain[-1]["terminal"] == "Succeeded"
        grew = any(a.get("cause") == "grow" for a in chain)
        gap_left = finished and not grew and gap >= GROW_IDLE_GAP_FLOOR_CHIP_S
        report = {
            "seed": args.seed,
            "mode": "no-grow",
            "grow_scenario_leg": ev,
            "idle_gap_chip_s": gap,
            "idle_gap_floor_chip_s": GROW_IDLE_GAP_FLOOR_CHIP_S,
            "gap_left_on_table": gap_left,
            "ok": not gap_left,
        }
        with open(args.out, "w") as f:
            json.dump(report, f, indent=2, default=str)
            f.write("\n")
        print(
            f"  idle gap left unreclaimed: {gap} chip-s "
            f"(floor {GROW_IDLE_GAP_FLOOR_CHIP_S}) — job finished at "
            f"width {chain[-1]['devices'] if chain else '?'}"
        )
        print(f"wrote {args.out}")
        if args.expect_violation:
            if gap_left:
                print("expected violation observed — shrink-only left "
                      f"{gap} idle chip-seconds on the table that the "
                      "GrowPlanner would have reclaimed")
                return 0
            print("ERROR: expected an idle-gap violation but shrink-only "
                  "left none (gap below floor or the job grew)")
            return 1
        return 0 if not gap_left else 1

    if args.no_elastic:
        # Counter-proof mode: ONLY the elastic leg, with elastic resume
        # disabled. The jobs recover via in-place restart with no
        # checkpoint, so a preempted run re-trains from step 0 — I8's
        # "loses at most one checkpoint interval" must demonstrably fail.
        print(
            f"chaos soak (elastic counter-proof): seed={args.seed} "
            f"jobs={args.elastic_jobs} rounds={args.rounds}",
            flush=True,
        )
        ev = run_preempt_soak(
            args.seed, args.elastic_jobs, args.rounds, elastic=False
        )
        i8 = check_i8(ev)
        invariants = {"I8_elastic_resume": i8}
        report = {
            "seed": args.seed,
            "mode": "no-elastic",
            "rounds": args.rounds,
            "elastic_leg": ev,
            "invariants": invariants,
            "ok": i8["ok"],
        }
        with open(args.out, "w") as f:
            json.dump(report, f, indent=2, default=str)
            f.write("\n")
        mark = "PASS" if i8["ok"] else "FAIL"
        print(f"  [{mark}] I8_elastic_resume: {i8['detail']}")
        print(f"wrote {args.out} (ok={i8['ok']})")
        if args.expect_violation:
            if not i8["ok"]:
                print("expected violation observed (I8) — without elastic "
                      "resume, preempted runs restart from step 0")
                return 0
            print("ERROR: expected an I8 violation but the leg passed")
            return 1
        return 0 if i8["ok"] else 1

    if args.shards > 0:
        if (args.unhardened or args.no_crash or args.no_durability
                or args.data_dir or args.preempt_storm):
            print("ERROR: --shards is incompatible with --unhardened/"
                  "--no-crash/--no-durability/--data-dir/--preempt-storm "
                  "(the sharded "
                  "soak is always hardened, crashy, and durable: WAL "
                  "bytes are the follower-shipping medium)")
            return 2
        print(
            f"chaos soak (sharded): seed={args.seed} crons={args.crons} "
            f"rounds={args.rounds} shards={args.shards} replicas=1",
            flush=True,
        )
        chaotic = run_sharded_soak(
            args.seed, args.crons, args.rounds, args.shards,
            workers=args.workers, chaotic=True,
            quiesce_timeout_s=args.quiesce_timeout,
        )
        print(
            f"  chaotic run: {chaotic['elapsed_s']}s "
            f"faults={chaotic['faults_injected']} "
            f"dropped_events={chaotic['dropped_watch_events']} "
            f"failovers={chaotic['failovers']} "
            f"kills={[k['point'] for k in chaotic['kills']]}",
            flush=True,
        )
        replay = run_sharded_soak(
            args.seed, args.crons, args.rounds, args.shards,
            workers=args.workers, chaotic=False,
            quiesce_timeout_s=args.quiesce_timeout,
        )
        print(f"  replay run: {replay['elapsed_s']}s", flush=True)

        invariants = check_invariants(chaotic, replay, HISTORY_LIMIT)
        ok = all(v["ok"] for v in invariants.values()) and deterministic
        report = {
            "seed": args.seed,
            "n_crons": args.crons,
            "rounds": args.rounds,
            "workers": args.workers,
            "shards": args.shards,
            "replicas": 1,
            "crash": True,
            "durability": True,
            "deterministic_schedule": deterministic,
            "fault_trace_hash": chaotic["fault_trace_hash"],
            "fault_schedule": chaotic["fault_schedule"],
            "faults_injected": chaotic["faults_injected"],
            "dropped_watch_events": chaotic["dropped_watch_events"],
            "lost_flips": chaotic["lost_flips"],
            "quiesce_timeouts": chaotic["quiesce_timeouts"],
            "readyz_degraded_seen": chaotic["readyz_degraded_seen"],
            "leadership_lost_seen": chaotic["leadership_lost_seen"],
            "kills": chaotic["kills"],
            "failovers": chaotic["failovers"],
            "generations": chaotic["generations"],
            "refires": chaotic["refires"],
            "orphans": chaotic["orphans"],
            "resurrections": chaotic["resurrections"],
            "phantom_deletes": chaotic.get("phantom_deletes", []),
            "wal": chaotic["wal"],
            "audit_checks": chaotic.get("audit_checks", []),
            "follower_lag": chaotic.get("follower_lag"),
            "debug_shards": chaotic.get("debug_shards"),
            "metrics": chaotic["metrics"],
            "elapsed_s": {
                "chaotic": chaotic["elapsed_s"],
                "replay": replay["elapsed_s"],
            },
            "invariants": invariants,
            "ok": ok,
        }
        if not invariants["I5_matches_fault_free_replay"]["ok"]:
            report["surface_chaotic"] = chaotic["surface"]
            report["surface_replay"] = replay["surface"]
        with open(args.out, "w") as f:
            json.dump(report, f, indent=2, default=str)
            f.write("\n")
        for name, v in invariants.items():
            mark = "PASS" if v["ok"] else "FAIL"
            print(f"  [{mark}] {name}: {v['detail']}")
        print(f"wrote {args.out} (ok={ok})")
        return 0 if ok else 1

    print(
        f"chaos soak: seed={args.seed} crons={args.crons} "
        f"rounds={args.rounds} unhardened={args.unhardened} "
        f"crash={crash} durability={not args.no_durability}",
        flush=True,
    )
    chaotic = run_soak(
        args.seed, args.crons, args.rounds, workers=args.workers,
        chaotic=True, unhardened=args.unhardened,
        quiesce_timeout_s=args.quiesce_timeout,
        crash=crash, durability=not args.no_durability,
        data_dir=args.data_dir,
    )
    print(
        f"  chaotic run: {chaotic['elapsed_s']}s "
        f"faults={chaotic['faults_injected']} "
        f"dropped_events={chaotic['dropped_watch_events']} "
        f"lost_flips={chaotic['lost_flips']} "
        f"kills={[k['point'] for k in chaotic['kills']]}",
        flush=True,
    )
    replay = run_soak(
        args.seed, args.crons, args.rounds, workers=args.workers,
        chaotic=False, unhardened=False,
        quiesce_timeout_s=args.quiesce_timeout,
        crash=crash, durability=not args.no_durability,
    )
    print(f"  replay run: {replay['elapsed_s']}s", flush=True)

    invariants = check_invariants(chaotic, replay, HISTORY_LIMIT)

    elastic_ev = None
    if args.preempt_storm:
        print(
            f"  elastic leg: jobs={args.elastic_jobs} "
            f"rounds={args.rounds} (real CPU-mesh training)",
            flush=True,
        )
        elastic_ev = run_preempt_soak(
            args.seed, args.elastic_jobs, args.rounds, elastic=True
        )
        print(
            f"  elastic leg: {elastic_ev['elapsed_s']}s "
            f"preempts={len(elastic_ev['preempt_events'])} "
            f"resumes={int(elastic_ev['metrics']['resumes'])}",
            flush=True,
        )
        invariants["I8_elastic_resume"] = check_i8(elastic_ev)

        # I9's goodput leg: under the storm, productive steps must
        # dominate re-trained waste across every attempt chain.
        goodput = compute_goodput(elastic_ev)
        gp_detail = (
            f"goodput {goodput['overall']} >= floor {GOODPUT_FLOOR} "
            f"across {len(goodput['per_chain'])} attempt chain(s) under "
            "the preempt storm"
        )
        i9 = invariants.get("I9_flight_recorder")
        if i9 is None:
            invariants["I9_flight_recorder"] = {
                "ok": goodput["ok"],
                "detail": gp_detail if goodput["ok"] else {
                    "goodput": goodput,
                },
                "goodput": goodput,
            }
        else:
            i9["ok"] = i9["ok"] and goodput["ok"]
            i9["goodput"] = goodput
            if goodput["ok"] and isinstance(i9["detail"], str):
                i9["detail"] += "; " + gp_detail
            elif not goodput["ok"]:
                i9["detail"] = {"audit": i9["detail"], "goodput": goodput}

    ok = all(v["ok"] for v in invariants.values()) and deterministic

    report = {
        "seed": args.seed,
        "n_crons": args.crons,
        "rounds": args.rounds,
        "workers": args.workers,
        "unhardened": args.unhardened,
        "crash": crash,
        "durability": not args.no_durability,
        "deterministic_schedule": deterministic,
        "fault_trace_hash": chaotic["fault_trace_hash"],
        "fault_schedule": chaotic["fault_schedule"],
        "faults_injected": chaotic["faults_injected"],
        "dropped_watch_events": chaotic["dropped_watch_events"],
        "lost_flips": chaotic["lost_flips"],
        "quiesce_timeouts": chaotic["quiesce_timeouts"],
        "readyz_degraded_seen": chaotic["readyz_degraded_seen"],
        "leadership_lost_seen": chaotic["leadership_lost_seen"],
        "kills": chaotic["kills"],
        "generations": chaotic["generations"],
        "refires": chaotic["refires"],
        "orphans": chaotic["orphans"],
        "resurrections": chaotic["resurrections"],
        "phantom_deletes": chaotic.get("phantom_deletes", []),
        "wal": chaotic["wal"],
        "audit_checks": chaotic.get("audit_checks", []),
        "metrics": chaotic["metrics"],
        "elapsed_s": {
            "chaotic": chaotic["elapsed_s"],
            "replay": replay["elapsed_s"],
        },
        "invariants": invariants,
        "ok": ok,
    }
    if elastic_ev is not None:
        report["elastic_leg"] = elastic_ev
    # The full surfaces are bulky at N>=200; persist only on divergence.
    if not invariants["I5_matches_fault_free_replay"]["ok"]:
        report["surface_chaotic"] = chaotic["surface"]
        report["surface_replay"] = replay["surface"]

    with open(args.out, "w") as f:
        json.dump(report, f, indent=2, default=str)
        f.write("\n")

    for name, v in invariants.items():
        mark = "PASS" if v["ok"] else "FAIL"
        print(f"  [{mark}] {name}: {v['detail']}")
    print(f"wrote {args.out} (ok={ok})")

    if args.expect_violation:
        violated = [k for k, v in invariants.items() if not v["ok"]]
        if args.no_durability and not any(
            k.startswith("I7") for k in violated
        ):
            print("ERROR: expected an I7 violation without durability "
                  f"but got {violated or 'none'}")
            return 1
        if violated:
            print(f"expected violation observed ({violated}) — the "
                  "demonstrated mode genuinely breaks an invariant")
            return 0
        print("ERROR: expected an invariant violation but all passed")
        return 1
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
