"""Reproducible HTTP front-door benchmark (``make bench-http``).

Measures the three production properties the front door claims, against
the REAL server — ``HTTPAPIServer`` with its selector fan-out loop, APF
admission, and group-commit durable writes — using raw client sockets
and ``http.client``, not mocks:

- **watch fan-out**: W watchers on one kind, E creates published; the
  client drains every stream through one selector loop and counts
  delivered frames. Headline: delivered events/s and the hub's encode
  count (must be exactly E — one JSON encode per event, shared across
  all W streams). ``--baseline-ref <git-ref>`` replays the identical
  scenario against a detached worktree of that ref (the pre-fan-out
  thread-per-connection server) and reports the speedup with an
  OK/REGRESSION verdict (gate: >= 5x). Without a baseline tree the
  artifact still carries ``legacy_model_events_per_s`` — the measured
  cost of the old per-watcher deepcopy+dumps encode path, CPU only
  (no socket sends), so it flatters the legacy side and is reported
  for context rather than gated.
- **write fan-in**: open-loop paced HTTP POST writers (each request
  waits for WAL durability before 201), scaled 1 -> N concurrent
  writers at constant per-writer rate. Group commit must hold p99
  within 2x of the single-writer p99 (plus a small absolute floor for
  scheduler noise at millisecond scale) while sharing fsyncs — the
  artifact reports fsyncs per durable write at N writers.
- **APF fairness**: a quiet tenant issuing paced gets of one large
  object while a noisy tenant floods 50x+ more cheap gets through the
  SAME priority level. Per-flow round-robin must keep the quiet
  tenant's p99 within max(20%, two dispatch quanta) of its undisturbed
  p99, the measured flood must really clear the 50x ratio, and a
  single-flow FIFO control run reports what the quiet tenant's p99
  looks like without fairness.
- **zero steady-state writes**: a read-only phase (lists, gets, a live
  watch) brackets the store's resourceVersion counter and the WAL's
  record count; both deltas must be zero.
- **distributed sweep**: the real multi-process topology — N shard
  processes (own store + WAL each) behind the consistent-hash router
  process. Watch streams on the router must deliver every event fanned
  in from the shards, and the routed closed-loop durable-create
  aggregate must stay within 20% of the shared-nothing sum (the same
  load driven directly at every shard concurrently, rates summed).
- **follower fan-out**: one shard leader, R follower read doors over
  its WAL ship, and the router fronting all of them. Per-door LIST and
  watch capacity is measured in isolation and summed (single-core
  host — see the leg docstring), gated at >= R x the leader-only door;
  1k write-then-list pairs through the router must see zero stale
  reads (rv barriers); the leader's durable write rate with replicas
  attached and point-read trickle live must hold within 5% of its
  no-replica baseline.

- **live shard split**: durable-write throughput on one boot shard,
  then a LIVE 1->2 keyspace split under a write storm (dark window and
  zero lost/double-applied acked writes measured), then the summed
  per-shard post-split rate — gated >= 1.8x the pre-split rate with a
  <= 2s dark window.

Writes ``BENCH_HTTP.json`` with per-scenario OK/REGRESSION verdicts and
an overall verdict; ``--check`` exits non-zero on REGRESSION and is the
CI smoke leg (small sizes, no baseline worktree).
"""

from __future__ import annotations

import argparse
import gc
import json
import os
import selectors
import shutil
import socket
import subprocess
import sys
import tempfile
import threading
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
# Code under test: an explicit tree (baseline subprocess) or this repo.
_TREE = os.environ.get("HTTPBENCH_TREE", REPO_ROOT)
sys.path.insert(0, _TREE)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

CRON_AV = "apps.kubedl.io/v1alpha1"
TOKEN = "bench-token"
# One JSON frame per event; both the old and the new server emit
# default-separator json.dumps payloads, so this marker counts ADDED
# frames on either side of an A/B run.
ADDED_MARKER = b'"type": "ADDED"'

# Latency-ratio gates carry a small absolute floor: at millisecond
# baselines a single scheduler hiccup swamps a pure ratio, so the gate
# is `p99_after <= max(ratio * p99_before, p99_before + floor_ms)`.
WRITE_P99_RATIO = 2.0
WRITE_P99_FLOOR_MS = 5.0
FAIRNESS_P99_RATIO = 1.2
FAIRNESS_P99_FLOOR_MS = 2.0
# The fairness claim is only meaningful if the flood really is a flood:
# the noisy tenant must land at least this many requests per quiet one.
FAIRNESS_MIN_RATE_RATIO = 50.0
FANOUT_MIN_SPEEDUP = 5.0
# Follower read plane: with R added replicas the read path's aggregate
# capacity (leader door + R follower doors, each measured at full tilt)
# must clear R x the leader-only door, and the leader's durable write
# throughput must stay within this tolerance of its no-replica baseline
# while the doors serve reads.
FOLLOWER_MIN_READ_SCALE = 3.0
FOLLOWER_WRITE_TOLERANCE = 0.05
# Live shard split: after a 1->2 split the summed per-shard durable
# write rate (sequential, shared-nothing projection — same methodology
# as make bench-shards) must clear this multiple of the pre-split
# single-shard rate, and the split's dark window (fence -> publish)
# must stay under the bound.
SPLIT_MIN_SCALEUP = 1.8
SPLIT_MAX_DARK_WINDOW_S = 2.0


def _cron(name: str, schedule: str = "@every 1h") -> dict:
    return {
        "apiVersion": CRON_AV, "kind": "Cron",
        "metadata": {"name": name, "namespace": "default"},
        "spec": {"schedule": schedule, "template": {"workload": {
            "apiVersion": "kubeflow.org/v1", "kind": "JAXJob",
            "spec": {"replicaSpecs": {"Worker": {"replicas": 1}}},
        }}},
    }


def _p99(samples_ms):
    if not samples_ms:
        return None
    ordered = sorted(samples_ms)
    idx = min(len(ordered) - 1, int(0.99 * len(ordered)))
    return round(ordered[idx], 3)


def _p50(samples_ms):
    if not samples_ms:
        return None
    ordered = sorted(samples_ms)
    return round(ordered[len(ordered) // 2], 3)


def _make_server(**kwargs):
    """Construct HTTPAPIServer passing only the kwargs this tree's
    constructor knows — the baseline worktree predates tokens/admission/
    metrics/durable_writes."""
    import inspect

    from cron_operator_tpu.runtime.apiserver_http import HTTPAPIServer

    sig = inspect.signature(HTTPAPIServer.__init__)
    accepted = {k: v for k, v in kwargs.items() if k in sig.parameters}
    return HTTPAPIServer(**accepted)


def _git_ref(tree: str) -> str:
    try:
        ref = subprocess.run(
            ["git", "-C", tree, "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, check=True,
        ).stdout.strip()
        porcelain = subprocess.run(
            ["git", "-C", tree, "status", "--porcelain"],
            capture_output=True, text=True, check=True,
        ).stdout.strip()
        return f"{ref}-dirty" if porcelain else ref
    except Exception:
        return "unknown"


# ---------------------------------------------------------------------------
# Scenario 1: watch fan-out
# ---------------------------------------------------------------------------

def _open_watch_socket(host: str, port: int, rv: int = 0) -> socket.socket:
    s = socket.create_connection((host, port), timeout=30)
    req = (
        f"GET /apis/{CRON_AV}/namespaces/default/crons"
        f"?watch=true&resourceVersion={rv} HTTP/1.1\r\n"
        f"Host: {host}\r\nAuthorization: Bearer {TOKEN}\r\n\r\n"
    )
    s.sendall(req.encode())
    buf = b""
    while b"\r\n\r\n" not in buf:
        chunk = s.recv(4096)
        if not chunk:
            raise RuntimeError("watch socket closed during establishment")
        buf += chunk
    head, _, rest = buf.partition(b"\r\n\r\n")
    status_line = head.split(b"\r\n", 1)[0]
    if b" 200 " not in status_line:
        raise RuntimeError(f"watch rejected: {status_line!r}")
    s.setblocking(False)
    return s, rest


def fanout_leg(watchers: int, events: int, timeout_s: float) -> dict:
    """W streams, E creates: count every delivered ADDED frame through
    one client-side selector loop. Works identically against the old
    thread-per-connection server and the new shared-encode fan-out."""
    srv = _make_server(token=TOKEN)
    srv.start()
    host, port = srv._server.server_address[0], srv.port
    socks = []
    t0 = time.perf_counter()
    try:
        pairs = [_open_watch_socket(host, port) for _ in range(watchers)]
        socks = [s for s, _ in pairs]
        establish_s = time.perf_counter() - t0

        sel = selectors.DefaultSelector()
        counts = {}
        for s, carry in pairs:
            counts[s] = carry.count(ADDED_MARKER)
            sel.register(s, selectors.EVENT_READ,
                         carry[-(len(ADDED_MARKER) - 1):])

        expected = watchers * events
        delivered = sum(counts.values())
        t0 = time.perf_counter()
        for i in range(events):
            srv.api.create(_cron(f"fan-{i}"))
        deadline = t0 + timeout_s
        while delivered < expected and time.perf_counter() < deadline:
            for key, _ in sel.select(timeout=0.5):
                s = key.fileobj
                try:
                    data = s.recv(1 << 16)
                except (BlockingIOError, InterruptedError):
                    continue
                except OSError:
                    sel.unregister(s)
                    continue
                if not data:
                    sel.unregister(s)
                    continue
                combined = key.data + data
                counts[s] += combined.count(ADDED_MARKER) - \
                    key.data.count(ADDED_MARKER)
                sel.modify(s, selectors.EVENT_READ,
                           combined[-(len(ADDED_MARKER) - 1):])
            delivered = sum(counts.values())
        elapsed = time.perf_counter() - t0
        sel.close()
    finally:
        for s in socks:
            try:
                s.close()
            except OSError:
                pass
        srv.stop()

    hub = getattr(srv, "hub", None)
    encodes = getattr(hub, "encodes", None)
    out = {
        "watchers": watchers,
        "events": events,
        "expected_frames": expected,
        "delivered_frames": delivered,
        "establish_s": round(establish_s, 3),
        "drain_s": round(elapsed, 3),
        "events_per_s": round(delivered / elapsed, 1) if elapsed else 0.0,
        "timed_out": delivered < expected,
    }
    if encodes is not None:
        out["hub_encodes"] = encodes
        out["encodes_per_event"] = round(encodes / events, 3) if events else 0
    return out


def _legacy_encode_model(watchers: int, events: int) -> float:
    """Measured events/s of the pre-fan-out encode path: deepcopy +
    json.dumps once per watcher per event. CPU cost only — the real old
    server additionally paid a per-frame flush+send and a condition-
    variable thundering herd, so this number FLATTERS the legacy side."""
    import copy

    obj = _cron("model")
    obj["metadata"]["resourceVersion"] = "12345"
    t0 = time.perf_counter()
    for _ in range(events):
        for _ in range(watchers):
            payload = {"type": "ADDED", "object": copy.deepcopy(obj)}
            json.dumps(payload)
    elapsed = time.perf_counter() - t0
    return round(watchers * events / elapsed, 1) if elapsed else 0.0


# ---------------------------------------------------------------------------
# Scenario 2: group-commit write fan-in (+ zero steady-state writes)
# ---------------------------------------------------------------------------

def _post_json(conn, path: str, payload: dict) -> int:
    body = json.dumps(payload)
    conn.request("POST", path, body=body, headers={
        "Authorization": f"Bearer {TOKEN}",
        "Content-Type": "application/json",
    })
    resp = conn.getresponse()
    resp.read()
    return resp.status


def _writer_thread(host, port, path, prefix, count, interval_s, start_at,
                   latencies, errors):
    import http.client

    conn = http.client.HTTPConnection(host, port, timeout=30)
    try:
        time.sleep(max(0.0, start_at - time.monotonic()))
        for j in range(count):
            next_at = start_at + (j + 1) * interval_s
            t0 = time.perf_counter()
            status = _post_json(
                conn, path, _cron(f"{prefix}-{j}"))
            dt_ms = (time.perf_counter() - t0) * 1e3
            if status != 201:
                errors.append(f"{prefix}-{j}: HTTP {status}")
            else:
                latencies.append(dt_ms)
            time.sleep(max(0.0, next_at - time.monotonic()))
    except Exception as exc:  # pragma: no cover — surfaced in artifact
        errors.append(f"{prefix}: {exc!r}")
    finally:
        conn.close()


def _write_round(srv, wal, writers: int, per_writer: int,
                 interval_s: float, tag: str = "paced") -> dict:
    host, port = srv._server.server_address[0], srv.port
    path = f"/apis/{CRON_AV}/namespaces/default/crons"
    latencies, errors = [], []
    fsyncs_before = wal.stats()["fsyncs"]
    records_before = wal.stats()["records_appended"]
    threads = []
    # Stagger starts across one interval so the open-loop offered load
    # is spread, not a synchronized burst every tick.
    base = time.monotonic() + 0.05
    t0 = time.perf_counter()
    for w in range(writers):
        start_at = base + (w / writers) * interval_s
        th = threading.Thread(
            target=_writer_thread,
            args=(host, port, path, f"{tag}{writers}-{w}", per_writer,
                  interval_s, start_at, latencies, errors),
        )
        th.start()
        threads.append(th)
    for th in threads:
        th.join(timeout=120.0)
    elapsed = time.perf_counter() - t0
    stats = wal.stats()
    n_writes = writers * per_writer
    fsyncs = stats["fsyncs"] - fsyncs_before
    return {
        "writers": writers,
        "writes": n_writes,
        "completed": len(latencies),
        "errors": errors[:5],
        "p50_ms": _p50(latencies),
        "p99_ms": _p99(latencies),
        "writes_per_s": round(len(latencies) / elapsed, 1) if elapsed else 0,
        "fsyncs": fsyncs,
        "fsyncs_per_write": round(fsyncs / n_writes, 3) if n_writes else None,
        "wal_records_delta": stats["records_appended"] - records_before,
    }


def write_fanin_leg(writer_counts, per_writer: int,
                    interval_ms: float) -> dict:
    """Open-loop paced durable writers at each concurrency in
    ``writer_counts`` against one WAL-attached server. Every 201 means
    the record survived an fsync (the handler's durability barrier)."""
    from cron_operator_tpu.runtime.apf import (
        FairQueueAdmission,
        LevelConfig,
    )
    from cron_operator_tpu.runtime.kube import APIServer
    from cron_operator_tpu.runtime.persistence import Persistence

    data_dir = tempfile.mkdtemp(prefix="httpbench-wal-")
    api = APIServer()
    # fsync_every high + no flush timer: durability comes ONLY from the
    # per-request group-commit barrier, which is what's being measured.
    wal = Persistence(data_dir, fsync_every=10_000, flush_interval_s=0)
    wal.start(api)
    # Seats sized above peak concurrency: this leg measures the write
    # path (store commit + group fsync), not admission queueing.
    admission = FairQueueAdmission(levels={
        "system": LevelConfig(seats=8, queue_depth=64, max_queued=256),
        "workload": LevelConfig(seats=max(writer_counts) * 2,
                                queue_depth=max(writer_counts) * 4,
                                max_queued=2048),
        "batch": LevelConfig(seats=8, queue_depth=32, max_queued=128),
    })
    srv = _make_server(api=api, token=TOKEN, admission=admission)
    srv.start()
    try:
        rounds = [
            _write_round(srv, wal, n, per_writer, interval_ms / 1e3)
            for n in writer_counts
        ]
        # Closed-loop burst: every writer fires continuously, so
        # durability barriers overlap and MUST share fsyncs — this is
        # the group-commit mechanism made visible (the paced rounds
        # above rarely overlap, so they fsync ~once per write).
        burst = _write_round(srv, wal, writer_counts[-1], per_writer, 0.0,
                             tag="burst")
        steady = _zero_steady_state_leg(srv, api, wal)
    finally:
        srv.stop()
        wal.close()
        api.close()
        shutil.rmtree(data_dir, ignore_errors=True)

    base = rounds[0]
    peak = rounds[-1]
    ratio = None
    if base["p99_ms"] and peak["p99_ms"]:
        ratio = round(peak["p99_ms"] / base["p99_ms"], 2)
    allowed = None
    sharing_ok = (burst["fsyncs_per_write"] is not None
                  and burst["fsyncs_per_write"] < 1.0
                  and not burst["errors"])
    ok = False
    if base["p99_ms"] is not None and peak["p99_ms"] is not None:
        allowed = round(max(WRITE_P99_RATIO * base["p99_ms"],
                            base["p99_ms"] + WRITE_P99_FLOOR_MS), 3)
        ok = peak["p99_ms"] <= allowed and not peak["errors"] and sharing_ok
    verdict = {
        "status": "OK" if ok else "REGRESSION",
        "p99_ratio": ratio,
        "allowed_p99_ms": allowed,
        "burst_fsyncs_per_write": burst["fsyncs_per_write"],
        "summary": (
            f"{'OK' if ok else 'REGRESSION'}: durable write p99 "
            f"{base['p99_ms']}ms @ {base['writers']} writer(s) -> "
            f"{peak['p99_ms']}ms @ {peak['writers']} writers "
            f"({ratio}x, allowed <= {allowed}ms); closed-loop burst at "
            f"{burst['writers']} writers shared fsyncs "
            f"({burst['fsyncs_per_write']} fsyncs/write, need < 1.0)"
        ),
    }
    return {"rounds": rounds, "burst": burst, "interval_ms": interval_ms,
            "verdict": verdict, "zero_steady_state": steady}


def _zero_steady_state_leg(srv, api, wal) -> dict:
    """Read-only traffic (lists, gets, a live watch) must commit nothing:
    the rv counter and the WAL record count bracket the phase."""
    import http.client

    host, port = srv._server.server_address[0], srv.port
    watch_sock, _ = _open_watch_socket(host, port)
    time.sleep(0.1)
    rv_before = getattr(api, "_rv", None)
    records_before = wal.stats()["records_appended"]
    conn = http.client.HTTPConnection(host, port, timeout=30)
    try:
        for _ in range(15):
            conn.request(
                "GET", f"/apis/{CRON_AV}/namespaces/default/crons",
                headers={"Authorization": f"Bearer {TOKEN}"})
            conn.getresponse().read()
            conn.request(
                "GET",
                f"/apis/{CRON_AV}/namespaces/default/crons/paced1-0-0",
                headers={"Authorization": f"Bearer {TOKEN}"})
            conn.getresponse().read()
    finally:
        conn.close()
        try:
            watch_sock.close()
        except OSError:
            pass
    rv_delta = (getattr(api, "_rv", None) or 0) - (rv_before or 0)
    records_delta = wal.stats()["records_appended"] - records_before
    ok = rv_delta == 0 and records_delta == 0
    return {
        "rv_delta": rv_delta,
        "wal_records_delta": records_delta,
        "verdict": {
            "status": "OK" if ok else "REGRESSION",
            "summary": (
                f"{'OK' if ok else 'REGRESSION'}: read-only HTTP phase "
                f"committed rv_delta={rv_delta}, "
                f"wal_records_delta={records_delta} (both must be 0)"
            ),
        },
    }


# ---------------------------------------------------------------------------
# Scenario 3: APF fairness under a noisy tenant
# ---------------------------------------------------------------------------

def _paced_get(host, port, path, token, count, interval_s, out_ms, stop):
    import http.client

    conn = http.client.HTTPConnection(host, port, timeout=30)
    try:
        for _ in range(count):
            if stop.is_set():
                break
            t0 = time.perf_counter()
            conn.request("GET", path,
                         headers={"Authorization": f"Bearer {token}"})
            conn.getresponse().read()
            out_ms.append((time.perf_counter() - t0) * 1e3)
            time.sleep(interval_s)
    finally:
        conn.close()


def _closed_loop_get(host, port, path, token, stop, counter):
    import http.client

    conn = http.client.HTTPConnection(host, port, timeout=30)
    try:
        while not stop.is_set():
            conn.request("GET", path,
                         headers={"Authorization": f"Bearer {token}"})
            resp = conn.getresponse()
            resp.read()
            if resp.status == 200:
                counter[0] += 1
    except Exception:
        pass
    finally:
        conn.close()


_FAIRNESS_SEATS = 2


def _fairness_phase(tokens: dict, quiet_samples: int, interval_s: float,
                    noisy_threads: int, fleet: int,
                    measure_alone: bool) -> dict:
    """One server, one flood window. ``tokens`` decides the flow layout:
    distinct identities exercise per-flow round-robin; identical
    identities collapse both tenants into one FIFO flow (the control).
    """
    from cron_operator_tpu.runtime.apf import (
        FairQueueAdmission,
        LevelConfig,
    )

    admission = FairQueueAdmission(levels={
        "system": LevelConfig(seats=4, queue_depth=64, max_queued=256),
        # Scarce seats on purpose: fairness only matters under
        # contention, and both tenants contend for these seats.
        "workload": LevelConfig(seats=_FAIRNESS_SEATS, queue_depth=128,
                                max_queued=1024, queue_timeout_s=30.0),
        "batch": LevelConfig(seats=2, queue_depth=32, max_queued=128),
    })
    srv = _make_server(token=None, admission=admission, tokens=tokens)
    srv.start()
    host, port = srv._server.server_address[0], srv.port
    list_path = f"/apis/{CRON_AV}/namespaces/default/crons"
    # The quiet tenant reads a deliberately large object so its own
    # service time (encode + send) dominates its latency; the noisy
    # flood's cheap gets then shift quiet p99 only by the queue wait.
    quiet_path = f"{list_path}/big-target"
    get_path = f"{list_path}/target-0"
    out: dict = {}
    try:
        for i in range(fleet):
            srv.api.create(_cron(f"target-{i}"))
        big = _cron("big-target")
        big["metadata"]["annotations"] = {
            "bench.kubedl.io/payload": "x" * 65536,
        }
        srv.api.create(big)

        if measure_alone:
            alone_ms: list = []
            _paced_get(host, port, quiet_path, "quiet-token",
                       quiet_samples, interval_s, alone_ms,
                       threading.Event())
            out["alone_ms"] = alone_ms

        burst_ms: list = []
        noisy_count = [0]
        stop = threading.Event()
        noisy = [
            threading.Thread(
                target=_closed_loop_get,
                args=(host, port, get_path, "noisy-token", stop,
                      noisy_count),
            )
            for _ in range(noisy_threads)
        ]
        for th in noisy:
            th.start()
        time.sleep(0.3)  # let the flood reach steady saturation
        t0 = time.perf_counter()
        _paced_get(host, port, quiet_path, "quiet-token", quiet_samples,
                   interval_s, burst_ms, stop)
        window = time.perf_counter() - t0
        stop.set()
        for th in noisy:
            th.join(timeout=10.0)
        out.update(burst_ms=burst_ms, noisy_count=noisy_count[0],
                   window=window)
    finally:
        srv.stop()
    return out


def fairness_leg(quiet_samples: int, quiet_interval_ms: float,
                 noisy_threads: int, fleet: int) -> dict:
    """Quiet tenant: paced gets of one large object. Noisy tenant: a
    closed-loop flood of cheap single-object gets through the SAME
    priority level (both are named workload-level gets, distinct flows).
    Per-flow round-robin keeps the quiet tenant's p99 near its
    undisturbed value while the noisy tenant saturates the level. A
    control run collapses both tenants into one flow (plain FIFO) to
    show what the quiet tenant's p99 looks like WITHOUT fairness."""
    interval_s = quiet_interval_ms / 1e3
    fair = _fairness_phase(
        tokens={"quiet-token": "tenant-quiet",
                "noisy-token": "tenant-noisy"},
        quiet_samples=quiet_samples, interval_s=interval_s,
        noisy_threads=noisy_threads, fleet=fleet, measure_alone=True)
    # Control: identical identities -> flow_for() maps both tenants to
    # one flow, so round-robin degenerates to FIFO behind the flood.
    fifo = _fairness_phase(
        tokens={"quiet-token": "tenant-shared",
                "noisy-token": "tenant-shared"},
        quiet_samples=quiet_samples, interval_s=interval_s,
        noisy_threads=noisy_threads, fleet=fleet, measure_alone=False)

    alone_ms = fair["alone_ms"]
    burst_ms = fair["burst_ms"]
    window = fair["window"]
    quiet_rps = len(burst_ms) / window if window else 0.0
    noisy_rps = fair["noisy_count"] / window if window else 0.0
    rate_ratio = round(noisy_rps / quiet_rps, 1) if quiet_rps else None
    p99_alone = _p99(alone_ms)
    p99_burst = _p99(burst_ms)
    p99_fifo = _p99(fifo["burst_ms"])
    # Fair queueing bounds the quiet tenant's extra wait at a couple of
    # dispatch quanta (one in-service noisy request per seat), so the
    # gate's absolute allowance is 2 measured quanta — on a host where
    # requests take tens of ms the 1.2x ratio term dominates instead.
    quantum_ms = (_FAIRNESS_SEATS / noisy_rps * 1e3) if noisy_rps else None
    allowed = None
    latency_ok = False
    if p99_alone is not None and p99_burst is not None and quantum_ms:
        allowed = round(max(
            FAIRNESS_P99_RATIO * p99_alone,
            p99_alone + 2 * quantum_ms + FAIRNESS_P99_FLOOR_MS), 3)
        latency_ok = p99_burst <= allowed
    flood_ok = rate_ratio is not None and rate_ratio >= FAIRNESS_MIN_RATE_RATIO
    ok = latency_ok and flood_ok
    degradation = (
        round(p99_burst / p99_alone, 2)
        if p99_alone and p99_burst else None
    )
    protection = (
        round(p99_fifo / p99_burst, 2)
        if p99_fifo and p99_burst else None
    )
    return {
        "quiet_samples": len(burst_ms),
        "quiet_interval_ms": quiet_interval_ms,
        "noisy_threads": noisy_threads,
        "quiet_rps": round(quiet_rps, 1),
        "noisy_rps": round(noisy_rps, 1),
        "noisy_to_quiet_rate_ratio": rate_ratio,
        "dispatch_quantum_ms": round(quantum_ms, 3) if quantum_ms else None,
        "quiet_p50_alone_ms": _p50(alone_ms),
        "quiet_p99_alone_ms": p99_alone,
        "quiet_p50_burst_ms": _p50(burst_ms),
        "quiet_p99_burst_ms": p99_burst,
        "quiet_p99_fifo_control_ms": p99_fifo,
        "fifo_to_fair_p99_ratio": protection,
        "degradation": degradation,
        "verdict": {
            "status": "OK" if ok else "REGRESSION",
            "allowed_p99_ms": allowed,
            "summary": (
                f"{'OK' if ok else 'REGRESSION'}: quiet tenant p99 "
                f"{p99_alone}ms alone -> {p99_burst}ms under a "
                f"{rate_ratio}x noisy flood ({degradation}x, allowed "
                f"<= {allowed}ms; flood ratio needs >= "
                f"{FAIRNESS_MIN_RATE_RATIO}x); single-flow FIFO control "
                f"p99 {p99_fifo}ms ({protection}x worse than fair)"
            ),
        },
    }


# ---------------------------------------------------------------------------
# Scenario 5: distributed sweep — shard processes behind the router
# ---------------------------------------------------------------------------

# The routed aggregate must stay within 20% of the shared-nothing sum:
# the same concurrent load, driven directly at each shard process and
# summed, is the ceiling the single-process router proxy is measured
# against.
DIST_MIN_SUM_RATIO = 0.8
# Far-future schedule so the per-shard CronReconcilers never fire a
# workload mid-bench — the measured surface is pure front-door traffic.
DIST_SCHEDULE = "0 0 1 1 *"


def _balanced_names(prefix: str, total: int, shards: int):
    """``total`` cron names spread as evenly as the consistent hash
    allows across homes (remainder to the lowest indices), so the routed
    drive offers near-identical load to every shard process and the
    comparison against the shared-nothing sum is not skewed by hash
    luck."""
    from cron_operator_tpu.runtime.shard import shard_index

    want = {si: total // shards + (1 if si < total % shards else 0)
            for si in range(shards)}
    buckets: dict = {si: [] for si in range(shards)}
    i = 0
    while any(len(buckets[si]) < want[si] for si in range(shards)):
        name = f"{prefix}-{i}"
        i += 1
        si = shard_index("default", name, shards)
        if len(buckets[si]) < want[si]:
            buckets[si].append(name)
    names: list = []
    for si in range(shards):
        names.extend(buckets[si])
    return names, {str(si): len(b) for si, b in buckets.items()}


def _drive_creates(host: str, port: int, names, threads_n: int, errors):
    """Closed-loop create drive: ``threads_n`` keep-alive connections
    split ``names`` and POST as fast as the durable 201s come back.
    Returns (completed, elapsed_s)."""
    import http.client

    path = f"/apis/{CRON_AV}/namespaces/default/crons"
    chunks = [names[i::threads_n] for i in range(threads_n)]
    done = [0] * threads_n
    gate = threading.Barrier(threads_n + 1)

    def worker(idx: int) -> None:
        conn = http.client.HTTPConnection(host, port, timeout=30)
        try:
            gate.wait()
            for name in chunks[idx]:
                status = _post_json(
                    conn, path, _cron(name, schedule=DIST_SCHEDULE))
                if status == 201:
                    done[idx] += 1
                else:
                    errors.append(f"{name}: HTTP {status}")
        except Exception as exc:  # pragma: no cover — surfaced in artifact
            errors.append(f"drive-{idx}: {exc!r}")
        finally:
            conn.close()

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(threads_n)]
    for t in threads:
        t.start()
    gate.wait()
    t0 = time.perf_counter()
    for t in threads:
        t.join(timeout=300.0)
    return sum(done), time.perf_counter() - t0


def _routed_watch(host: str, port: int, watchers: int, events: int,
                  names, timeout_s: float, rv: int = 0,
                  write_port: int | None = None) -> dict:
    """W watch streams on one front door; E creates driven at
    ``write_port`` (default: the same door). Streams attach at ``rv``
    so non-empty stores replay no backlog and the expected frame count
    stays exactly ``watchers * events``. On the router every frame
    crosses two sockets (shard -> router watch stream -> hub -> client)
    and must still arrive exactly once per watcher; on a follower door
    it additionally rides the WAL ship hop first."""
    import http.client

    socks = []
    t0 = time.perf_counter()
    try:
        pairs = [_open_watch_socket(host, port, rv=rv)
                 for _ in range(watchers)]
        socks = [s for s, _ in pairs]
        establish_s = time.perf_counter() - t0

        sel = selectors.DefaultSelector()
        counts = {}
        for s, carry in pairs:
            counts[s] = carry.count(ADDED_MARKER)
            sel.register(s, selectors.EVENT_READ,
                         carry[-(len(ADDED_MARKER) - 1):])

        conn = http.client.HTTPConnection(
            host, write_port if write_port is not None else port,
            timeout=30)
        path = f"/apis/{CRON_AV}/namespaces/default/crons"
        expected = watchers * events
        delivered = sum(counts.values())
        t0 = time.perf_counter()
        for name in names[:events]:
            _post_json(conn, path, _cron(name, schedule=DIST_SCHEDULE))
        conn.close()
        deadline = t0 + timeout_s
        while delivered < expected and time.perf_counter() < deadline:
            for key, _ in sel.select(timeout=0.5):
                s = key.fileobj
                try:
                    data = s.recv(1 << 16)
                except (BlockingIOError, InterruptedError):
                    continue
                except OSError:
                    sel.unregister(s)
                    continue
                if not data:
                    sel.unregister(s)
                    continue
                combined = key.data + data
                counts[s] += combined.count(ADDED_MARKER) - \
                    key.data.count(ADDED_MARKER)
                sel.modify(s, selectors.EVENT_READ,
                           combined[-(len(ADDED_MARKER) - 1):])
            delivered = sum(counts.values())
        elapsed = time.perf_counter() - t0
        sel.close()
    finally:
        for s in socks:
            try:
                s.close()
            except OSError:
                pass
    return {
        "watchers": watchers,
        "events": events,
        "expected_frames": expected,
        "delivered_frames": delivered,
        "establish_s": round(establish_s, 3),
        "drain_s": round(elapsed, 3),
        "events_per_s": round(delivered / elapsed, 1) if elapsed else 0.0,
        "timed_out": delivered < expected,
    }


def distributed_leg(shards: int, writers_per_shard: int,
                    creates_per_writer: int, watchers: int, events: int,
                    timeout_s: float) -> dict:
    """Spawn the real process topology — one shard process per index plus
    the consistent-hash router, each its own OS process with its own
    store + WAL — and measure it two ways:

    - **routed watch**: W streams on the router, E creates spread across
      shard homes, full delivery through the cross-process fan-in.
    - **routed vs shared-nothing writes**: the same closed-loop durable
      create load driven (a) directly at every shard concurrently and
      summed — the shared-nothing ceiling — and (b) through the router.
      Gate: routed aggregate >= ``DIST_MIN_SUM_RATIO`` of the sum.
    """
    import shutil as _shutil
    import signal as _signal
    import urllib.request

    data_dir = tempfile.mkdtemp(prefix="httpbench-dist-")
    log_dir = os.path.join(data_dir, "logs")
    os.makedirs(log_dir)
    base = 23360 + (os.getpid() % 13) * 128
    procs: list = []
    errors_direct: list = []
    errors_routed: list = []
    leg: dict = {"shards": shards, "port_base": base, "spawn_ok": False}

    def spawn(role_args, tag):
        log = open(os.path.join(log_dir, f"{tag}.log"), "ab")
        return subprocess.Popen(
            [sys.executable, "-m", "cron_operator_tpu.cli.main", "start",
             "--health-probe-bind-address", "0",
             "--serve-api-token", TOKEN] + role_args,
            stdout=log, stderr=subprocess.STDOUT, cwd=_TREE,
        )

    def debug_doc(port, timeout=1.0):
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/debug/shards",
            headers={"Authorization": f"Bearer {TOKEN}"})
        try:
            with urllib.request.urlopen(req, timeout=timeout) as r:
                return json.loads(r.read())
        except Exception:
            return None

    def wait_serving(port, deadline_s):
        deadline = time.monotonic() + deadline_s
        while time.monotonic() < deadline:
            doc = debug_doc(port)
            if doc is not None:
                return doc
            time.sleep(0.05)
        return None

    try:
        for si in range(shards):
            procs.append(spawn([
                "--shard-role", "shard", "--shard-index", str(si),
                "--data-dir", data_dir,
                "--serve-api", f"127.0.0.1:{base + 1 + si}",
                "--ship-port", str(base + 64 + si),
            ], f"shard-{si}"))
        for si in range(shards):
            if wait_serving(base + 1 + si, 30.0) is None:
                raise RuntimeError(f"shard {si} never served")
        procs.append(spawn([
            "--shard-role", "router",
            "--serve-api", f"127.0.0.1:{base}",
            "--peers", ",".join(f"127.0.0.1:{base + 1 + si}"
                                for si in range(shards)),
        ], "router"))
        if wait_serving(base, 30.0) is None:
            raise RuntimeError("router never served")
        leg["spawn_ok"] = True

        # Phase 1: routed watch fan-in (empty stores, so expected frames
        # are exactly watchers * events).
        watch_names, _ = _balanced_names("dw", events, shards)
        leg["watch"] = _routed_watch(
            "127.0.0.1", base, watchers, events, watch_names, timeout_s)

        # Phase 2: shared-nothing ceiling — every shard driven directly
        # and concurrently, per-shard rate summed.
        per_shard_total = writers_per_shard * creates_per_writer
        direct: dict = {}

        def drive_shard(si: int) -> None:
            names = [f"sn{si}-{j}" for j in range(per_shard_total)]
            completed, elapsed = _drive_creates(
                "127.0.0.1", base + 1 + si, names, writers_per_shard,
                errors_direct)
            direct[str(si)] = {
                "completed": completed,
                "elapsed_s": round(elapsed, 3),
                "writes_per_s": round(completed / elapsed, 1)
                if elapsed else 0.0,
            }

        drivers = [threading.Thread(target=drive_shard, args=(si,))
                   for si in range(shards)]
        for t in drivers:
            t.start()
        for t in drivers:
            t.join(timeout=300.0)
        shared_nothing_sum = round(
            sum(d["writes_per_s"] for d in direct.values()), 1)

        # Phase 3: the same total load through the router, names balanced
        # across hash homes.
        routed_names, split = _balanced_names(
            "rt", per_shard_total * shards, shards)
        routed_completed, routed_elapsed = _drive_creates(
            "127.0.0.1", base, routed_names, writers_per_shard * shards,
            errors_routed)
        routed_rate = round(routed_completed / routed_elapsed, 1) \
            if routed_elapsed else 0.0

        doc = debug_doc(base, timeout=5.0)
        leg.update({
            "writers_per_shard": writers_per_shard,
            "creates_per_writer": creates_per_writer,
            "direct": direct,
            "shared_nothing_sum_writes_per_s": shared_nothing_sum,
            "routed": {
                "completed": routed_completed,
                "elapsed_s": round(routed_elapsed, 3),
                "writes_per_s": routed_rate,
                "name_split_by_hash_home": split,
            },
            "sum_ratio": round(routed_rate / shared_nothing_sum, 3)
            if shared_nothing_sum else None,
            "errors": (errors_direct + errors_routed)[:5],
            "errors_total": len(errors_direct) + len(errors_routed),
            "debug_shards": doc,
        })
    except Exception as exc:
        leg["error"] = repr(exc)
    finally:
        for p in procs:
            try:
                p.send_signal(_signal.SIGTERM)
            except OSError:
                pass
        deadline = time.monotonic() + 20.0
        for p in procs:
            try:
                p.wait(timeout=max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()
        _shutil.rmtree(data_dir, ignore_errors=True)
    return leg


def _distributed_verdict(leg: dict, check_mode: bool) -> dict:
    watch = leg.get("watch") or {}
    ratio = leg.get("sum_ratio")
    mech_ok = (leg.get("spawn_ok") and "error" not in leg
               and not watch.get("timed_out", True)
               and leg.get("errors_total", 1) == 0)
    if check_mode:
        # Smoke: gate the mechanism (topology up, full watch delivery,
        # zero failed writes); the throughput ratio is reported, not
        # gated — CI boxes are too noisy for a 20% margin.
        ok = bool(mech_ok and ratio is not None)
        gate = "mechanism only (--check)"
    else:
        ok = bool(mech_ok and ratio is not None
                  and ratio >= DIST_MIN_SUM_RATIO)
        gate = f"ratio >= {DIST_MIN_SUM_RATIO}"
    return {
        "status": "OK" if ok else "REGRESSION",
        "sum_ratio": ratio,
        "required_ratio": None if check_mode else DIST_MIN_SUM_RATIO,
        "summary": (
            f"{'OK' if ok else 'REGRESSION'}: routed aggregate "
            f"{(leg.get('routed') or {}).get('writes_per_s')} writes/s vs "
            f"shared-nothing sum "
            f"{leg.get('shared_nothing_sum_writes_per_s')} writes/s across "
            f"{leg.get('shards')} shard processes (ratio {ratio}, gate "
            f"{gate}); watch fan-in delivered "
            f"{watch.get('delivered_frames')}/{watch.get('expected_frames')}"
            f" frames through the router"
        ),
    }


# ---------------------------------------------------------------------------
# Follower read plane (leader + R follower doors behind the router)
# ---------------------------------------------------------------------------

def _closed_loop_list(host: str, port: int, duration_s: float,
                      conns: int, errors) -> dict:
    """Closed-loop full-collection LIST drive: ``conns`` keep-alive
    connections GET the crons list as fast as 200s come back for
    ``duration_s``. Returns the sustained lists/s of ONE front door."""
    import http.client

    path = f"/apis/{CRON_AV}/namespaces/default/crons"
    done = [0] * conns
    gate = threading.Barrier(conns + 1)
    deadline_box: list = [0.0]

    def worker(idx: int) -> None:
        conn = http.client.HTTPConnection(host, port, timeout=30)
        try:
            gate.wait()
            while time.perf_counter() < deadline_box[0]:
                conn.request("GET", path, headers={
                    "Authorization": f"Bearer {TOKEN}"})
                resp = conn.getresponse()
                resp.read()
                if resp.status == 200:
                    done[idx] += 1
                else:
                    errors.append(f"list@{port}: HTTP {resp.status}")
        except Exception as exc:  # pragma: no cover — surfaced in artifact
            errors.append(f"list@{port}: {exc!r}")
        finally:
            conn.close()

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(conns)]
    for t in threads:
        t.start()
    gate.wait()
    t0 = time.perf_counter()
    deadline_box[0] = t0 + duration_s
    for t in threads:
        t.join(timeout=duration_s + 60.0)
    elapsed = time.perf_counter() - t0
    total = sum(done)
    return {
        "lists": total,
        "elapsed_s": round(elapsed, 3),
        "lists_per_s": round(total / elapsed, 1) if elapsed else 0.0,
    }


def follower_fanout_leg(replicas: int, fleet: int, pairs: int,
                        watchers: int, events: int, list_secs: float,
                        write_creates: int, timeout_s: float) -> dict:
    """Spawn the follower read plane as real processes — one shard
    leader, ``replicas`` socket-fed follower doors over its WAL ship,
    and the router fronting all of them — then measure the scale-out
    claim three ways:

    - **read capacity**: closed-loop LISTs and full watch fan-out
      delivery, each front door measured AT FULL TILT IN ISOLATION and
      the rates summed. This host has one CPU core, so driving all
      doors concurrently can never show aggregate scaling — capacity
      per endpoint is the honest unit; the sum is what a multi-core
      deployment buys. Gate: (leader + sum of followers) >=
      ``FOLLOWER_MIN_READ_SCALE`` x leader alone, for lists and for
      delivered watch events/s.
    - **read-your-writes**: ``pairs`` write-then-list pairs through the
      router; every list must contain the cron the immediately
      preceding write created (rv barrier, not luck). Gate: zero stale
      reads, and the follower plane (not leader fallback) serves the
      bulk of them.
    - **leader write cost**: the leader's closed-loop durable create
      rate with the replicas attached and a paced point-read trickle at
      every follower door must stay within
      ``FOLLOWER_WRITE_TOLERANCE`` of its no-replica baseline.
    """
    import http.client
    import shutil as _shutil
    import signal as _signal
    import urllib.parse
    import urllib.request

    data_dir = tempfile.mkdtemp(prefix="httpbench-follower-")
    log_dir = os.path.join(data_dir, "logs")
    os.makedirs(log_dir)
    base = 25480 + (os.getpid() % 13) * 64
    leader_api = base + 1
    leader_ship = base + 51
    follower_ports = [base + 11 + i for i in range(replicas)]
    procs: list = []
    errors: list = []
    leg: dict = {"replicas": replicas, "port_base": base,
                 "spawn_ok": False}
    list_path = f"/apis/{CRON_AV}/namespaces/default/crons"

    def spawn(role_args, tag):
        log = open(os.path.join(log_dir, f"{tag}.log"), "ab")
        return subprocess.Popen(
            [sys.executable, "-m", "cron_operator_tpu.cli.main", "start",
             "--health-probe-bind-address", "0",
             "--serve-api-token", TOKEN] + role_args,
            stdout=log, stderr=subprocess.STDOUT, cwd=_TREE,
        )

    def get_json(port, path, timeout=5.0):
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}{path}",
            headers={"Authorization": f"Bearer {TOKEN}"})
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return json.loads(r.read())

    def debug_doc(port, timeout=1.0):
        try:
            return get_json(port, "/debug/shards", timeout=timeout)
        except Exception:
            return None

    def wait_serving(port, deadline_s):
        deadline = time.monotonic() + deadline_s
        while time.monotonic() < deadline:
            doc = debug_doc(port)
            if doc is not None:
                return doc
            time.sleep(0.05)
        return None

    def collection_rv(port) -> int:
        doc = get_json(port, list_path)
        return int(doc.get("metadata", {}).get("resourceVersion", 0) or 0)

    def follower_rv(port) -> int:
        doc = debug_doc(port)
        try:
            return int(doc["shards"][0]["rv"])
        except (TypeError, KeyError, IndexError, ValueError):
            return -1

    def wait_caught_up(ports, min_rv, deadline_s) -> bool:
        deadline = time.monotonic() + deadline_s
        pending = list(ports)
        while pending and time.monotonic() < deadline:
            pending = [p for p in pending if follower_rv(p) < min_rv]
            if pending:
                time.sleep(0.02)
        return not pending

    def write_best_of(prefix, rounds, threads_n, port) -> dict:
        """Best-of-N closed-loop create rounds: the max rate of the
        rounds, so one scheduler hiccup on this single-core host does
        not poison a 5% comparison."""
        rates = []
        for r in range(rounds):
            names = [f"{prefix}{r}-{j}" for j in range(write_creates)]
            completed, elapsed = _drive_creates(
                "127.0.0.1", port, names, threads_n, errors)
            if completed != len(names):
                errors.append(
                    f"{prefix}{r}: {completed}/{len(names)} completed")
            rates.append(round(completed / elapsed, 1) if elapsed else 0.0)
        return {"rounds": rates, "writes_per_s": max(rates)}

    try:
        procs.append(spawn([
            "--shard-role", "shard", "--shard-index", "0",
            "--data-dir", data_dir,
            "--serve-api", f"127.0.0.1:{leader_api}",
            "--ship-port", str(leader_ship),
        ], "leader"))
        if wait_serving(leader_api, 30.0) is None:
            raise RuntimeError("leader shard never served")

        # Phase 1: leader write baseline with NO replicas attached.
        leg["write_alone"] = write_best_of("fwa", 2, 4, leader_api)

        # Phase 2: follower doors over the leader's WAL ship.
        for i, fport in enumerate(follower_ports):
            procs.append(spawn([
                "--shard-role", "follower", "--shard-index", "0",
                "--ship-port", str(leader_ship),
                "--serve-api", f"127.0.0.1:{fport}",
            ], f"follower-{i}"))
        for fport in follower_ports:
            if wait_serving(fport, 30.0) is None:
                raise RuntimeError(f"follower :{fport} never served")
        if not wait_caught_up(follower_ports, collection_rv(leader_api),
                              30.0):
            raise RuntimeError("followers never replayed the bootstrap")

        # Phase 3: router fronting the leader, read plane fanned out.
        procs.append(spawn([
            "--shard-role", "router",
            "--serve-api", f"127.0.0.1:{base}",
            "--peers", f"127.0.0.1:{leader_api}",
            "--read-peers", ",".join(f"127.0.0.1:{p}"
                                     for p in follower_ports),
        ], "router"))
        if wait_serving(base, 30.0) is None:
            raise RuntimeError("router never served")
        leg["spawn_ok"] = True

        # Phase 4: seed a fleet through the router so capacity phases
        # list/watch a realistically sized collection.
        fleet_names = [f"ffleet-{j}" for j in range(fleet)]
        completed, elapsed = _drive_creates(
            "127.0.0.1", base, fleet_names, 4, errors)
        leg["fleet"] = {"size": completed,
                        "elapsed_s": round(elapsed, 3)}

        # Phase 5: read-your-writes — write through the router, list
        # through the router, every pair must see its own write.
        doc_before = debug_doc(base, timeout=5.0)
        stale = 0
        conn = http.client.HTTPConnection("127.0.0.1", base, timeout=30)
        t0 = time.perf_counter()
        try:
            for i in range(pairs):
                name = f"fpair-{i}"
                obj = _cron(name, schedule=DIST_SCHEDULE)
                obj["metadata"]["labels"] = {"bench-pair": f"p{i}"}
                status = _post_json(conn, list_path, obj)
                if status != 201:
                    errors.append(f"{name}: HTTP {status}")
                    continue
                sel = urllib.parse.quote(f"bench-pair=p{i}")
                conn.request(
                    "GET", f"{list_path}?labelSelector={sel}",
                    headers={"Authorization": f"Bearer {TOKEN}"})
                resp = conn.getresponse()
                body = resp.read()
                if resp.status != 200:
                    errors.append(f"list {name}: HTTP {resp.status}")
                    stale += 1
                    continue
                items = json.loads(body).get("items", [])
                if not any(it.get("metadata", {}).get("name") == name
                           for it in items):
                    stale += 1
        finally:
            conn.close()
        ryw_elapsed = time.perf_counter() - t0
        doc_after = debug_doc(base, timeout=5.0)

        def _plane(doc):
            for sh in (doc or {}).get("shards", []):
                if isinstance(sh.get("read_plane"), dict):
                    return sh["read_plane"]
            return {}

        before_f = int(_plane(doc_before).get("reads_follower", 0) or 0)
        after_plane = _plane(doc_after)
        reads_follower = int(after_plane.get("reads_follower", 0) or 0) \
            - before_f
        leg["read_your_writes"] = {
            "pairs": pairs,
            "stale": stale,
            "elapsed_s": round(ryw_elapsed, 3),
            "pairs_per_s": round(pairs / ryw_elapsed, 1)
            if ryw_elapsed else 0.0,
            "served_by_follower": reads_follower,
            "follower_share": round(reads_follower / pairs, 3)
            if pairs else None,
            "read_plane": after_plane,
        }

        # Phase 6: LIST capacity per front door, sequentially (see
        # docstring: single-core host, so isolation-then-sum is the
        # honest aggregate).
        lists: dict = {"leader": _closed_loop_list(
            "127.0.0.1", leader_api, list_secs, 2, errors)}
        for i, fport in enumerate(follower_ports):
            lists[f"follower-{i}"] = _closed_loop_list(
                "127.0.0.1", fport, list_secs, 2, errors)
        leader_lps = lists["leader"]["lists_per_s"]
        agg_lps = round(sum(d["lists_per_s"] for d in lists.values()), 1)
        leg["list_capacity"] = {
            "per_endpoint": lists,
            "aggregate_lists_per_s": agg_lps,
            "scale": round(agg_lps / leader_lps, 2) if leader_lps else 0.0,
        }

        # Phase 7: watch fan-out capacity per front door. Events are
        # always written at the LEADER (follower doors receive them via
        # the WAL ship); each door must deliver every frame to every
        # watcher. Streams attach at the door's current rv so the frame
        # count is exact; the door is first waited level with the
        # leader so no earlier phase's tail inflates it.
        watch: dict = {}
        rv = collection_rv(leader_api)
        watch["leader"] = _routed_watch(
            "127.0.0.1", leader_api, watchers, events,
            [f"fev-l-{j}" for j in range(events)], timeout_s, rv=rv)
        for i, fport in enumerate(follower_ports):
            if not wait_caught_up([fport], collection_rv(leader_api),
                                  20.0):
                errors.append(f"follower-{i} lagged before watch phase")
            watch[f"follower-{i}"] = _routed_watch(
                "127.0.0.1", fport, watchers, events,
                [f"fev-{i}-{j}" for j in range(events)], timeout_s,
                rv=follower_rv(fport), write_port=leader_api)
        leader_eps = watch["leader"]["events_per_s"]
        agg_eps = round(sum(d["events_per_s"] for d in watch.values()), 1)
        leg["watch_capacity"] = {
            "per_endpoint": watch,
            "aggregate_events_per_s": agg_eps,
            "scale": round(agg_eps / leader_eps, 2) if leader_eps else 0.0,
            "timed_out": any(d["timed_out"] for d in watch.values()),
        }

        # Phase 8: leader write rate with the replicas attached and a
        # paced point-read trickle live at every follower door.
        stop = threading.Event()
        trickle_ms: list = []
        trickle_threads = [
            threading.Thread(
                target=_paced_get,
                args=("127.0.0.1", fport, f"{list_path}/ffleet-0",
                      TOKEN, 100000, 0.1, trickle_ms, stop))
            for fport in follower_ports
        ]
        for t in trickle_threads:
            t.start()
        try:
            leg["write_with_replicas"] = write_best_of(
                "fww", 2, 4, leader_api)
        finally:
            stop.set()
            for t in trickle_threads:
                t.join(timeout=30.0)
        alone = leg["write_alone"]["writes_per_s"]
        with_r = leg["write_with_replicas"]["writes_per_s"]
        leg["write_ratio"] = round(with_r / alone, 3) if alone else None
        leg["trickle_reads"] = len(trickle_ms)

        leg["methodology"] = (
            "single-core host: each front door's read capacity is "
            "measured in isolation and the aggregate is the sum — "
            "concurrent aggregate scaling needs at least one core per "
            "endpoint, which this box cannot exhibit")
        leg["errors"] = errors[:5]
        leg["errors_total"] = len(errors)
        leg["debug_router"] = doc_after
        leg["debug_followers"] = [debug_doc(p) for p in follower_ports]
    except Exception as exc:
        leg["error"] = repr(exc)
        leg.setdefault("errors", errors[:5])
        leg.setdefault("errors_total", len(errors))
    finally:
        for p in procs:
            try:
                p.send_signal(_signal.SIGTERM)
            except OSError:
                pass
        deadline = time.monotonic() + 20.0
        for p in procs:
            try:
                p.wait(timeout=max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()
        _shutil.rmtree(data_dir, ignore_errors=True)
    return leg


def _follower_fanout_verdict(leg: dict, check_mode: bool) -> dict:
    ryw = leg.get("read_your_writes") or {}
    lists = leg.get("list_capacity") or {}
    watch = leg.get("watch_capacity") or {}
    ratio = leg.get("write_ratio")
    stale = ryw.get("stale")
    share = ryw.get("follower_share")
    mech_ok = (leg.get("spawn_ok") and "error" not in leg
               and leg.get("errors_total", 1) == 0
               and stale == 0
               and not watch.get("timed_out", True)
               and ryw.get("served_by_follower", 0) >= 1)
    if check_mode:
        # Smoke: gate the mechanism (plane up, rv barriers hold — zero
        # stale read-your-writes pairs, full watch delivery at every
        # door, at least one follower-served read); capacity scale and
        # the write tolerance are reported, not gated.
        ok = bool(mech_ok)
        gate = "mechanism only (--check)"
    else:
        ok = bool(mech_ok
                  and (lists.get("scale") or 0) >= FOLLOWER_MIN_READ_SCALE
                  and (watch.get("scale") or 0) >= FOLLOWER_MIN_READ_SCALE
                  and share is not None and share >= 0.8
                  and ratio is not None
                  and abs(ratio - 1.0) <= FOLLOWER_WRITE_TOLERANCE)
        gate = (f"scale >= {FOLLOWER_MIN_READ_SCALE}, write ratio "
                f"within {FOLLOWER_WRITE_TOLERANCE:.0%}")
    return {
        "status": "OK" if ok else "REGRESSION",
        "list_scale": lists.get("scale"),
        "watch_scale": watch.get("scale"),
        "write_ratio": ratio,
        "stale_reads": stale,
        "summary": (
            f"{'OK' if ok else 'REGRESSION'}: follower read plane "
            f"({leg.get('replicas')} replicas) lists x{lists.get('scale')} "
            f"watch x{watch.get('scale')} vs leader alone (gate {gate}); "
            f"{stale} stale of {ryw.get('pairs')} write-then-read pairs "
            f"through the router ({ryw.get('served_by_follower')} "
            f"follower-served); leader writes with replicas+read trickle "
            f"at {ratio} of baseline"
        ),
    }


# ---------------------------------------------------------------------------
# Scenario 7: live shard split (write-path scale-out past boot shards)
# ---------------------------------------------------------------------------

def split_leg(pre_writes: int, storm_secs: float,
              post_writes_per_shard: int, batch: int = 25) -> dict:
    """Live 1->2 shard split: the write path scales past the boot-time
    shard count WITHOUT a restart.

    - **pre-split**: closed-loop durable creates (flush per ``batch``)
      against the single boot shard through the router.
    - **live split**: the same write storm keeps running through the
      router while ``split_shard(0)`` carves the keyspace; the dark
      window (fence -> publish) and any lost/double-applied acked write
      are measured. The router retries ``WrongShardError`` refusals, so
      the storm must see zero client-visible errors.
    - **post-split**: each shard's owned keyspace driven at full tilt
      in isolation and the rates summed — the shared-nothing scale-out
      projection, same methodology as ``make bench-shards`` (this host
      has one core; concurrent driving cannot show aggregate scaling).
      The denominator is a **contemporaneous control**: a fresh
      single-shard plane (the boot configuration) whose rounds are
      interleaved with the per-shard rounds in the same clock window.
      Comparing against the historical phase-1 rate instead puts any
      slow drift across the leg (CPU frequency, allocator/GC growth)
      straight into the ratio — measured swings of +-25% on this host
      — while interleaved control rounds see the same machine state.
      The phase-1 rate is still reported as context.

    Gates: aggregate >= ``SPLIT_MIN_SCALEUP`` x the interleaved
    single-shard control, dark window <= ``SPLIT_MAX_DARK_WINDOW_S``,
    zero lost or double-applied acked writes.
    """
    from cron_operator_tpu.runtime.shard import ShardedControlPlane

    gvk = (CRON_AV, "Cron")
    data_dir = tempfile.mkdtemp(prefix="httpbench-split-")
    control_dir = tempfile.mkdtemp(prefix="httpbench-splitctl-")
    leg: dict = {"pre_writes": pre_writes,
                 "post_writes_per_shard": post_writes_per_shard,
                 "batch": batch}
    plane = ShardedControlPlane(
        n_shards=1, data_dir=data_dir, flush_interval_s=0)
    control = None

    def _bench_cron(name):
        return {
            "apiVersion": CRON_AV, "kind": "Cron",
            "metadata": {"name": name, "namespace": "default"},
            "spec": {"schedule": "@every 1h"},
        }

    def drive(names, shards_to_flush, cleanup=False, router=None):
        router = router or plane.router
        # A cyclic collector pause inside a ~30ms timed window distorts
        # that round by 30-50%, and the allocation pattern is periodic
        # enough to hit the same phase position repeatedly — collect
        # OUTSIDE the window, then keep the collector off inside it.
        gc.collect()
        gc.disable()
        try:
            t0 = time.perf_counter()
            for i, name in enumerate(names):
                router.create(_bench_cron(name))
                if (i + 1) % batch == 0:
                    for s in shards_to_flush:
                        s.persistence.flush()
            for s in shards_to_flush:
                s.persistence.flush()
            elapsed = time.perf_counter() - t0
        finally:
            gc.enable()
        if cleanup:
            # Untimed: return the store to its pre-round population.
            # Commit cost grows with resident objects, so every measured
            # round — on BOTH sides of the ratio — must run at the same
            # store size; without this, phase 3 gets billed for phase
            # 1's and the storm's leftovers and the ratio reads low.
            for name in names:
                router.delete(CRON_AV, "Cron", "default", name)
            for s in shards_to_flush:
                s.persistence.flush()
        return round(len(names) / elapsed, 1) if elapsed else 0.0

    # Interpreter warm-up and scheduler noise swamp a single round at
    # these sizes, so each phase drives ROUNDS rounds (each cleaned up
    # to the same resident store size) and takes the MEDIAN — the same
    # estimator on both sides of the ratio. Best-of overestimates
    # whichever side has noisier rounds; the median is robust to a
    # single stalled or lucky round without that bias.
    ROUNDS = 5

    def _median(rates):
        s = sorted(rates)
        return s[len(s) // 2]

    def best_rate(round_names, shards_to_flush):
        rates = []
        for r in range(ROUNDS):
            rates.append(drive(round_names(r), shards_to_flush,
                               cleanup=True))
        return _median(rates), rates

    driven: list = []

    def tracked(names):
        driven.extend(names)
        return names

    try:
        # Phase 1: single-shard durable-write baseline (after an
        # unmeasured warm-up round).
        drive(tracked([f"warm-{i}"
                       for i in range(min(200, pre_writes))]),
              [plane.shards[0]])
        pre_rate, pre_rounds = best_rate(
            lambda r: [f"pre{r}-{i}" for i in range(pre_writes)],
            [plane.shards[0]])
        leg["pre_split_writes_per_s"] = pre_rate
        leg["pre_split_rounds"] = pre_rounds

        # Phase 2: split LIVE under a write storm through the router.
        stop = threading.Event()
        acked: list = []
        storm_errors: list = []

        def storm():
            i = 0
            while not stop.is_set():
                name = f"storm-{i}"
                try:
                    plane.router.create(_bench_cron(name))
                    acked.append(name)
                except Exception as exc:  # client-visible failure
                    storm_errors.append(f"{name}: {exc!r}")
                i += 1
                time.sleep(0.001)

        storm_t = threading.Thread(target=storm, daemon=True)
        storm_t.start()
        time.sleep(storm_secs / 2)
        report = plane.split_shard(0)
        time.sleep(storm_secs / 2)
        stop.set()
        storm_t.join(timeout=30.0)

        # Zero lost / double-applied: every acked name readable on its
        # map home and ONLY there.
        lost, doubled = [], []
        for name in acked + driven:
            owner = plane.ownership.owner("default", name)
            on_home = plane.shards[owner].store.get_frozen(
                gvk[0], gvk[1], "default", name) is not None
            off_home = any(
                s.store.get_frozen(gvk[0], gvk[1], "default", name)
                is not None
                for s in plane.shards if s.index != owner)
            if not on_home:
                lost.append(name)
            if off_home:
                doubled.append(name)
        leg["split"] = {
            "i6_ok": report["i6_ok"],
            "epoch": report["epoch"],
            "moved": report["moved"],
            "dark_window_s": round(report["dark_window_s"], 4),
            "duration_s": round(report["duration_s"], 3),
            "storm_acked": len(acked),
            "storm_errors": storm_errors[:5],
            "storm_errors_total": len(storm_errors),
            "lost_writes": len(lost),
            "double_applied": len(doubled),
            "wrong_shard_retries": plane.router.wrong_shard_retries,
        }

        # Untimed: clear the storm's residue (checked above) so phase
        # 3's rounds run at the same resident population as phase 1's —
        # the storm count varies run to run and commit cost tracks
        # store size, which would put per-run jitter into the ratio.
        for name in acked:
            try:
                plane.router.delete(CRON_AV, "Cron", "default", name)
            except Exception:
                pass  # a lost write already failed the gate above
        for s in plane.shards:
            s.persistence.flush()

        # Phase 3: per-shard post-split rates vs a contemporaneous
        # single-shard control, rounds interleaved (control, shard 0,
        # shard 1, repeat) so both sides of the ratio sample the same
        # machine state.
        needed = post_writes_per_shard

        def owned_names(si, r):
            out, i = [], 0
            while len(out) < needed:
                name = f"post{r}-{i}"
                if plane.ownership.owner("default", name) == si:
                    out.append(name)
                i += 1
            return out

        control = ShardedControlPlane(
            n_shards=1, data_dir=control_dir, flush_interval_s=0)
        # same warm-up discipline (and resident population) as the
        # split plane got before its phase-1 rounds
        drive([f"cwarm-{i}" for i in range(min(200, pre_writes))],
              [control.shards[0]], router=control.router)
        rounds_by = {"control": [], "0": [], "1": []}
        for r in range(ROUNDS):
            rounds_by["control"].append(drive(
                [f"ctl{r}-{i}" for i in range(needed)],
                [control.shards[0]], cleanup=True,
                router=control.router))
            for si in (0, 1):
                rounds_by[str(si)].append(drive(
                    owned_names(si, r), [plane.shards[si]],
                    cleanup=True))
        control_rate = _median(rounds_by["control"])
        per_shard = {
            str(si): {"writes_per_s": _median(rounds_by[str(si)]),
                      "rounds": rounds_by[str(si)]}
            for si in (0, 1)
        }
        agg = round(sum(d["writes_per_s"] for d in per_shard.values()), 1)
        leg.update({
            "post_split_per_shard": per_shard,
            "post_split_sum_writes_per_s": agg,
            "control_single_shard_writes_per_s": control_rate,
            "control_rounds": rounds_by["control"],
            "scaleup": (round(agg / control_rate, 3)
                        if control_rate else None),
            "scaleup_vs_pre_split": (round(agg / pre_rate, 3)
                                     if pre_rate else None),
        })
    except Exception as exc:
        leg["error"] = repr(exc)
    finally:
        plane.close()
        if control is not None:
            control.close()
        shutil.rmtree(data_dir, ignore_errors=True)
        shutil.rmtree(control_dir, ignore_errors=True)
    return leg


def _run_split_leg_isolated(pre_writes: int, storm_secs: float,
                            post_writes_per_shard: int) -> dict:
    """Full-run split leg in a FRESH interpreter (``--role split-only``,
    same idiom as the baseline A/B worktree run). The scale-up ratio
    compares allocation-heavy closed-loop phases, and by the time the
    full sweep reaches this leg the process carries every prior leg's
    heap (GC scans grow with live objects), which depresses the
    post-split phases 15-20% and flakes the >= 1.8x gate. A clean
    process measures the mechanism, not the bench's own garbage.
    Falls back to in-process on spawn failure."""
    try:
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--role", "split-only",
             "--split-pre-writes", str(pre_writes),
             "--split-storm-secs", str(storm_secs),
             "--split-post-writes", str(post_writes_per_shard),
             "--stdout"],
            env=dict(os.environ, JAX_PLATFORMS="cpu"),
            capture_output=True, text=True, timeout=600,
        )
        if out.returncode != 0:
            raise RuntimeError(
                f"split-only run failed rc={out.returncode}: "
                f"{out.stderr[-800:]}")
        leg = json.loads(out.stdout.strip().splitlines()[-1])
        leg["isolated_process"] = True
        return leg
    except Exception as exc:
        leg = split_leg(pre_writes, storm_secs, post_writes_per_shard)
        leg["isolated_process"] = False
        leg["isolation_fallback"] = repr(exc)
        return leg


def _split_verdict(leg: dict, check_mode: bool) -> dict:
    split = leg.get("split") or {}
    scaleup = leg.get("scaleup")
    dark = split.get("dark_window_s")
    mech_ok = ("error" not in leg
               and split.get("i6_ok") is True
               and split.get("lost_writes") == 0
               and split.get("double_applied") == 0
               and split.get("storm_errors_total", 1) == 0
               and dark is not None
               and dark <= SPLIT_MAX_DARK_WINDOW_S)
    if check_mode:
        # Smoke: gate the mechanism (clean cutover, zero loss, dark
        # window bound); the scale-up ratio is reported, not gated.
        ok = bool(mech_ok)
        gate = "mechanism only (--check)"
    else:
        ok = bool(mech_ok and scaleup is not None
                  and scaleup >= SPLIT_MIN_SCALEUP)
        gate = (f"sum >= {SPLIT_MIN_SCALEUP}x interleaved single-shard "
                f"control, dark window <= {SPLIT_MAX_DARK_WINDOW_S}s")
    return {
        "status": "OK" if ok else "REGRESSION",
        "scaleup": scaleup,
        "dark_window_s": dark,
        "lost_writes": split.get("lost_writes"),
        "double_applied": split.get("double_applied"),
        "summary": (
            f"{'OK' if ok else 'REGRESSION'}: live 1->2 split "
            f"{leg.get('control_single_shard_writes_per_s')} -> "
            f"{leg.get('post_split_sum_writes_per_s')} durable writes/s "
            f"aggregate (x{scaleup} vs contemporaneous single-shard "
            f"control; pre-split measured "
            f"{leg.get('pre_split_writes_per_s')}), dark window {dark}s, "
            f"{split.get('lost_writes')} lost / "
            f"{split.get('double_applied')} double-applied of "
            f"{split.get('storm_acked')} storm-acked writes "
            f"({split.get('wrong_shard_retries')} wrong-shard retries) "
            f"(gate {gate})"
        ),
    }


# ---------------------------------------------------------------------------
# Baseline A/B (fan-out only: the one scenario the old server can run)
# ---------------------------------------------------------------------------

def _run_baseline_fanout(ref: str, watchers: int, events: int,
                         timeout_s: float) -> dict:
    tree = tempfile.mkdtemp(prefix="httpbench-baseline-")
    subprocess.run(
        ["git", "-C", REPO_ROOT, "worktree", "add", "--detach", tree, ref],
        check=True, capture_output=True, text=True,
    )
    try:
        env = dict(os.environ, HTTPBENCH_TREE=tree, JAX_PLATFORMS="cpu")
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--role", "fanout-only",
             "--watchers", str(watchers), "--events", str(events),
             "--fanout-timeout", str(timeout_s), "--stdout"],
            env=env, capture_output=True, text=True,
            timeout=timeout_s + 300,
        )
        if out.returncode != 0:
            raise RuntimeError(
                f"baseline run failed rc={out.returncode}: "
                f"{out.stderr[-800:]}"
            )
        return json.loads(out.stdout.strip().splitlines()[-1])
    finally:
        subprocess.run(
            ["git", "-C", REPO_ROOT, "worktree", "remove", "--force", tree],
            capture_output=True,
        )


def _fanout_verdict(after: dict, baseline: dict | None,
                    check_mode: bool) -> dict:
    encode_ok = after.get("encodes_per_event") == 1.0
    complete = not after["timed_out"]
    if baseline is not None:
        speedup = None
        if baseline.get("events_per_s"):
            speedup = round(
                after["events_per_s"] / baseline["events_per_s"], 1)
        ok = (complete and encode_ok and speedup is not None
              and speedup >= FANOUT_MIN_SPEEDUP)
        return {
            "status": "OK" if ok else "REGRESSION",
            "speedup_vs_baseline": speedup,
            "required_speedup": FANOUT_MIN_SPEEDUP,
            "summary": (
                f"{'OK' if ok else 'REGRESSION'}: fan-out at "
                f"{after['watchers']} watchers delivers "
                f"{after['events_per_s']} events/s vs baseline "
                f"{baseline.get('events_per_s')} events/s "
                f"({speedup}x, need >= {FANOUT_MIN_SPEEDUP}x); "
                f"encodes/event={after.get('encodes_per_event')}"
            ),
        }
    # No baseline tree (smoke mode): gate the mechanism (encode-once,
    # full delivery); the legacy encode model is context, not a gate —
    # it omits the old server's socket and thread costs.
    ok = complete and encode_ok
    return {
        "status": "OK" if ok else "REGRESSION",
        "speedup_vs_baseline": None,
        "summary": (
            f"{'OK' if ok else 'REGRESSION'}: fan-out delivered "
            f"{after['delivered_frames']}/{after['expected_frames']} "
            f"frames at {after['events_per_s']} events/s with "
            f"encodes/event={after.get('encodes_per_event')} "
            f"(authoritative >=5x gate needs --baseline-ref)"
        ),
    }


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--out", default=os.path.join(REPO_ROOT,
                                                 "BENCH_HTTP.json"))
    p.add_argument("--baseline-ref", default=None,
                   help="git ref of the pre-fan-out server for the A/B "
                        "watch leg")
    p.add_argument("--watchers", type=int, default=1000)
    p.add_argument("--events", type=int, default=20)
    p.add_argument("--fanout-timeout", type=float, default=240.0)
    p.add_argument("--writers", default="1,64",
                   help="comma-separated writer concurrencies (first is "
                        "the p99 baseline, last the peak)")
    p.add_argument("--writes-per-writer", type=int, default=15)
    p.add_argument("--write-interval-ms", type=float, default=100.0)
    p.add_argument("--quiet-samples", type=int, default=150)
    p.add_argument("--quiet-interval-ms", type=float, default=350.0,
                   help="quiet-tenant pacing; slow enough that the "
                        "closed-loop flood clears a 50x rate ratio")
    p.add_argument("--noisy-threads", type=int, default=24)
    p.add_argument("--fairness-fleet", type=int, default=400)
    p.add_argument("--dist-shards", type=int, default=2)
    p.add_argument("--dist-writers", type=int, default=6,
                   help="closed-loop writer connections per shard in the "
                        "distributed sweep")
    p.add_argument("--dist-creates", type=int, default=40,
                   help="creates per writer connection per phase")
    p.add_argument("--dist-watchers", type=int, default=200)
    p.add_argument("--dist-events", type=int, default=10)
    p.add_argument("--dist-timeout", type=float, default=120.0)
    p.add_argument("--follower-replicas", type=int, default=3)
    p.add_argument("--follower-fleet", type=int, default=150,
                   help="crons seeded before the follower capacity "
                        "phases so lists/watches see a real collection")
    p.add_argument("--follower-pairs", type=int, default=1000,
                   help="write-then-list read-your-writes pairs driven "
                        "through the router (gate: zero stale)")
    p.add_argument("--follower-watchers", type=int, default=100)
    p.add_argument("--follower-events", type=int, default=25)
    p.add_argument("--follower-list-secs", type=float, default=4.0,
                   help="closed-loop LIST drive per front door")
    p.add_argument("--follower-write-creates", type=int, default=300,
                   help="creates per write round in the leader "
                        "write-cost comparison")
    p.add_argument("--follower-timeout", type=float, default=180.0)
    p.add_argument("--split-pre-writes", type=int, default=600,
                   help="durable creates in the single-shard baseline "
                        "phase of the live-split leg")
    p.add_argument("--split-storm-secs", type=float, default=4.0,
                   help="write-storm duration bracketing the live "
                        "1->2 split")
    p.add_argument("--split-post-writes", type=int, default=600,
                   help="durable creates per shard in the post-split "
                        "sequential sweep")
    p.add_argument("--stdout", action="store_true",
                   help="print the artifact JSON to stdout only")
    p.add_argument("--check", action="store_true",
                   help="smoke mode: small sizes unless overridden, and "
                        "exit non-zero on any REGRESSION verdict")
    p.add_argument("--role", choices=["full", "fanout-only", "split-only"],
                   default="full", help=argparse.SUPPRESS)
    args = p.parse_args()

    if args.check and "--watchers" not in " ".join(sys.argv):
        args.watchers = 100
        args.events = 10
        args.writers = "1,16"
        args.writes_per_writer = 8
        args.quiet_samples = 40
        args.noisy_threads = 8
        args.fairness_fleet = 150
        args.dist_writers = 2
        args.dist_creates = 10
        args.dist_watchers = 25
        args.dist_events = 5
        args.follower_fleet = 40
        args.follower_pairs = 60
        args.follower_watchers = 25
        args.follower_events = 5
        args.follower_list_secs = 1.0
        args.follower_write_creates = 60
        args.split_pre_writes = 150
        args.split_storm_secs = 1.5
        args.split_post_writes = 150

    if args.role == "fanout-only":
        result = fanout_leg(args.watchers, args.events, args.fanout_timeout)
        print(json.dumps(result))
        return 0

    if args.role == "split-only":
        result = split_leg(
            args.split_pre_writes, args.split_storm_secs,
            args.split_post_writes)
        print(json.dumps(result))
        return 0

    writer_counts = [int(w) for w in args.writers.split(",") if w]

    fanout = fanout_leg(args.watchers, args.events, args.fanout_timeout)
    fanout["legacy_model_events_per_s"] = _legacy_encode_model(
        args.watchers, args.events)
    baseline = None
    if args.baseline_ref:
        baseline = _run_baseline_fanout(
            args.baseline_ref, args.watchers, args.events,
            args.fanout_timeout)
    fanout_v = _fanout_verdict(fanout, baseline, args.check)

    writes = write_fanin_leg(
        writer_counts, args.writes_per_writer, args.write_interval_ms)
    fairness = fairness_leg(
        args.quiet_samples, args.quiet_interval_ms, args.noisy_threads,
        args.fairness_fleet)
    distributed = distributed_leg(
        args.dist_shards, args.dist_writers, args.dist_creates,
        args.dist_watchers, args.dist_events, args.dist_timeout)
    distributed_v = _distributed_verdict(distributed, args.check)
    follower = follower_fanout_leg(
        args.follower_replicas, args.follower_fleet, args.follower_pairs,
        args.follower_watchers, args.follower_events,
        args.follower_list_secs, args.follower_write_creates,
        args.follower_timeout)
    follower_v = _follower_fanout_verdict(follower, args.check)
    if args.check:
        # Smoke: in-process is fine — the mechanism gate (clean
        # cutover, zero loss, dark-window bound) is noise-immune.
        split = split_leg(
            args.split_pre_writes, args.split_storm_secs,
            args.split_post_writes)
    else:
        split = _run_split_leg_isolated(
            args.split_pre_writes, args.split_storm_secs,
            args.split_post_writes)
    split_v = _split_verdict(split, args.check)

    verdicts = {
        "fanout": fanout_v,
        "write_fanin": writes["verdict"],
        "fairness": fairness["verdict"],
        "zero_steady_state": writes["zero_steady_state"]["verdict"],
        "distributed": distributed_v,
        "follower_fanout": follower_v,
        "split_leg": split_v,
    }
    ok = all(v["status"] == "OK" for v in verdicts.values())
    artifact = {
        "schema": "http-front-door-bench/v1",
        "git_ref": _git_ref(_TREE),
        "fanout": fanout,
        "fanout_baseline": baseline,
        "write_fanin": writes,
        "fairness": fairness,
        "distributed": distributed,
        "distributed_verdict": distributed_v,
        "follower_fanout": follower,
        "follower_fanout_verdict": follower_v,
        "split_leg": split,
        "split_leg_verdict": split_v,
        "verdict": {
            "status": "OK" if ok else "REGRESSION",
            "summary": "; ".join(v["summary"] for v in verdicts.values()),
        },
    }
    text = json.dumps(artifact, indent=2, sort_keys=True)
    if args.stdout:
        print(json.dumps(artifact))
    else:
        with open(args.out, "w") as f:
            f.write(text + "\n")
        print(text)
        print(f"\nwrote {args.out}", file=sys.stderr)
    for v in verdicts.values():
        print(v["summary"], file=sys.stderr)
    if args.check and not ok:
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
