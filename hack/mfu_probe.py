"""ResNet-50 MFU attribution probe (VERDICT r4 #1) — thin wrapper.

The r4 artifact reported mfu=0.1247 at batch 64 with no attribution. This
probe separates the three candidate causes:

- **batch too small** — sweep batch sizes; MFU should climb if the MXU is
  under-fed at 64.
- **dispatch/tunnel overhead** — time the SAME train step two ways:
  ``chain`` (one jitted ``lax.scan`` of CHAIN steps — pure device
  compute, zero per-step host involvement) vs ``dispatch`` (a
  scan-of-one program re-dispatched per step — the pre-overlap
  Trainer's shape). The difference is host dispatch + tunnel cost,
  not the model.
- **conv efficiency** — if the chain MFU is still low at the best batch,
  the convs themselves are the ceiling; optionally dump a profiler trace
  (``profile_dir=...``) for the best config.

All timing is ``cron_operator_tpu.ops.microbench.timed_chain`` — the
span-differenced ((t_2k − t_k)/(k·iters), value-fetch-synced) chain
primitive this file used to carry a private copy of. See its docstring
for the methodology; hack/step_bench.py's device-floor leg uses the
same function, so probe numbers and bench numbers are comparable.

Run: ``python hack/mfu_probe.py [batch=64,128,256] [image=224]
[chain=5] [profile_dir=/tmp/trace]``. Prints one JSON line.
"""

from __future__ import annotations

import json
import os
import sys
import time

os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 ".jax_cache"),
)
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")

# One source of truth for the FLOPs model and the ordered peak table —
# bench.py's PEAK_FLOPS already encodes the "v5 lite before v5" ordering
# lesson (its r3 dict produced mfu:null on the real chip).
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from bench import PEAK_FLOPS, _flops_per_image  # noqa: E402

from cron_operator_tpu.ops.microbench import timed_chain  # noqa: E402


def _parse(argv):
    out = {}
    for a in argv:
        if "=" in a:
            k, v = a.split("=", 1)
            out[k] = v
    return out


def main() -> int:
    params_cli = _parse(sys.argv[1:])
    batches = [int(b) for b in params_cli.get("batch", "64,128,256").split(",")]
    image = int(params_cli.get("image", "224"))
    chain = int(params_cli.get("chain", "5"))
    profile_dir = params_cli.get("profile_dir")

    import jax
    import jax.numpy as jnp
    import optax

    from cron_operator_tpu.models import ResNet50

    dev = jax.devices()[0]
    kind = dev.device_kind
    peak = next((v for k, v in PEAK_FLOPS if k in kind.lower()), None)
    flops_per_image = _flops_per_image(image)

    model = ResNet50()
    tx = optax.sgd(0.1, momentum=0.9)

    def loss_of(p, x, y):
        logits = model.apply({"params": p}, x)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        return -jnp.mean(jnp.take_along_axis(logp, y[..., None], -1))

    def make_step(batch):
        """The train-step body (carry → carry) — ONE definition shared
        by both timing modes and the profiler block, so the profiled
        trace is the same program the sweep timed."""
        def step(carry):
            p, o, key = carry
            key, k1, k2 = jax.random.split(key, 3)
            x = jax.random.normal(k1, (batch, image, image, 3),
                                  jnp.bfloat16)
            y = jax.random.randint(k2, (batch,), 0, 1000)
            _, g = jax.value_and_grad(loss_of)(p, x, y)
            u, o = tx.update(g, o, p)
            return (optax.apply_updates(p, u), o, key)
        return step

    def init_carry():
        params = jax.jit(model.init)(
            jax.random.PRNGKey(0), jnp.zeros((1, image, image, 3))
        )["params"]
        return (params, tx.init(params), jax.random.PRNGKey(1))

    results = []
    for batch in batches:
        rec = {"batch": batch, "image": image}
        try:
            step = make_step(batch)

            # --- chain mode: scan-of-CHAIN, pure device compute -----------
            t0 = time.perf_counter()
            chain_step, c = timed_chain(step, init_carry(), iters=chain)
            rec["compile_plus_measure_s"] = round(time.perf_counter() - t0, 1)
            if chain_step is not None:
                rec["chain_step_ms"] = round(chain_step * 1e3, 2)
                rec["chain_images_per_s"] = round(batch / chain_step, 1)
                if peak:
                    rec["chain_mfu"] = round(
                        batch * flops_per_image / chain_step / peak, 4
                    )
            else:
                rec["chain_step_ms"] = None

            # --- dispatch mode: scan-of-ONE re-dispatched per step --------
            # (the pre-overlap Trainer's shape: one call per step; the
            # span differencing cancels the end-of-span sync, leaving
            # per-dispatch cost = device step + host dispatch)
            disp_step, _ = timed_chain(step, c, iters=1)
            if disp_step is not None:
                rec["dispatch_step_ms"] = round(disp_step * 1e3, 2)
                if peak:
                    rec["dispatch_mfu"] = round(
                        batch * flops_per_image / disp_step / peak, 4
                    )
            else:
                rec["dispatch_step_ms"] = None
            del c
        except Exception as exc:  # noqa: BLE001 — one OOM batch must not
            rec["error"] = str(exc)[-400:]  # kill the sweep
        results.append(rec)

    # Keyed on images/s, not MFU: MFU needs a PEAK entry for the device
    # kind, and an unknown kind must not silently skip a requested trace.
    best = max(
        (r for r in results if r.get("chain_images_per_s")),
        key=lambda r: r["chain_images_per_s"],
        default=None,
    )
    profile_error = None
    if profile_dir and best is not None:
        # Re-run the best config briefly under the profiler for op-level
        # attribution (TensorBoard/XProf artifact). Same step body as the
        # sweep: make_step is the single step-builder. Guarded: an
        # optional trace must never discard the sweep's measurements.
        try:
            step = make_step(best["batch"])
            run = jax.jit(
                lambda c: jax.lax.scan(
                    lambda c, _: (step(c), None), c, None, length=chain
                )[0],
                donate_argnums=0,
            )
            c = run(init_carry())
            float(jax.tree_util.tree_leaves(c)[0].ravel()[0])
            jax.profiler.start_trace(profile_dir)
            for _ in range(3):
                c = run(c)
            float(jax.tree_util.tree_leaves(c)[0].ravel()[0])
            jax.profiler.stop_trace()
        except Exception as exc:  # noqa: BLE001
            profile_error = str(exc)[-400:]

    print(json.dumps({
        "device_kind": kind,
        "backend": jax.default_backend(),
        "peak_flops": peak,
        "flops_per_image": flops_per_image,
        "chain_len": chain,
        "timing": "ops.microbench.timed_chain (span-differenced)",
        "sweep": results,
        "best": best,
        "profile_dir": profile_dir if best else None,
        "profile_error": profile_error,
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
