"""ResNet-50 step-time attribution (VERDICT r4 #1, stage 2) — thin wrapper.

The sweep (hack/mfu_probe.py) showed chain ≈ dispatch (no tunnel/host
overhead) and best MFU ~15% at batch 128 — so the compute itself is the
ceiling. This probe times the step's components separately:

- ``rng``        — just the synthetic-batch generation (jax.random.normal
                   of [b, 224, 224, 3] + randint labels). Threefry on TPU
                   is ALU-heavy; if this is a big slice, the "training"
                   number is paying for the data generator.
- ``rng_rbg``    — same under the rbg PRNG (hardware RNG, much cheaper).
- ``fwd``        — forward pass only, fixed batch.
- ``fwdbwd``     — value_and_grad + SGD update, fixed batch (the train
                   step minus data generation).
- ``fwdbwd_nonorm`` — same but with GroupNorm replaced by identity:
                   the delta is the norm layers' cost (53 of them; a
                   two-pass reduction each ⇒ prime HBM-traffic suspect).
- ``step``       — the full step as benched (rng + fwd + bwd + opt).

All timing delegates to ``cron_operator_tpu.ops.microbench.timed_chain``
(span-differenced scan-of-chain; this file used to carry a private copy
of that logic). Also prints XLA's own flop count for the fwd
(cost_analysis), checking the 12.3 GFLOP/img MFU denominator.

Run: ``python hack/mfu_attrib.py [batch=128] [image=224] [chain=5]``.
Prints one JSON line.
"""

from __future__ import annotations

import json
import os
import sys

os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 ".jax_cache"),
)
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from cron_operator_tpu.ops.microbench import timed_chain  # noqa: E402


def _parse(argv):
    out = {}
    for a in argv:
        if "=" in a:
            k, v = a.split("=", 1)
            out[k] = v
    return out


def main() -> int:
    cli = _parse(sys.argv[1:])
    batch = int(cli.get("batch", "128"))
    image = int(cli.get("image", "224"))
    chain = int(cli.get("chain", "5"))

    import flax.linen as nn
    import jax
    import jax.numpy as jnp
    import optax

    from cron_operator_tpu.models import ResNet50

    class _Identity(nn.Module):
        """GroupNorm stand-in: same call signature, no reduction."""
        dtype: jnp.dtype = jnp.bfloat16

        @nn.compact
        def __call__(self, x):
            # A learnable scale keeps parameter structure non-empty so
            # value_and_grad still has something per layer; cost ~0.
            s = self.param("scale", nn.initializers.ones, (1,))
            return x * s.astype(x.dtype)

    tx = optax.sgd(0.1, momentum=0.9)

    def timed(body, carry):
        """Per-step ms of a carry→carry body via timed_chain (scan of
        CHAIN iterations, span-differenced). timed_chain's sync pulls
        the FIRST carry leaf as a scalar — keep a plain float leading
        each carry (not a typed PRNG key)."""
        t, _ = timed_chain(body, carry, iters=chain)
        return round(t * 1e3, 2) if t else None

    out = {"batch": batch, "image": image, "chain": chain}

    # --- rng-only --------------------------------------------------------
    def rng_body(carry):
        acc, key = carry
        key, k1, k2 = jax.random.split(key, 3)
        x = jax.random.normal(k1, (batch, image, image, 3), jnp.bfloat16)
        y = jax.random.randint(k2, (batch,), 0, 1000)
        # Touch the outputs so XLA cannot DCE the generation.
        return (acc + x.mean().astype(jnp.float32) + y.sum(), key)

    out["rng_ms"] = timed(rng_body, (jnp.float32(0), jax.random.PRNGKey(0)))

    # --- rng under rbg ---------------------------------------------------
    try:
        out["rng_rbg_ms"] = timed(
            rng_body, (jnp.float32(0), jax.random.key(0, impl="rbg"))
        )
    except Exception as exc:  # noqa: BLE001
        out["rng_rbg_ms"] = f"error: {str(exc)[-200:]}"

    # --- model variants --------------------------------------------------
    def build(norm=None):
        kw = {}
        if norm is not None:
            from cron_operator_tpu.models.resnet import BottleneckBlock
            from functools import partial as _p

            kw["block"] = _p(BottleneckBlock, norm=norm)
        model = ResNet50(**kw)
        params = jax.jit(model.init)(
            jax.random.PRNGKey(0), jnp.zeros((1, image, image, 3))
        )["params"]
        return model, params

    def loss_of(model, p, x, y):
        logits = model.apply({"params": p}, x)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        return -jnp.mean(jnp.take_along_axis(logp, y[..., None], -1))

    x_fix = jax.random.normal(
        jax.random.PRNGKey(3), (batch, image, image, 3), jnp.bfloat16
    )
    y_fix = jax.random.randint(jax.random.PRNGKey(4), (batch,), 0, 1000)

    model, params = build()

    # XLA's own flop count for the fwd — sanity on the MFU denominator.
    try:
        lowered = jax.jit(
            lambda p, x: model.apply({"params": p}, x)
        ).lower(params, x_fix)
        ca = lowered.compile().cost_analysis()
        ca = ca[0] if isinstance(ca, (list, tuple)) else ca
        if ca and "flops" in ca:
            out["xla_fwd_flops_per_image"] = round(ca["flops"] / batch / 1e9,
                                                   2)
    except Exception as exc:  # noqa: BLE001
        out["xla_fwd_flops_per_image"] = f"error: {str(exc)[-200:]}"

    # fwd only
    out["fwd_ms"] = timed(
        lambda acc: acc + loss_of(model, params, x_fix, y_fix),
        jnp.float32(0),
    )

    # fwd+bwd+opt, fixed data
    def make_step(model, params):
        def body(carry):
            p, o = carry
            _, g = jax.value_and_grad(
                lambda pp: loss_of(model, pp, x_fix, y_fix)
            )(p)
            u, o = tx.update(g, o, p)
            return (optax.apply_updates(p, u), o)
        return body, (params, tx.init(params))

    body, carry = make_step(model, params)
    out["fwdbwd_ms"] = timed(body, carry)

    # fwd+bwd+opt with identity norm
    model_nn, params_nn = build(norm=_Identity)
    body, carry = make_step(model_nn, params_nn)
    out["fwdbwd_nonorm_ms"] = timed(body, carry)

    # full step (rng + train), the benched configuration
    def full_body(carry):
        p, o, key = carry
        key, k1, k2 = jax.random.split(key, 3)
        x = jax.random.normal(k1, (batch, image, image, 3), jnp.bfloat16)
        y = jax.random.randint(k2, (batch,), 0, 1000)
        _, g = jax.value_and_grad(lambda pp: loss_of(model, pp, x, y))(p)
        u, o = tx.update(g, o, p)
        return (optax.apply_updates(p, u), o, key)

    out["step_ms"] = timed(
        full_body, (params, tx.init(params), jax.random.PRNGKey(1))
    )

    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
