"""Process entry point — the ``cron-operator start`` analog.

Parity targets: root command ``/root/reference/cmd/main.go:32-49`` and the
start command's flag surface ``/root/reference/cmd/operator/start.go:215-247``
(max-concurrent-reconciles, qps/burst, metrics/health bind addresses,
leader-elect, zap log level/encoder). TPU-native additions: ``--load`` to
apply manifests at startup (standalone single-process mode — there is no
external kube-apiserver or training-operator; the embedded control plane and
the local TPU training runtime fill those roles) and ``--backend`` to pick
how JAXJob workloads execute.
"""

from __future__ import annotations

import argparse
import hmac
import inspect
import json
import logging
import os as _os
import signal
import sys
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional
from urllib.parse import parse_qs

from cron_operator_tpu import __version__


def _parse_bind(addr: str) -> Optional[int]:
    """':8081' / '8081' → port; '0' → disabled (reference metrics default)."""
    if addr in ("0", "", "none"):
        return None
    return int(addr.rsplit(":", 1)[-1])


def _bool_arg(v: str) -> bool:
    """Go-style bool flag value ('--metrics-secure=false')."""
    if v.lower() in ("1", "true", "t", "yes"):
        return True
    if v.lower() in ("0", "false", "f", "no"):
        return False
    raise argparse.ArgumentTypeError(f"expected true/false, got {v!r}")


def _watched_tls(cert_path, cert_name, cert_key, enable_http2, log, what):
    """Provided-cert TLS for an inbound surface (metrics / served API):
    build the context and start the rotation watcher. Returns
    ``(ctx, watcher)`` or ``(None, None)`` after logging an actionable
    error (a typo'd cert dir must exit 2, not crash-loop on a raw
    OSError traceback)."""
    import ssl

    from cron_operator_tpu.utils.tlsutil import CertWatcher, server_context

    cert = _os.path.join(cert_path, cert_name)
    key = _os.path.join(cert_path, cert_key)
    try:
        ctx = server_context(cert, key, enable_http2=enable_http2)
    except (OSError, ssl.SSLError) as err:
        log.error(
            "%s TLS could not load the certificate pair %s / %s: %s — "
            "check the --%s-cert-path/-name/-key flags", what, cert, key,
            err, what,
        )
        return None, None
    watcher = CertWatcher(ctx, cert, key).start()
    log.info("%s TLS from %s (watched)", what, cert_path)
    return ctx, watcher


def _takes_params(fn) -> bool:
    """True iff a route callable declares a (query-params) parameter.
    Resolved once per request, not per route registration, so plain
    zero-arg lambdas keep working unchanged."""
    try:
        return bool(inspect.signature(fn).parameters)
    except (TypeError, ValueError):  # builtins without a signature
        return False


def _serve(
    port: int,
    routes,
    name: str,
    tls_ctx=None,
    token: Optional[str] = None,
    authn=None,
) -> ThreadingHTTPServer:
    """Serve ``routes`` on ``port`` (0 = ephemeral; read
    ``server.server_address``). ``tls_ctx`` wraps the listener in TLS.
    Auth is the reference FilterProvider analog (start.go:121-133),
    picked per deployment mode: ``token`` requires a static
    ``Authorization: Bearer <token>`` (embedded mode); ``authn`` is a
    callable(authorization_header) -> bool for kube-delegated
    TokenReview/SubjectAccessReview (cluster mode,
    runtime.authfilter.ScrapeAuthenticator). 401 otherwise.

    Routes map an exact path to a zero-arg callable returning
    ``(body, content_type)``; a callable declaring a parameter instead
    receives the parsed query string (``urllib.parse.parse_qs`` shape) —
    how the filterable debug routes (``/debug/audit``) take their
    ``?kind=&trace=&limit=`` params."""

    def _denied(headers) -> bool:
        if token is not None:
            return not hmac.compare_digest(
                headers.get("Authorization") or "", f"Bearer {token}"
            )
        if authn is not None:
            return not authn(headers.get("Authorization"))
        return False

    class Handler(BaseHTTPRequestHandler):
        # A stalled peer must not hold a handler thread forever (the TLS
        # handshake also runs under this deadline — see wrap below).
        timeout = 30

        def do_GET(self):  # noqa: N802
            if _denied(self.headers):
                body = b"Unauthorized"
                self.send_response(401)
                self.send_header("WWW-Authenticate", "Bearer")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            path, _, query = self.path.partition("?")
            fn = routes.get(path)
            if fn is None:
                self.send_response(404)
                self.end_headers()
                return
            if _takes_params(fn):
                body, ctype = fn(parse_qs(query))
            else:
                body, ctype = fn()
            data = body.encode()
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def log_message(self, *args):
            pass

    server = ThreadingHTTPServer(("0.0.0.0", port), Handler)
    if tls_ctx is not None:
        # Lazy handshake: with do_handshake_on_connect the handshake
        # would run inside accept() on the single serve_forever thread,
        # so one peer that connects and never sends a ClientHello wedges
        # every later scrape. Deferring it moves the handshake into the
        # per-connection handler thread, where Handler.timeout bounds it.
        server.socket = tls_ctx.wrap_socket(
            server.socket, server_side=True, do_handshake_on_connect=False
        )
    threading.Thread(target=server.serve_forever, name=name, daemon=True).start()
    return server


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="cron-operator-tpu",
        description="TPU-native cron-scheduling framework for ML training workloads",
    )
    parser.add_argument("--version", action="version", version=__version__)
    sub = parser.add_subparsers(dest="command")

    start = sub.add_parser("start", help="start the operator manager")
    # Reference flag surface (start.go:215-247):
    start.add_argument("--max-concurrent-reconciles", type=int, default=10)
    start.add_argument("--qps", type=float, default=30,
                       help="kube client QPS (cluster mode: token-bucket "
                            "flow control, reference default 30; the "
                            "embedded control plane is not rate-limited)")
    start.add_argument("--burst", type=int, default=50,
                       help="kube client burst (cluster mode)")
    start.add_argument("--metrics-bind-address", default="0",
                       help="':8080' to enable, '0' to disable (default)")
    # Secure-metrics trio (reference start.go:226-242; default-secure,
    # default-no-h2 per the Rapid-Reset CVE guidance it cites):
    # nargs='?' + const=True: Go flag parity — bare `--metrics-secure`
    # means true, `--metrics-secure=false` still works.
    start.add_argument("--metrics-secure", type=_bool_arg, default=True,
                       nargs="?", const=True,
                       metavar="BOOL",
                       help="serve /metrics over HTTPS (default true; "
                            "--metrics-secure=false for plain HTTP). With "
                            "no --metrics-cert-path a self-signed cert is "
                            "generated (dev/testing convenience, as in the "
                            "reference)")
    start.add_argument("--metrics-cert-path", default="",
                       help="directory containing the metrics server "
                            "certificate (watched for rotation)")
    start.add_argument("--metrics-cert-name", default="tls.crt")
    start.add_argument("--metrics-cert-key", default="tls.key")
    start.add_argument("--metrics-token", default=None,
                       help="bearer token required to scrape /metrics "
                            "(defaults to --serve-api-token when that is "
                            "set; unauthenticated otherwise)")
    start.add_argument("--enable-http2", action="store_true", default=False,
                       help="allow HTTP/2 ALPN on the TLS endpoints "
                            "(default off, mirroring the reference's CVE "
                            "mitigation; the embedded servers speak "
                            "HTTP/1.1 either way)")
    start.add_argument("--health-probe-bind-address", default=":8081")
    start.add_argument("--leader-elect", action="store_true", default=False)
    start.add_argument("--zap-log-level", default="info",
                       choices=["debug", "info", "warn", "error"])
    start.add_argument("--zap-encoder", default="console",
                       choices=["console", "json"])
    # TPU-native flags:
    start.add_argument("--api-server", default="embedded",
                       choices=["embedded", "cluster"],
                       help="'embedded' runs the in-process control plane "
                            "(standalone mode); 'cluster' reconciles CRs in "
                            "a real Kubernetes cluster (in-cluster config "
                            "or --kube-* flags)")
    start.add_argument("--kube-server", default=None,
                       help="kube-apiserver URL (default: in-cluster "
                            "discovery)")
    start.add_argument("--kube-token-file", default=None,
                       help="bearer-token file for --kube-server")
    start.add_argument("--kube-ca-file", default=None,
                       help="CA bundle for --kube-server")
    start.add_argument("--kube-insecure", action="store_true", default=False,
                       help="skip TLS verification (dev only)")
    start.add_argument("--load", action="append", default=[],
                       metavar="MANIFEST.yaml",
                       help="apply YAML manifest(s) into the embedded control "
                            "plane at startup (repeatable)")
    start.add_argument("--backend", default=None,
                       choices=["local", "none"],
                       help="JAXJob execution backend: 'local' runs training "
                            "in-process on the available TPU/CPU devices; "
                            "'none' schedules objects only. Defaults to "
                            "'local' in embedded mode, 'none' in cluster "
                            "mode (real workloads run as pods there)")
    start.add_argument("--serve-api", default=None, metavar="[HOST]:PORT",
                       help="embedded mode only: serve the control plane "
                            "over the Kubernetes REST protocol (apply Crons "
                            "with any kube-style client instead of --load)")
    start.add_argument("--serve-api-token", default=None,
                       help="bearer token required by --serve-api "
                            "(default: unauthenticated on localhost)")
    # The reference's webhook server is cert-watched TLS
    # (start.go:100-119); the served API is this framework's equivalent
    # inbound surface, so it carries the same cert plumbing. Opt-in
    # (certs provided, never self-signed): webhook-style serving always
    # has operator-provisioned certs.
    start.add_argument("--serve-api-cert-path", default="",
                       help="directory with the API server certificate — "
                            "enables HTTPS on --serve-api (watched for "
                            "rotation, like --metrics-cert-path)")
    start.add_argument("--serve-api-cert-name", default="tls.crt")
    start.add_argument("--serve-api-cert-key", default="tls.key")
    start.add_argument("--serve-api-tenant-token", action="append",
                       default=[], metavar="TOKEN=TENANT",
                       help="additional --serve-api bearer token mapped to a "
                            "named tenant identity (repeatable); tenants get "
                            "separate APF fair-queue flows, so one tenant's "
                            "burst cannot starve another's requests")
    start.add_argument("--serve-api-seats", type=int, default=None,
                       metavar="N",
                       help="concurrency seats for the front door's "
                            "'workload' priority level (system/batch levels "
                            "scale to N/2; default: APF built-in budgets)")
    start.add_argument("--serve-api-queue-depth", type=int, default=None,
                       metavar="N",
                       help="per-tenant admission queue depth before 429 "
                            "(default: APF built-in budgets)")
    start.add_argument("--run-for", type=float, default=None,
                       metavar="SECONDS",
                       help="exit after N seconds (default: run until signal)")
    start.add_argument("--chaos-seed", type=int, default=None,
                       metavar="SEED",
                       help="embedded mode only: wrap the control plane in "
                            "the seeded fault injector (runtime/faults.py) — "
                            "deterministic conflict/transient/latency "
                            "injection for resilience drills; faults are "
                            "counted in faults_injected_total{kind}. See "
                            "README 'Fault tolerance & chaos testing'")
    start.add_argument("--data-dir", default=None, metavar="DIR",
                       help="embedded mode only: persist control-plane "
                            "state to DIR (append-only WAL + compacted "
                            "snapshots) and recover it on startup — Crons, "
                            "workloads, lastScheduleTime and resource "
                            "versions survive a crash/restart; ticks "
                            "missed during downtime fire or are skipped "
                            "per concurrencyPolicy and spec."
                            "startingDeadlineSeconds. Unset = in-memory "
                            "only (state lost on exit)")
    start.add_argument("--shards", type=int, default=1, metavar="N",
                       help="embedded mode only: partition the control "
                            "plane into N shards by a stable hash of "
                            "(namespace, name). Each shard owns its own "
                            "store, WAL directory (<data-dir>/shard-i), "
                            "worker pool and leader lease; a router "
                            "preserves the single-store client surface. "
                            "See README 'Scale-out'")
    start.add_argument("--replicas", type=int, default=0, choices=[0, 1],
                       metavar="R",
                       help="embedded mode only: hot-standby follower "
                            "replicas per shard (0 or 1). Followers "
                            "replay the shard's WAL byte stream "
                            "continuously and are promotable on leader "
                            "failure; requires --data-dir")
    start.add_argument("--split", action="append", default=[],
                       metavar="shard=K",
                       help="embedded sharded mode with --data-dir only: "
                            "after startup, live-split shard K — carve "
                            "its widest owned hash range in half onto a "
                            "brand-new child shard while serving "
                            "(repeatable; see README 'Scale-out')")
    start.add_argument("--auto-split-p99", type=float, default=None,
                       metavar="S",
                       help="auto-split: when a shard's durable-write "
                            "p99 (group-commit fsync histogram) stays "
                            "above S seconds across two consecutive "
                            "probe windows, split it live. Requires "
                            "sharded embedded mode with --data-dir")
    start.add_argument("--auto-split-max", type=int, default=8,
                       metavar="N",
                       help="auto-split ceiling: never grow past N "
                            "total shards (default 8)")
    start.add_argument("--scrub-interval", type=float, default=30.0,
                       metavar="S",
                       help="embedded sharded mode with --data-dir: "
                            "background integrity-scrub cadence in "
                            "seconds — re-verify sealed WAL segment "
                            "CRCs, snapshot digests and leader/follower "
                            "agreement while cold (0 disables; findings "
                            "land on /debug/shards and as "
                            "corruption_detected events)")
    start.add_argument("--no-checksums", action="store_true", default=False,
                       help="DANGEROUS: disable per-record WAL CRC32C "
                            "stamping and verification (and with it the "
                            "corruption-aware recovery guarantees). "
                            "Exists for the chaos counter-proof and A/B "
                            "overhead measurement only")
    start.add_argument("--fleet-pool", default=None, metavar="POOL",
                       help="enable the heterogeneity-aware fleet "
                            "scheduler over a pool of named slice types, "
                            "e.g. 'v5e-16=2,v4-8=4,cpu=8' (shorthand=count;"
                            " names that are not TPU slice shorthands "
                            "model 1-chip host-local capacity). Fired "
                            "workloads are placed on the slice type "
                            "maximizing aggregate throughput, queued when "
                            "saturated, and may preempt lower-priority "
                            "gangs. See README 'Fleet scheduling'")
    start.add_argument("--fleet-quota", action="append", default=[],
                       metavar="TENANT=CHIPS",
                       help="per-tenant concurrent chip quota for the "
                            "fleet scheduler (repeatable). Tenant = the "
                            "tpu.kubedl.io/tenant annotation, defaulting "
                            "to the workload's namespace")
    start.add_argument("--fleet-queue-depth", type=int, default=256,
                       metavar="N",
                       help="bounded fleet admission queue: fired "
                            "workloads beyond N waiting are shed with a "
                            "FleetRejected event (default 256)")
    start.add_argument("--audit-log", default=None, metavar="FILE",
                       help="append every audit record (committed store "
                            "verbs, controller decisions, cluster events) "
                            "as one JSON line to FILE — the durable "
                            "flight-recorder tape. Unset = in-memory ring "
                            "only (always on; served at /debug/audit)")
    start.add_argument("--shard-role", default=None,
                       choices=["router", "shard", "standby", "follower",
                                "supervisor"],
                       help="multi-PROCESS control plane role (see README "
                            "'Scale-out'). 'shard': one shard backend "
                            "process (store + WAL + Manager pool + WAL "
                            "ship socket + lease heartbeat); 'standby': "
                            "the shard's socket-fed replica that "
                            "self-promotes on lease expiry (add "
                            "--serve-reads to also serve the read plane); "
                            "'follower': a NON-promoting socket-fed "
                            "replica serving read-only list/watch on "
                            "--serve-api (scale reads by adding more); "
                            "'router': the consistent-hash front door over "
                            "--peers (add --read-peers for follower read "
                            "routing); 'supervisor': spawn the whole "
                            "topology as child processes (dev mode)")
    start.add_argument("--shard-index", type=int, default=0, metavar="I",
                       help="shard/standby roles: which shard this process "
                            "serves (owns <data-dir>/shard-I)")
    start.add_argument("--ship-port", type=int, default=0, metavar="PORT",
                       help="shard role: WAL ship socket port (0 = "
                            "ephemeral); standby/follower roles: the "
                            "leader's ship port to subscribe to")
    start.add_argument("--peers", default=None, metavar="HOST:PORT,...",
                       help="router role: comma-separated shard API "
                            "addresses in shard-index order")
    start.add_argument("--serve-reads", type=int, default=None,
                       metavar="PORT",
                       help="standby role: also bind a follower read "
                            "door on PORT (0 = ephemeral) serving "
                            "read-only list/watch from the replica — the "
                            "read plane's attached mode. The door stays "
                            "up across promotion (the replica store "
                            "becomes the leader store)")
    start.add_argument("--read-peers", default=None,
                       metavar="H:P,H:P;H:P,...",
                       help="router role: follower read endpoints per "
                            "shard — shards separated by ';' in "
                            "shard-index order, each a comma-separated "
                            "endpoint list (empty = no read plane for "
                            "that shard). Collection reads and watch "
                            "subscriptions round-robin across them with "
                            "read-your-writes rv barriers; writes and "
                            "consistency=strong reads ride the leader")
    start.add_argument("--lease-ttl", type=float, default=2.0, metavar="S",
                       help="shard/standby roles: leader lease TTL in "
                            "seconds (heartbeat renews at TTL/4; a standby "
                            "treats a lease older than TTL as leader death)")
    start.add_argument("--port-base", type=int, default=18080, metavar="P",
                       help="supervisor role: router serves on P, shard i "
                            "API on P+1+i, shard i WAL ship on P+51+i, "
                            "shard i standby read door on P+101+i")
    start.add_argument("--no-fencing", action="store_true", default=False,
                       help="shard/standby roles: do NOT fence the "
                            "persistence layer when the lease is lost to "
                            "a higher generation — a demoted zombie "
                            "keeps appending into the shared WAL "
                            "(split-brain). For the chaos counter-proof "
                            "only; never disable in a real deployment")
    start.add_argument("--promote-api-port", type=int, default=None,
                       metavar="PORT",
                       help="standby role: API port to bind AFTER "
                            "promotion (default: the followed leader's "
                            "--serve-api port). A gray-failed leader — "
                            "SIGSTOPped, not dead — still holds its "
                            "sockets, so promotion onto the same port "
                            "would fail; give the standby its own")
    start.add_argument("--promote-ship-port", type=int, default=None,
                       metavar="PORT",
                       help="standby role: WAL ship port to bind after "
                            "promotion (default: --ship-port); see "
                            "--promote-api-port")
    start.add_argument("--router-timeout", type=float, default=None,
                       metavar="S",
                       help="router role: per-request timeout toward "
                            "shard peers (default 30s). The circuit "
                            "breaker scores timeouts as failures, so a "
                            "tight timeout bounds how long a wedged "
                            "shard can hold requests before the "
                            "breaker fails fast")
    start.add_argument("--no-breakers", action="store_true", default=False,
                       help="router role: disable the per-shard circuit "
                            "breakers (every request goes to the wire "
                            "even when the shard is known-wedged)")
    start.add_argument("--no-net-heartbeats", action="store_true",
                       default=False,
                       help="shard/standby/follower roles: disable the "
                            "WAL-ship link heartbeats (ping/pong + read/"
                            "write deadlines). Without them a half-open "
                            "connection — asymmetric partition, dropped "
                            "FIN — wedges shipping silently while "
                            "follower lag grows. For the chaos "
                            "counter-proof only; never disable in a "
                            "real deployment")

    # kubectl-style inspection for standalone mode: the reference relies
    # on kubectl + CRD printcolumns (cron_types.go:33-36); with no
    # kube-apiserver in the embedded deployment, `get` is that surface,
    # speaking the same REST dialect --serve-api exposes.
    get = sub.add_parser(
        "get", help="list resources from a running operator's API"
    )
    get.add_argument("resource", choices=["crons", "workloads"],
                     help="'crons' prints the reference printcolumns; "
                          "'workloads' lists scheduled jobs with status")
    _add_connection_flags(get)

    desc = sub.add_parser(
        "describe", help="show one Cron's spec, status and events"
    )
    desc.add_argument("resource", choices=["cron"])
    desc.add_argument("name")
    _add_connection_flags(desc)

    # The reference's operational verbs are kubectl idioms: suspend is
    # `kubectl patch cron ... spec.suspend=true` (the gate the reconciler
    # honors at cron_controller.go:169-173); a manual run is `kubectl
    # create job --from=cronjob/...`. Standalone mode has no kubectl, so
    # the CLI carries them.
    for verb, desc_text in (
        ("suspend", "set spec.suspend=true (ticks stop firing)"),
        ("resume", "clear spec.suspend (ticks fire again)"),
    ):
        v = sub.add_parser(verb, help=desc_text)
        v.add_argument("resource", choices=["cron"])
        v.add_argument("name")
        _add_connection_flags(v)

    trig = sub.add_parser(
        "trigger",
        help="instantiate a Cron's workload template once, immediately "
             "(kubectl create job --from=cronjob analog); ignores "
             "suspend/deadline/concurrency gates",
    )
    trig.add_argument("resource", choices=["cron"])
    trig.add_argument("name")
    _add_connection_flags(trig)

    dele = sub.add_parser(
        "delete",
        help="delete a Cron (kubectl delete analog); owned workloads are "
             "cascade-collected via their owner references",
    )
    dele.add_argument("resource", choices=["cron"])
    dele.add_argument("name")
    _add_connection_flags(dele)
    return parser


def _add_connection_flags(p: argparse.ArgumentParser) -> None:
    """Shared client-connection flags for the inspection subcommands."""
    p.add_argument("-n", "--namespace", default="default")
    p.add_argument("--server", default="http://127.0.0.1:8443",
                   help="operator --serve-api address (or a real "
                        "kube-apiserver URL)")
    p.add_argument("--token", default=None, help="bearer token")
    p.add_argument("--ca-file", default=None,
                   help="CA bundle for an HTTPS --server")
    p.add_argument("--insecure", action="store_true", default=False,
                   help="skip TLS verification (dev only)")


def _client_from_args(args: argparse.Namespace):
    from cron_operator_tpu.api.scheme import default_scheme
    from cron_operator_tpu.runtime.cluster import (
        ClusterAPIServer,
        ClusterConfig,
    )

    return ClusterAPIServer(
        ClusterConfig(args.server, token=args.token,
                      ca_file=args.ca_file, insecure=args.insecure),
        scheme=default_scheme(),
    )


def _configure_logging(level: str, encoder: str) -> None:
    lvl = {"debug": logging.DEBUG, "info": logging.INFO,
           "warn": logging.WARNING, "error": logging.ERROR}[level]
    if encoder == "json":
        fmt = ('{"ts":"%(asctime)s","level":"%(levelname)s",'
               '"logger":"%(name)s","msg":"%(message)s"}')
    else:
        fmt = "%(asctime)s\t%(levelname)s\t%(name)s\t%(message)s"
    logging.basicConfig(level=lvl, format=fmt, stream=sys.stderr)


def _parse_hostport(spec: Optional[str], default_host: str = "127.0.0.1",
                    default_port: int = 0) -> tuple:
    """'[HOST]:PORT' → (host, port); None → defaults."""
    if not spec:
        return default_host, default_port
    host, _, port = spec.rpartition(":")
    return host or default_host, int(port)


def _shard_manager_stack(store, scheme, metrics, tracer, journal,
                         args, recovering: bool):
    """The per-shard worker pool: Manager + CronReconciler + local
    executor against THIS process's store — the in-process analog of
    what each shard got in `--shards N` mode, now per OS process."""
    from cron_operator_tpu.api.scheme import GVK_CRON
    from cron_operator_tpu.backends.local import LocalExecutor
    from cron_operator_tpu.controller import CronReconciler
    from cron_operator_tpu.runtime import Manager

    manager = Manager(
        store,
        max_concurrent_reconciles=args.max_concurrent_reconciles,
        recovering=recovering,
        metrics=metrics,
        audit=journal,
    )
    reconciler = CronReconciler(store, metrics=manager.metrics,
                                tracer=tracer, audit=journal)
    manager.add_controller(
        "cron", reconciler.reconcile,
        for_gvk=GVK_CRON, owns=scheme.workload_kinds(),
    )
    executor = None
    if (args.backend or "local") == "local":
        executor = LocalExecutor(store, metrics=metrics, tracer=tracer,
                                 audit=journal)
        executor.start()
    manager.start()
    return manager, executor


def cmd_start_process(args: argparse.Namespace) -> int:
    """``start --shard-role ...``: one role of the multi-process control
    plane (runtime/transport.py). Each shard is a real OS process; the
    router proxies by shard index; standbys follow the shard's WAL over
    a socket and self-promote on lease-file expiry — so a literal
    ``kill -9`` of a shard leader is survivable (chaos_soak --processes
    proves it)."""
    _configure_logging(args.zap_log_level, args.zap_encoder)
    log = logging.getLogger("setup")

    from cron_operator_tpu.api.scheme import default_scheme
    from cron_operator_tpu.runtime.manager import Metrics
    from cron_operator_tpu.runtime.transport import (
        FollowerReadServer,
        RouterServer,
        ShardServing,
        StandbyServer,
    )
    from cron_operator_tpu.telemetry import AuditJournal, Tracer

    role = args.shard_role
    scheme = default_scheme()
    stop = threading.Event()
    if threading.current_thread() is threading.main_thread():
        for sig in (signal.SIGINT, signal.SIGTERM):
            signal.signal(sig, lambda *_: stop.set())

    if role == "supervisor":
        return _run_supervisor(args, stop, log)

    host, port = _parse_hostport(args.serve_api)
    metrics = Metrics()
    tracer = Tracer()
    tracer.instrument(metrics)

    if role == "shard":
        if not args.data_dir:
            log.error("--shard-role shard requires --data-dir")
            return 2
        serving = ShardServing(
            args.shard_index, args.data_dir, api_host=host, api_port=port,
            ship_port=args.ship_port, lease_ttl_s=args.lease_ttl,
            token=args.serve_api_token, scheme=scheme, metrics=metrics,
            fencing=not args.no_fencing, tracer=tracer,
            net_heartbeats=not args.no_net_heartbeats,
        )
        serving.audit.instrument(metrics)
        recovering = (serving.recovered is not None
                      and not serving.recovered.empty)
        if recovering:
            log.info(
                "shard %d recovered %d object(s) at rv=%d from %s",
                args.shard_index, len(serving.recovered.objects),
                serving.recovered.rv, serving.sdir,
            )
        manager, executor = _shard_manager_stack(
            serving.store, scheme, metrics, tracer, serving.audit,
            args, recovering,
        )
        log.info(
            "shard %d serving: api %s:%d, WAL ship :%d, lease ttl %.2fs "
            "(pid %d)", args.shard_index, host, serving.api_port,
            serving.ship_port, args.lease_ttl, _os.getpid(),
        )
        stop.wait(timeout=args.run_for)
        log.info("shard %d shutting down", args.shard_index)
        manager.stop()
        if executor is not None:
            executor.stop()
        serving.close()  # writes the audit-check (I9) report
        return 0

    if role == "standby":
        if not args.data_dir:
            log.error("--shard-role standby requires --data-dir")
            return 2
        if not args.ship_port:
            log.error("--shard-role standby requires --ship-port "
                      "(the leader's WAL ship socket)")
            return 2
        standby = StandbyServer(
            args.shard_index, args.data_dir, leader_host=host,
            ship_port=args.ship_port, api_port=port,
            lease_ttl_s=args.lease_ttl, token=args.serve_api_token,
            scheme=scheme, metrics=metrics,
            promote_api_port=args.promote_api_port,
            promote_ship_port=args.promote_ship_port,
            fencing=not args.no_fencing, tracer=tracer,
            serve_reads=args.serve_reads is not None,
            read_port=args.serve_reads or 0,
            net_heartbeats=not args.no_net_heartbeats,
        )
        log.info(
            "shard %d standby: following :%d, watching lease %s%s (pid %d)",
            args.shard_index, args.ship_port, standby.lease.path,
            (f", read door :{standby.read_door.port}"
             if standby.read_door is not None else ""),
            _os.getpid(),
        )
        report = standby.run(stop, max_wait_s=args.run_for)
        if report is None:
            log.info("shard %d standby stopping (never promoted)",
                     args.shard_index)
            standby.close()
            return 0
        log.info(
            "shard %d standby PROMOTED in %.3fs (i6_ok=%s, rv=%d); "
            "now serving api :%d", args.shard_index,
            report["duration_s"], report["i6_ok"], report["rv"],
            standby.serving.api_port,
        )
        standby.serving.audit.instrument(metrics)
        manager, executor = _shard_manager_stack(
            standby.serving.store, scheme, metrics, tracer,
            standby.serving.audit, args, recovering=True,
        )
        stop.wait(timeout=args.run_for)
        log.info("shard %d (promoted) shutting down", args.shard_index)
        manager.stop()
        if executor is not None:
            executor.stop()
        standby.close()
        return 0

    if role == "follower":
        if not args.ship_port:
            log.error("--shard-role follower requires --ship-port "
                      "(the leader's WAL ship socket)")
            return 2
        door = FollowerReadServer(
            args.shard_index, leader_host=host, ship_port=args.ship_port,
            host=host, port=port, token=args.serve_api_token,
            scheme=scheme, metrics=metrics, tracer=tracer,
            net_heartbeats=not args.no_net_heartbeats,
        )
        door.audit.instrument(metrics)
        log.info(
            "shard %d follower: read door %s:%d over WAL ship :%d (pid %d)",
            args.shard_index, host, door.port, args.ship_port,
            _os.getpid(),
        )
        stop.wait(timeout=args.run_for)
        log.info("shard %d follower shutting down", args.shard_index)
        door.close()
        return 0

    if role == "router":
        if not args.peers:
            log.error("--shard-role router requires --peers")
            return 2
        read_peers = None
        if args.read_peers:
            # ';' separates shards (shard-index order), ',' separates a
            # shard's follower endpoints; an empty segment leaves that
            # shard on the plain leader-only client.
            read_peers = [
                [e.strip() for e in seg.split(",") if e.strip()]
                for seg in args.read_peers.split(";")
            ]
        router = RouterServer(
            [p.strip() for p in args.peers.split(",") if p.strip()],
            host=host, port=port, token=args.serve_api_token,
            peer_token=args.serve_api_token, scheme=scheme,
            metrics=metrics,
            breakers=not args.no_breakers,
            request_timeout_s=args.router_timeout,
            tracer=tracer,
            read_peers=read_peers,
        )
        log.info("router serving %d shard(s) on %s:%d (pid %d)",
                 len(router.clients), host, router.port, _os.getpid())
        stop.wait(timeout=args.run_for)
        log.info("router shutting down")
        router.close()
        return 0

    log.error("unknown --shard-role %r", role)
    return 2


def _run_supervisor(args: argparse.Namespace, stop: threading.Event,
                    log) -> int:
    """Dev-mode topology: spawn router + N shard leaders + N standbys as
    child processes on deterministic ports and babysit them."""
    import subprocess
    import time

    if not args.data_dir:
        log.error("--shard-role supervisor requires --data-dir")
        return 2
    n = max(1, args.shards)
    base = args.port_base
    common = ["--zap-log-level", args.zap_log_level,
              "--health-probe-bind-address", "0",
              "--lease-ttl", str(args.lease_ttl)]
    if args.serve_api_token:
        common += ["--serve-api-token", args.serve_api_token]
    if args.no_net_heartbeats:
        common += ["--no-net-heartbeats"]

    def spawn(extra):
        cmd = [sys.executable, "-m", "cron_operator_tpu.cli.main",
               "start"] + extra + common
        return subprocess.Popen(cmd)

    procs = []
    peers = []
    read_peers = []
    for i in range(n):
        api_port, ship_port = base + 1 + i, base + 51 + i
        read_port = base + 101 + i
        peers.append(f"127.0.0.1:{api_port}")
        read_peers.append(f"127.0.0.1:{read_port}")
        procs.append(spawn([
            "--shard-role", "shard", "--shard-index", str(i),
            "--data-dir", args.data_dir,
            "--serve-api", f"127.0.0.1:{api_port}",
            "--ship-port", str(ship_port),
        ]))
        procs.append(spawn([
            "--shard-role", "standby", "--shard-index", str(i),
            "--data-dir", args.data_dir,
            "--serve-api", f"127.0.0.1:{api_port}",
            "--ship-port", str(ship_port),
            "--serve-reads", str(read_port),
        ]))
    procs.append(spawn([
        "--shard-role", "router",
        "--serve-api", f"127.0.0.1:{base}",
        "--peers", ",".join(peers),
        "--read-peers", ";".join(read_peers),
    ]))
    log.info(
        "supervisor: %d shard(s) + read-serving standbys + router on "
        "ports %d..%d (router %d); SIGINT/SIGTERM tears the topology "
        "down", n, base, base + 101 + n - 1, base,
    )
    try:
        stop.wait(timeout=args.run_for)
    finally:
        for p in procs:
            if p.poll() is None:
                p.terminate()
        deadline = time.monotonic() + 10.0
        for p in procs:
            try:
                p.wait(timeout=max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                p.kill()
    return 0


def cmd_start(args: argparse.Namespace) -> int:
    if getattr(args, "shard_role", None):
        return cmd_start_process(args)
    _configure_logging(args.zap_log_level, args.zap_encoder)
    log = logging.getLogger("setup")

    from cron_operator_tpu.api.scheme import GVK_CRON, default_scheme
    from cron_operator_tpu.controller import CronReconciler
    from cron_operator_tpu.runtime import APIServer, Manager
    from cron_operator_tpu.runtime.manager import PROMETHEUS_CONTENT_TYPE
    from cron_operator_tpu.runtime.kube import AlreadyExistsError

    scheme = default_scheme()
    if args.api_server == "cluster":
        from cron_operator_tpu.runtime.cluster import (
            ClusterAPIServer,
            ClusterConfig,
        )

        if args.kube_server:
            cfg = ClusterConfig(args.kube_server)
        else:
            cfg = ClusterConfig.in_cluster()
        # Explicit --kube-* flags override either base config.
        if args.kube_token_file:
            with open(args.kube_token_file) as f:
                cfg.token = f.read().strip()
        if args.kube_ca_file:
            cfg.ca_file = args.kube_ca_file
        if args.kube_insecure:
            cfg.insecure = True
        cfg.qps = float(args.qps)
        cfg.burst = int(args.burst)
        api = ClusterAPIServer(cfg, scheme=scheme)
        log.info("cluster mode: reconciling against %s", cfg.server)
    else:
        api = APIServer()

    # Live splits force the sharded plane even at --shards 1 (a split's
    # child needs the per-shard dir layout), and a data dir that has
    # LIVED through splits (ownership.json present) must come back up
    # sharded regardless of flags — the root-level single-store layout
    # cannot serve shard-i dirs.
    wants_split = bool(args.split) or args.auto_split_p99 is not None
    has_ownership = bool(
        args.data_dir
        and _os.path.exists(_os.path.join(args.data_dir, "ownership.json"))
    )
    sharded = (args.shards > 1 or args.replicas > 0
               or wants_split or has_ownership)
    if args.api_server == "cluster" and (args.shards != 1 or args.replicas):
        log.error("--shards/--replicas apply to the embedded control "
                  "plane only; a real cluster scales out via "
                  "etcd/apiserver replicas")
        return 2
    if args.shards < 1:
        log.error("--shards must be >= 1, got %d", args.shards)
        return 2
    if wants_split and (args.api_server == "cluster" or not args.data_dir):
        log.error("--split/--auto-split-p99 require the embedded "
                  "control plane with --data-dir (the WAL byte stream "
                  "is the split handoff medium)")
        return 2
    split_targets: List[int] = []
    for spec in args.split:
        try:
            split_targets.append(int(spec.split("=", 1)[-1]))
        except ValueError:
            log.error("--split expects shard=K, got %r", spec)
            return 2
    fleet = None
    fleet_matrix_path = None
    if args.fleet_pool and (args.api_server == "cluster" or sharded):
        # The fleet's capacity books are process-local and its creates
        # must see the same store the watch pump releases against.
        log.error("--fleet-pool applies to the single-shard embedded "
                  "control plane only")
        return 2

    # One tracer + one audit journal per process: the cron tick's trace
    # id links reconcile/submit spans (controller) to compile/first-step
    # spans (backend) on /debug/traces, and the journal records every
    # committed store verb / controller decision / cluster event for
    # /debug/audit (optionally tee'd to --audit-log as JSONL).
    from cron_operator_tpu.telemetry import AuditJournal, Tracer

    tracer = Tracer()
    journal = AuditJournal(sink_path=args.audit_log or None)

    persistence = None
    recovered = None
    plane = None
    managers: List[Manager] = []
    if sharded:
        # Sharded control plane (runtime/shard.py): N hash-partitioned
        # vertical slices, each with its own store, WAL dir, worker pool
        # and leader lease, behind a router that preserves the
        # single-store client surface for --serve-api/--load/backends.
        from cron_operator_tpu.runtime.manager import Metrics
        from cron_operator_tpu.runtime.shard import (
            ShardedControlPlane,
            ShardMetrics,
            ShardRouter,
        )

        shared_metrics = Metrics()
        tracer.instrument(shared_metrics)
        journal.instrument(shared_metrics)
        try:
            plane = ShardedControlPlane(
                n_shards=args.shards, replicas=args.replicas,
                data_dir=args.data_dir, metrics=shared_metrics,
                audit=journal, tracer=tracer,
                checksums=not args.no_checksums,
                scrub_interval_s=max(0.0, args.scrub_interval),
            )
        except ValueError as err:
            log.error("%s", err)
            return 2
        for s in plane.shards:
            if s.recovered is not None and not s.recovered.empty:
                log.info(
                    "durability: shard %d recovered %d object(s) at rv=%d "
                    "from %s", s.index, len(s.recovered.objects),
                    s.recovered.rv, s.data_dir,
                )
            if s.recovered is not None and s.recovered.integrity:
                verdict = s.recovered.integrity.get("verdict")
                if verdict not in (None, "clean", "verified"):
                    log.warning(
                        "integrity: shard %d recovery verdict %s: %s",
                        s.index, verdict, s.recovered.integrity,
                    )
        shard_backends = [s.store for s in plane.shards]
        if args.chaos_seed is not None:
            from cron_operator_tpu.runtime.faults import (
                FaultInjector,
                FaultPlan,
            )

            # Per-shard injectors with decorrelated seeds: shard i must
            # not see the same fault schedule as shard 0.
            shard_backends = [
                FaultInjector(b, FaultPlan.default_chaos(args.chaos_seed + i))
                for i, b in enumerate(shard_backends)
            ]
            log.warning("CHAOS MODE: injecting seeded faults (seed=%d) "
                        "into all %d shards", args.chaos_seed, args.shards)
        api = ShardRouter(shard_backends, ownership=plane.ownership,
                          metrics=shared_metrics)
        log.info(
            "sharded control plane: %d shard(s) (%d at boot, ownership "
            "epoch %d), %d hot-standby replica(s) per shard%s",
            plane.n_shards, plane.n_boot, plane.ownership.epoch,
            args.replicas,
            f", data dir {args.data_dir}" if args.data_dir else "",
        )
        if args.backend is None:
            args.backend = "local"
        for i, backend in enumerate(shard_backends):
            s = plane.shards[i]
            m = Manager(
                backend,
                max_concurrent_reconciles=args.max_concurrent_reconciles,
                leader_elect=args.leader_elect,
                recovering=s.recovered is not None and not s.recovered.empty,
                metrics=ShardMetrics(shared_metrics, i),
                audit=journal.shard_view(i),
            )
            # The shard's audit view stamps every record with the shard
            # index; /debug/shards names this manager as the leader.
            s.leader = m.identity
            # Each shard's reconciler talks DIRECTLY to its shard's
            # backend: workloads land on their owner's shard, keeping
            # ownerReferences and cascade delete intra-shard.
            rec = CronReconciler(backend, metrics=m.metrics, tracer=tracer,
                                 audit=journal.shard_view(i))
            m.add_controller(
                "cron",
                rec.reconcile,
                for_gvk=GVK_CRON,
                owns=scheme.workload_kinds(),
            )
            managers.append(m)
        manager = managers[0]  # registry-wide reads (/metrics) go anywhere
    else:
        if args.data_dir:
            if args.api_server == "cluster":
                log.error("--data-dir applies to the embedded control "
                          "plane only; cluster mode persists in etcd")
                return 2
            from cron_operator_tpu.runtime.persistence import Persistence

            # Attach to the raw store (before any chaos wrapper): the WAL
            # hooks live inside APIServer's commit path. The audit hook
            # goes on FIRST so recovery itself lands in the journal as a
            # crash_recovery cluster event.
            persistence = Persistence(args.data_dir)
            persistence.attach_audit(journal)
            recovered = persistence.start(api)
            if recovered.empty:
                log.info("durability: empty data dir %s; starting fresh",
                         args.data_dir)
            else:
                log.info(
                    "durability: recovered %d object(s) at rv=%d from %s "
                    "(snapshot=%s, wal records replayed=%d, torn dropped=%d)",
                    len(recovered.objects), recovered.rv, args.data_dir,
                    recovered.had_snapshot, recovered.wal_records_replayed,
                    recovered.torn_records_dropped,
                )

        # The raw (unwrapped) store backs /debug/shards in single-shard
        # mode; the audit hook rides the commit path, so it too attaches
        # before any chaos wrapper. Cluster mode has no embedded commit
        # path — the journal still records controller decisions there.
        raw_store = api
        if args.api_server != "cluster":
            api.attach_audit(journal)

        if args.chaos_seed is not None:
            if args.api_server == "cluster":
                log.error("--chaos-seed requires the embedded control plane "
                          "(never inject faults into a real cluster)")
                return 2
            from cron_operator_tpu.runtime.faults import (
                FaultInjector,
                FaultPlan,
            )

            api = FaultInjector(api, FaultPlan.default_chaos(args.chaos_seed))
            log.warning("CHAOS MODE: injecting seeded faults (seed=%d) into "
                        "the embedded control plane", args.chaos_seed)

        if args.backend is None:
            # In cluster mode workloads run as real pods; executing them
            # in-process inside the operator is opt-in only.
            args.backend = "none" if args.api_server == "cluster" else "local"
        manager = Manager(
            api,
            max_concurrent_reconciles=args.max_concurrent_reconciles,
            leader_elect=args.leader_elect,
            # After recovering real state, hold readyz until the catch-up
            # enqueue sweep drains once (missed ticks fired/skipped).
            recovering=recovered is not None and not recovered.empty,
            audit=journal,
        )
        tracer.instrument(manager.metrics)
        journal.instrument(manager.metrics)
        if args.fleet_pool:
            from cron_operator_tpu.runtime.fleet import (
                FleetScheduler,
                ThroughputMatrix,
                parse_pool,
                parse_quotas,
            )

            try:
                fleet_types = parse_pool(args.fleet_pool)
                fleet_quotas = parse_quotas(args.fleet_quota)
            except ValueError as err:
                log.error("--fleet-pool/--fleet-quota: %s", err)
                return 2
            # Throughput-matrix persistence (ROADMAP item 3): seed the
            # EMA from the previous run's sidecar so a restart plans with
            # yesterday's learned rates instead of the neutral prior; the
            # observatory's rollup hook saves it back periodically.
            matrix = None
            if args.data_dir:
                fleet_matrix_path = _os.path.join(
                    args.data_dir, "fleet_matrix.json"
                )
                seed = ThroughputMatrix.load_seed(fleet_matrix_path)
                matrix = ThroughputMatrix(seed=seed)
                if seed:
                    log.info(
                        "fleet: throughput matrix seeded with %d rate(s) "
                        "from %s", len(seed), fleet_matrix_path,
                    )
            # The fleet submits through the (possibly chaos-wrapped) api
            # so placement creates share the store path every other
            # write takes; its watch pump releases slices on terminal
            # workloads and refines the throughput matrix from the
            # tokens/s the executor publishes.
            fleet = FleetScheduler(
                fleet_types,
                api=api,
                matrix=matrix,
                metrics=manager.metrics,
                audit=journal,
                quotas=fleet_quotas,
                max_queue=args.fleet_queue_depth,
                backend_name=args.backend,
            )
            log.info(
                "fleet scheduler: pool %s, %d tenant quota(s), queue "
                "depth %d",
                ", ".join(f"{t.name}x{t.count}" for t in fleet_types),
                len(fleet_quotas), args.fleet_queue_depth,
            )
        reconciler = CronReconciler(api, metrics=manager.metrics,
                                    tracer=tracer, audit=journal,
                                    fleet=fleet)
        manager.add_controller(
            "cron",
            reconciler.reconcile,
            for_gvk=GVK_CRON,
            owns=scheme.workload_kinds(),
        )
        managers = [manager]

    # Fleet observatory: (a) the opted-in metric families mirror every
    # sample into a bounded multi-resolution time-series store, served
    # at /debug/timeline; (b) audit decision records fold into derived
    # utilization / deadline-SLO / queue-wait / goodput accounting,
    # served at /debug/fleet and rolled up as JSONL into --data-dir.
    # Both are pure in-memory folds — zero store/WAL writes added.
    from cron_operator_tpu.telemetry import (
        DEFAULT_HISTORY_FAMILIES,
        FleetObservatory,
        TimeSeriesStore,
    )

    registry = shared_metrics if sharded else manager.metrics
    history = TimeSeriesStore()
    registry.instrument(history, families=DEFAULT_HISTORY_FAMILIES)
    observatory = FleetObservatory(
        metrics=registry, tracer=tracer, data_dir=args.data_dir or None,
    )
    journal.attach_observer(observatory.on_record)
    if fleet is not None:
        observatory.attach_fleet(fleet)
        if fleet_matrix_path is not None:
            observatory.add_rollup_hook(
                lambda: fleet.matrix.save(fleet_matrix_path)
            )

    api_http = None
    api_cert_watcher = None
    if args.serve_api:
        if args.api_server == "cluster":
            log.error("--serve-api applies to the embedded control plane "
                      "only; cluster mode already has an apiserver")
            return 2
        from cron_operator_tpu.runtime.apiserver_http import HTTPAPIServer

        host, _, port = args.serve_api.rpartition(":")
        if not port.isdigit():
            log.error("--serve-api expects [HOST]:PORT, got %r",
                      args.serve_api)
            return 2
        api_tls_ctx = None
        if args.serve_api_cert_path:
            api_tls_ctx, api_cert_watcher = _watched_tls(
                args.serve_api_cert_path, args.serve_api_cert_name,
                args.serve_api_cert_key, args.enable_http2, log,
                "serve-api",
            )
            if api_tls_ctx is None:
                return 2
        tenant_tokens = {}
        for spec in args.serve_api_tenant_token:
            tok, _, tenant = spec.partition("=")
            if not tok or not tenant:
                log.error("--serve-api-tenant-token expects TOKEN=TENANT, "
                          "got %r", spec)
                return 2
            tenant_tokens[tok] = tenant
        admission = None
        if args.serve_api_seats or args.serve_api_queue_depth:
            from cron_operator_tpu.runtime.apf import (
                DEFAULT_LEVELS, FairQueueAdmission, LevelConfig,
            )

            seats = args.serve_api_seats or DEFAULT_LEVELS["workload"].seats
            depth = (args.serve_api_queue_depth
                     or DEFAULT_LEVELS["workload"].queue_depth)
            admission = FairQueueAdmission(levels={
                "system": LevelConfig(seats=max(1, seats // 2),
                                      queue_depth=depth * 2),
                "workload": LevelConfig(seats=seats, queue_depth=depth),
                "batch": LevelConfig(seats=max(1, seats // 2),
                                     queue_depth=max(1, depth // 2),
                                     max_queued=max(4, depth * 4)),
            })
        front_metrics = shared_metrics if sharded else manager.metrics
        api_http = HTTPAPIServer(
            api=api, scheme=scheme, host=host or "127.0.0.1",
            port=int(port), token=args.serve_api_token,
            tls_ctx=api_tls_ctx, tokens=tenant_tokens or None,
            admission=admission, metrics=front_metrics,
        )
        api_http.start()
        log.info("embedded API serving on %s", api_http.url)

    executor = None
    if args.backend == "local":
        from cron_operator_tpu.backends.local import LocalExecutor

        # The executor is process-wide (it drains workloads from every
        # shard through the router), so its metrics skip the shard label.
        executor_metrics = (
            shared_metrics if sharded else manager.metrics  # noqa: F821
        )
        executor = LocalExecutor(api, metrics=executor_metrics, tracer=tracer,
                                 audit=journal)
        executor.start()
    if fleet is not None:
        # Priority preemptions route through the executor so the elastic
        # chain resumes the victim (no executor → books-only preemption).
        fleet.backend = executor
        fleet.start()
    observatory.start()

    def _debug_shards_json() -> str:
        # Sharded: the plane owns the authoritative per-shard view
        # (WAL stats, follower lag, failover counts). Single store:
        # synthesize the same shape so dashboards/scripts need not
        # branch on topology.
        if plane is not None:
            return plane.render_debug_json()
        store = raw_store
        entry = {
            "shard": 0,
            "objects": len(store) if hasattr(store, "__len__") else None,
            "rv": int(getattr(store, "_rv", 0)),
            "failovers": 0,
            "leader": manager.identity,
            "data_dir": args.data_dir or None,
        }
        if persistence is not None:
            entry["wal"] = persistence.stats()
            entry["wal_buffered_bytes"] = persistence.buffered_bytes()
        return json.dumps(
            {
                "n_shards": 1,
                "replicas": 0,
                "composite_rv": entry["rv"],
                "objects": entry["objects"],
                "shards": [entry],
            },
            indent=2,
            default=str,
        )

    servers: List[ThreadingHTTPServer] = []
    health_port = _parse_bind(args.health_probe_bind_address)
    if health_port is not None:
        servers.append(
            _serve(
                health_port,
                {
                    # Sharded: the process is healthy/ready only when
                    # EVERY shard's manager is.
                    "/healthz": lambda: (
                        "ok" if all(m.healthz() for m in managers)
                        else "unhealthy", "text/plain"),
                    "/readyz": lambda: (
                        "ok" if all(m.readyz() for m in managers)
                        else "not ready", "text/plain"),
                },
                "health-probes",
            )
        )
        log.info("health probes serving on :%d", health_port)
    metrics_port = _parse_bind(args.metrics_bind_address)
    cert_watcher = None
    if metrics_port is not None:
        tls_ctx = None
        if args.metrics_secure:
            from cron_operator_tpu.utils.tlsutil import (
                self_signed_cert,
                server_context,
            )

            if args.metrics_cert_path:
                tls_ctx, cert_watcher = _watched_tls(
                    args.metrics_cert_path, args.metrics_cert_name,
                    args.metrics_cert_key, args.enable_http2, log,
                    "metrics",
                )
                if tls_ctx is None:
                    return 2
            else:
                try:
                    cert, key = self_signed_cert()
                except ImportError as err:
                    # Only the self-signed fallback needs `cryptography`;
                    # provided certs (server_context) use stdlib ssl. Fail
                    # fast with the actionable choices instead of a
                    # crash-looping ModuleNotFoundError mid-startup.
                    log.error(
                        "metrics TLS needs the 'cryptography' package to "
                        "generate a self-signed cert (%s); install it, "
                        "provide --metrics-cert-path, or pass "
                        "--metrics-secure=false", err,
                    )
                    return 2
                tls_ctx = server_context(
                    cert, key, enable_http2=args.enable_http2
                )
                log.info(
                    "metrics TLS with a generated self-signed cert (%s) — "
                    "pass --metrics-cert-path for production", cert,
                )
            if not args.enable_http2:
                log.info("disabling http/2")
        metrics_token = args.metrics_token or args.serve_api_token
        metrics_authn = None
        if args.metrics_secure and not metrics_token:
            if args.api_server == "cluster":
                # The reference's exact gate: every scrape's bearer token
                # goes through TokenReview + SubjectAccessReview for GET
                # /metrics (start.go:121-133 FilterProvider). The RBAC
                # for the review calls ships in
                # config/rbac/metrics_auth_role.yaml; scrapers bind
                # metrics_reader_role.yaml. Prometheus sends its SA token
                # via the ServiceMonitor's bearerTokenFile.
                from cron_operator_tpu.runtime.authfilter import (
                    ScrapeAuthenticator,
                )

                metrics_authn = ScrapeAuthenticator(api).allow
                log.info(
                    "metrics scrapes authenticated via kube "
                    "TokenReview/SubjectAccessReview"
                )
            else:
                # Divergence from the reference: its FilterProvider can
                # lean on the cluster's TokenReview/SubjectAccessReview
                # for every scrape (start.go:121-133); embedded mode has
                # no tokenreview authority, so instead of serving TLS
                # without authentication we mint a per-process bearer
                # token. Logged exactly once, at startup — copy it into
                # the scraper, or pass --metrics-token to pin one.
                import secrets

                metrics_token = secrets.token_urlsafe(32)
                log.warning(
                    "metrics auth: no --metrics-token/--serve-api-token "
                    "set; generated bearer token for this process: %s",
                    metrics_token,
                )
        servers.append(
            _serve(
                metrics_port,
                {
                    "/metrics": lambda: (
                        manager.metrics.render_prometheus(),
                        PROMETHEUS_CONTENT_TYPE,
                    ),
                    # Finished spans of recent ticks, grouped by trace id —
                    # the qualitative debug view behind the /metrics
                    # quantities (same TLS/token gate as /metrics).
                    "/debug/traces": lambda: (
                        tracer.render_json(), "application/json"
                    ),
                    # Flight recorder: typed audit records with filter
                    # params (?kind=&event=&trace=&shard=&key=&limit=).
                    "/debug/audit": lambda params: (
                        journal.render_json(params), "application/json"
                    ),
                    # Per-shard durability view: rv, WAL stats, follower
                    # replication lag, leader identity.
                    "/debug/shards": lambda: (
                        _debug_shards_json(), "application/json"
                    ),
                    # Bounded metric history at several bucket widths
                    # (?family=&series=&res=&limit=).
                    "/debug/timeline": lambda params: (
                        history.render_json(params), "application/json"
                    ),
                    # Derived fleet accounting: utilization, deadline
                    # SLO, queue waits, goodput, throughput matrix.
                    "/debug/fleet": lambda params: (
                        observatory.render_json(params), "application/json"
                    ),
                },
                "metrics",
                tls_ctx=tls_ctx,
                token=metrics_token,
                authn=metrics_authn,
            )
        )
        log.info("metrics serving on :%d (%s)", metrics_port,
                 "https" if tls_ctx is not None else "http")

    for manifest in args.load:
        import yaml

        with open(manifest) as f:
            for doc in yaml.safe_load_all(f):
                if not doc:
                    continue
                doc.setdefault("metadata", {}).setdefault("namespace", "default")
                try:
                    api.create(doc)
                except AlreadyExistsError:
                    # Idempotent apply: restarts/replicas must not crash on
                    # manifests already in the cluster.
                    log.info(
                        "%s %s/%s already exists; leaving as-is",
                        doc.get("kind"), doc["metadata"]["namespace"],
                        doc["metadata"].get("name"),
                    )
                    continue
                log.info(
                    "applied %s %s/%s", doc.get("kind"),
                    doc["metadata"]["namespace"], doc["metadata"].get("name"),
                )

    stop = threading.Event()
    if threading.current_thread() is threading.main_thread():
        for sig in (signal.SIGINT, signal.SIGTERM):
            signal.signal(sig, lambda *_: stop.set())

    log.info("starting %d manager(s) (version %s)", len(managers),
             __version__)
    for m in managers:
        m.start()
    if args.api_server == "cluster":
        from cron_operator_tpu.api.scheme import GVK_CRON as _cron_gvk

        api.start_watches([_cron_gvk] + scheme.workload_kinds())

    # -- live shard splitting (admin trigger + auto-split monitor) --------

    def _wire_split_child() -> None:
        """Start the serving stack of the newest split child: the CLI's
        router gains the backend + the new ownership map, and a fresh
        Manager + reconciler lead the child exactly like a boot shard."""
        child = plane.shards[-1]
        backend = child.store
        api.add_shard(backend)
        api.set_ownership(plane.ownership)
        m = Manager(
            backend,
            max_concurrent_reconciles=args.max_concurrent_reconciles,
            leader_elect=args.leader_elect,
            recovering=True,  # inherited objects get a catch-up pass
            metrics=ShardMetrics(shared_metrics, child.index),
            audit=journal.shard_view(child.index),
        )
        child.leader = m.identity
        rec = CronReconciler(backend, metrics=m.metrics, tracer=tracer,
                             audit=journal.shard_view(child.index))
        m.add_controller("cron", rec.reconcile, for_gvk=GVK_CRON,
                         owns=scheme.workload_kinds())
        managers.append(m)
        m.start()
        log.info("shard %d: split child serving (manager %s)",
                 child.index, m.identity)

    def _run_split(index: int) -> bool:
        try:
            report = plane.split_shard(index)
        except Exception:
            log.exception("live split of shard %d failed", index)
            return False
        _wire_split_child()
        log.info(
            "live split: shard %d -> child %d at epoch %d (moved=%d, "
            "dark window %.3fs)", report["parent"], report["child"],
            report["epoch"], report["moved"], report["dark_window_s"],
        )
        return True

    def _auto_split_monitor() -> None:
        """Sample each shard's group-commit fsync histogram every probe
        window; two CONSECUTIVE windows with a delta p99 above the
        threshold (and enough writes to mean it) split the hottest
        shard live, up to --auto-split-max total shards."""
        probe_s = 5.0
        min_samples = 32
        prev: Dict[int, Any] = {}
        streak: Dict[int, int] = {}
        while not stop.wait(probe_s):
            if plane.n_shards >= max(2, args.auto_split_max):
                return
            hottest = None  # (p99, shard index)
            for s in list(plane.shards):
                h = ShardMetrics(shared_metrics, s.index).histogram(
                    "wal_fsync_seconds")
                if h is None:
                    continue
                last = prev.get(s.index)
                prev[s.index] = h
                if last is None:
                    continue
                delta = [a - b for a, b in zip(h["counts"], last["counts"])]
                n = h["count"] - last["count"]
                if n < min_samples:
                    streak[s.index] = 0
                    continue
                p99 = _histogram_quantile(h["buckets"], delta, 0.99)
                if p99 is not None and p99 > args.auto_split_p99:
                    streak[s.index] = streak.get(s.index, 0) + 1
                    if hottest is None or p99 > hottest[0]:
                        hottest = (p99, s.index)
                else:
                    streak[s.index] = 0
            if hottest is not None and streak.get(hottest[1], 0) >= 2:
                index = hottest[1]
                streak[index] = 0
                log.warning(
                    "auto-split: shard %d durable-write p99 %.4fs > "
                    "%.4fs for two consecutive windows — splitting live",
                    index, hottest[0], args.auto_split_p99,
                )
                _run_split(index)
                prev.clear()

    if plane is not None:
        for split_index in split_targets:
            _run_split(split_index)
        if args.auto_split_p99 is not None:
            threading.Thread(
                target=_auto_split_monitor, name="auto-split", daemon=True
            ).start()

    stop.wait(timeout=args.run_for)

    log.info("shutting down")
    if cert_watcher is not None:
        cert_watcher.stop()
    if api_cert_watcher is not None:
        api_cert_watcher.stop()
    for m in managers:
        m.stop()
    if api_http is not None:
        api_http.stop()
    observatory.stop()
    # Final rollup: flush the accounting line + sidecar hooks (the
    # throughput matrix save) so a clean shutdown persists the model.
    observatory.rollup()
    if fleet is not None:
        fleet.stop()
    if executor is not None:
        executor.stop()
    if plane is not None:
        plane.close()  # per-shard stores, WALs and follower stores
    elif args.api_server == "cluster":
        api.stop()  # ClusterAPIServer: stop watch threads
    else:
        api.close()  # embedded store: stop the watch dispatcher
    if persistence is not None:
        persistence.close()  # flush + fsync the WAL tail
    for s in servers:
        s.shutdown()
    return 0


def _histogram_quantile(buckets, counts, q: float) -> Optional[float]:
    """Bucket-resolution quantile over per-bucket counts (the last
    count is the +Inf overflow bucket). Returns the upper edge of the
    bucket holding the q-rank sample — the same conservative estimate
    Prometheus histogram_quantile gives at bucket granularity."""
    total = sum(counts)
    if total <= 0:
        return None
    rank = q * total
    cum = 0.0
    for i, c in enumerate(counts):
        cum += c
        if cum >= rank:
            return float(buckets[i]) if i < len(buckets) else float("inf")
    return float("inf")


def _age(creation_ts: Optional[str], now=None) -> str:
    """kubectl-style age: 42s / 7m / 3h / 5d."""
    from datetime import datetime, timezone

    from cron_operator_tpu.api.v1alpha1 import parse_time

    created = parse_time(creation_ts)
    if created is None:
        return "<unknown>"
    now = now or datetime.now(timezone.utc)
    s = max(0, int((now - created).total_seconds()))
    if s < 120:
        return f"{s}s"
    if s < 7200:
        return f"{s // 60}m"
    if s < 172800:
        return f"{s // 3600}h"
    return f"{s // 86400}d"


def _print_table(headers: List[str], rows: List[List[str]]) -> None:
    widths = [
        max(len(h), *(len(r[i]) for r in rows)) if rows else len(h)
        for i, h in enumerate(headers)
    ]
    for line in ([headers] + rows):
        print("   ".join(c.ljust(w) for c, w in zip(line, widths)).rstrip())


def cmd_get(args: argparse.Namespace) -> int:
    from cron_operator_tpu.controller.workload import get_job_status
    from cron_operator_tpu.runtime.kube import ApiError, NotFoundError

    api = _client_from_args(args)
    scheme = api.scheme
    try:
        if args.resource == "crons":
            crons = api.list("apps.kubedl.io/v1alpha1", "Cron",
                             namespace=args.namespace)
            rows = []
            for c in crons:
                meta = c.get("metadata") or {}
                spec = c.get("spec") or {}
                st = c.get("status") or {}
                rows.append([
                    meta.get("name", ""),
                    spec.get("schedule", ""),
                    str(bool(spec.get("suspend", False))).lower(),
                    st.get("lastScheduleTime") or "<none>",
                    _age(meta.get("creationTimestamp")),
                ])
            # Reference CRD printcolumns (cron_types.go:33-36).
            _print_table(
                ["NAME", "SCHEDULE", "SUSPEND", "LAST SCHEDULE", "AGE"],
                rows,
            )
        else:
            rows = []
            for gvk in scheme.workload_kinds():
                try:
                    workloads = api.list(gvk.api_version, gvk.kind,
                                         namespace=args.namespace)
                except NotFoundError:
                    # A real apiserver without this workload CRD installed
                    # 404s the kind; list what exists instead of aborting.
                    continue
                for w in workloads:
                    meta = w.get("metadata") or {}
                    status = get_job_status(w)
                    last = (
                        status.last_condition_type() if status else None
                    )
                    rows.append([
                        meta.get("name", ""),
                        gvk.kind,
                        last or "Pending",
                        (meta.get("labels") or {}).get(
                            "kubedl.io/cron-name", "<none>"),
                        _age(meta.get("creationTimestamp")),
                    ])
            _print_table(["NAME", "KIND", "STATUS", "CRON", "AGE"], rows)
    except ApiError as err:
        # Connection refused / 401 / missing CRD etc. — a CLI prints one
        # line, not a traceback.
        print(f"error: {err}", file=sys.stderr)
        return 1
    finally:
        api.stop()
    return 0


def cmd_describe(args: argparse.Namespace) -> int:
    """kubectl-describe analog for a Cron: spec, status, and its events
    (the reference delegates this to kubectl; standalone mode has none)."""
    from cron_operator_tpu.runtime.kube import ApiError, NotFoundError

    api = _client_from_args(args)
    try:
        try:
            cron = api.get("apps.kubedl.io/v1alpha1", "Cron",
                           args.namespace, args.name)
        except NotFoundError:
            print(f"error: cron {args.namespace}/{args.name} not found",
                  file=sys.stderr)
            return 1
        spec = cron.get("spec") or {}
        st = cron.get("status") or {}
        meta = cron.get("metadata") or {}
        print(f"Name:               {meta.get('name')}")
        print(f"Namespace:          {meta.get('namespace')}")
        print(f"Schedule:           {spec.get('schedule')}")
        print(f"Concurrency Policy: {spec.get('concurrencyPolicy', 'Allow')}")
        print(f"Suspend:            "
              f"{str(bool(spec.get('suspend', False))).lower()}")
        if spec.get("deadline"):
            print(f"Deadline:           {spec['deadline']}")
        if spec.get("historyLimit") is not None:
            print(f"History Limit:      {spec['historyLimit']}")
        print(f"Last Schedule Time: {st.get('lastScheduleTime', '<none>')}")
        active = st.get("active") or []
        print(f"Active:             {len(active)}")
        for ref in active:
            print(f"  {ref.get('kind')}/{ref.get('name')}")
        history = st.get("history") or []
        if history:
            print("History:")
            for h in history:
                obj = h.get("object") or {}
                print(f"  {obj.get('kind')}/{obj.get('name')}   "
                      f"{h.get('status', '')}   created "
                      f"{h.get('created', '')}")
        try:
            events = api.list("v1", "Event", args.namespace)
        except NotFoundError:
            events = []
        mine = sorted(
            (
                e for e in events
                if (e.get("involvedObject") or {}).get("name") == args.name
                and (e.get("involvedObject") or {}).get("kind") == "Cron"
            ),
            # Real apiservers LIST in name order (random uuid suffixes);
            # chronological order is what a debugger needs.
            key=lambda e: e.get("lastTimestamp") or "",
        )
        print("Events:" if mine else "Events:             <none>")
        for e in mine[-20:]:
            print(f"  {e.get('type', ''):8} {e.get('reason', ''):22} "
                  f"{_age(e.get('lastTimestamp')):>6}   "
                  f"{e.get('message', '')}")
    except ApiError as err:
        print(f"error: {err}", file=sys.stderr)
        return 1
    finally:
        api.stop()
    return 0


def cmd_suspend(args: argparse.Namespace, suspend: bool) -> int:
    """Flip ``spec.suspend`` (the reference's ``kubectl patch`` idiom; the
    reconciler stops/starts ticking on the watch event,
    ``cron_controller.go:169-173``). Read-modify-update with a conflict
    retry: the primary use case is suspending a cron the live operator is
    actively reconciling, so a status patch landing between GET and PUT
    (resourceVersion bump) must not fail the command."""
    from cron_operator_tpu.runtime.kube import (
        ApiError,
        ConflictError,
        NotFoundError,
    )

    api = _client_from_args(args)
    try:
        for attempt in range(5):
            try:
                cron = api.get("apps.kubedl.io/v1alpha1", "Cron",
                               args.namespace, args.name)
            except NotFoundError:
                print(f"error: cron {args.namespace}/{args.name} not found",
                      file=sys.stderr)
                return 1
            already = bool((cron.get("spec") or {}).get("suspend", False))
            if already == suspend:
                print(f"cron.apps.kubedl.io/{args.name} unchanged "
                      f"(suspend={str(suspend).lower()})")
                return 0
            cron.setdefault("spec", {})["suspend"] = suspend
            try:
                api.update(cron)
            except ConflictError:
                continue  # re-read the bumped resourceVersion and retry
            print(f"cron.apps.kubedl.io/{args.name} "
                  f"{'suspended' if suspend else 'resumed'}")
            return 0
        print("error: persistent resourceVersion conflicts (5 attempts)",
              file=sys.stderr)
        return 1
    except ApiError as err:
        print(f"error: {err}", file=sys.stderr)
        return 1
    finally:
        api.stop()


def cmd_trigger(args: argparse.Namespace) -> int:
    """Create one workload from the Cron's template right now — the
    ``kubectl create job --from=cronjob/<name>`` analog. Deliberately
    bypasses the reconciler's scheduling gates (suspend/deadline/
    concurrency): a manual trigger is an operator saying "run it anyway".
    Everything else matches a scheduled run — shared ownership stamping
    (cron-name label + owner-ref via ``attach_cron_ownership``, so status
    sync, history and cascade-GC pick it up) and the same TPU admission/
    topology injection the tick path applies before POSTing."""
    import copy as _copy
    import time as _time

    from cron_operator_tpu.backends.tpu import inject_tpu_topology
    from cron_operator_tpu.controller.workload import attach_cron_ownership
    from cron_operator_tpu.runtime.kube import (
        AlreadyExistsError,
        ApiError,
        NotFoundError,
    )

    api = _client_from_args(args)
    try:
        try:
            cron = api.get("apps.kubedl.io/v1alpha1", "Cron",
                           args.namespace, args.name)
        except NotFoundError:
            print(f"error: cron {args.namespace}/{args.name} not found",
                  file=sys.stderr)
            return 1
        template = ((cron.get("spec") or {}).get("template") or {}).get(
            "workload")
        if (
            not template
            or not template.get("kind")
            or not template.get("apiVersion")
        ):
            print("error: cron has no workload template with "
                  "apiVersion + kind", file=sys.stderr)
            return 1

        # The timestamp is second-granular, so two triggers in the same
        # second would collide; disambiguate with a short suffix instead
        # of telling the user to retry (ADVICE r4). Each attempt builds
        # the workload from scratch AFTER the name is final: the TPU seam
        # below bakes the name into the coordinator env
        # (JAX_COORDINATOR_ADDRESS = "{name}-worker-0..."), so renaming a
        # previously injected object would ship a dangling DNS name.
        created = name = None
        for attempt in range(5):
            suffix = f"-{attempt}" if attempt else ""
            name = f"{args.name}-manual-{int(_time.time())}{suffix}"
            w = _copy.deepcopy(template)
            meta = w.setdefault("metadata", {})
            meta.pop("generateName", None)
            # "-manual-" keeps manual runs visually distinct from
            # scheduled ones (whose names encode the tick unix time) and
            # out of the deterministic-name fail-over guard's namespace.
            meta["name"] = name
            attach_cron_ownership(
                w, args.name, (cron.get("metadata") or {}).get("uid"),
                args.namespace,
            )
            # Same TPU seam as the tick path (cron_controller reconcile):
            # nodeSelectors / chip resources / replicas=hosts /
            # coordinator env must be on the object we POST; invalid
            # annotations fail the command the way FailedTPUAdmission
            # fails the tick.
            try:
                inject_tpu_topology(w)
            except ValueError as err:
                print(f"error: TPU admission failed: {err}",
                      file=sys.stderr)
                return 1
            try:
                created = api.create(w)
                break
            except AlreadyExistsError:
                continue
        if created is None:
            print(f"error: {name} already exists (retry in 1s)",
                  file=sys.stderr)
            return 1
        api.record_event(
            cron, "Normal", "ManualTrigger",
            f"manually triggered workload {meta['name']}",
        )
        kind = created.get("kind", "workload")
        print(f"{kind.lower()}/{created['metadata']['name']} created")
    except ApiError as err:
        print(f"error: {err}", file=sys.stderr)
        return 1
    finally:
        api.stop()
    return 0


def cmd_delete(args: argparse.Namespace) -> int:
    """kubectl-delete analog. Background propagation — owned workloads go
    via their owner references (the store's cascade GC; a real apiserver's
    garbage collector)."""
    from cron_operator_tpu.runtime.kube import ApiError, NotFoundError

    api = _client_from_args(args)
    try:
        try:
            api.delete("apps.kubedl.io/v1alpha1", "Cron",
                       args.namespace, args.name, propagation="Background")
        except NotFoundError:
            print(f"error: cron {args.namespace}/{args.name} not found",
                  file=sys.stderr)
            return 1
        print(f"cron.apps.kubedl.io/{args.name} deleted")
    except ApiError as err:
        print(f"error: {err}", file=sys.stderr)
        return 1
    finally:
        api.stop()
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    if args.command == "start":
        return cmd_start(args)
    if args.command == "get":
        return cmd_get(args)
    if args.command == "describe":
        return cmd_describe(args)
    if args.command == "suspend":
        return cmd_suspend(args, suspend=True)
    if args.command == "resume":
        return cmd_suspend(args, suspend=False)
    if args.command == "trigger":
        return cmd_trigger(args)
    if args.command == "delete":
        return cmd_delete(args)
    parser.print_help()
    return 0


if __name__ == "__main__":
    sys.exit(main())
