"""Group/version/kind registry — the analog of the reference's scheme setup
(``/root/reference/api/v1alpha1/groupversion_info.go`` and the scheme
composition at ``cmd/operator/start.go:53-59``).

Because the runtime stores everything as unstructured dicts, the scheme's job
here is (a) GVK parsing/formatting, (b) mapping registered kinds to plural
resource names (for store bookkeeping and CRD-style addressing), and
(c) tracking which kinds are known workload kinds for watch wiring.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional


@dataclass(frozen=True)
class GVK:
    group: str
    version: str
    kind: str

    @property
    def api_version(self) -> str:
        return f"{self.group}/{self.version}" if self.group else self.version

    def __str__(self) -> str:  # e.g. "kubeflow.org/v1, Kind=JAXJob"
        return f"{self.api_version}, Kind={self.kind}"


def parse_api_version(api_version: str) -> tuple[str, str]:
    """Split "group/version" (or bare "v1") into (group, version)."""
    if "/" in api_version:
        group, version = api_version.split("/", 1)
        return group, version
    return "", api_version


def gvk_of(obj: Dict[str, Any]) -> Optional[GVK]:
    """GVK of an unstructured object, or None if apiVersion/kind absent.

    The reference validates this on the workload template at
    ``internal/controller/cron_util.go:40-56`` (empty GVK → error).
    """
    api_version = obj.get("apiVersion") or ""
    kind = obj.get("kind") or ""
    if not api_version or not kind:
        return None
    group, version = parse_api_version(api_version)
    return GVK(group=group, version=version, kind=kind)


def _default_plural(kind: str) -> str:
    lower = kind.lower()
    if lower.endswith("s") or lower.endswith("x") or lower.endswith("ch"):
        return lower + "es"
    if lower.endswith("y"):
        return lower[:-1] + "ies"
    return lower + "s"


class Scheme:
    """Registry of known kinds → plural resource names + workload flags."""

    def __init__(self) -> None:
        self._plurals: Dict[GVK, str] = {}
        self._workload_kinds: set[GVK] = set()

    def register(self, gvk: GVK, plural: Optional[str] = None,
                 workload: bool = False) -> None:
        self._plurals[gvk] = plural or _default_plural(gvk.kind)
        if workload:
            self._workload_kinds.add(gvk)

    def plural(self, gvk: GVK) -> str:
        return self._plurals.get(gvk) or _default_plural(gvk.kind)

    def is_registered(self, gvk: GVK) -> bool:
        return gvk in self._plurals

    def workload_kinds(self) -> list[GVK]:
        return sorted(self._workload_kinds, key=lambda g: (g.group, g.kind))

    def items(self) -> list[tuple[GVK, str]]:
        """All registered (GVK, plural) pairs — REST-path reverse mapping."""
        return sorted(self._plurals.items(), key=lambda kv: str(kv[0]))


KUBEFLOW_GROUP = "kubeflow.org"
KUBEFLOW_V1 = "v1"

GVK_CRON = GVK("apps.kubedl.io", "v1alpha1", "Cron")
GVK_PYTORCHJOB = GVK(KUBEFLOW_GROUP, KUBEFLOW_V1, "PyTorchJob")
GVK_TFJOB = GVK(KUBEFLOW_GROUP, KUBEFLOW_V1, "TFJob")
GVK_MPIJOB = GVK(KUBEFLOW_GROUP, KUBEFLOW_V1, "MPIJob")
GVK_XGBOOSTJOB = GVK(KUBEFLOW_GROUP, KUBEFLOW_V1, "XGBoostJob")
# The new first-class TPU workload kind (Kubeflow JAXJob follows the same
# JobStatus convention; see SURVEY.md §3.3 / §7 step 4).
GVK_JAXJOB = GVK(KUBEFLOW_GROUP, KUBEFLOW_V1, "JAXJob")


def default_scheme() -> Scheme:
    """Scheme with the Cron kind plus the workload-kind surface the reference
    grants RBAC for (``charts/cron-operator/templates/cluster_role.yaml:25-124``
    covers pytorchjobs/tfjobs/mpijobs/xgboostjobs) extended with JAXJob."""
    s = Scheme()
    s.register(GVK_CRON, "crons")
    s.register(GVK_PYTORCHJOB, "pytorchjobs", workload=True)
    s.register(GVK_TFJOB, "tfjobs", workload=True)
    s.register(GVK_MPIJOB, "mpijobs", workload=True)
    s.register(GVK_XGBOOSTJOB, "xgboostjobs", workload=True)
    s.register(GVK_JAXJOB, "jaxjobs", workload=True)
    return s


__all__ = [
    "GVK",
    "parse_api_version",
    "gvk_of",
    "Scheme",
    "default_scheme",
    "GVK_CRON",
    "GVK_PYTORCHJOB",
    "GVK_TFJOB",
    "GVK_MPIJOB",
    "GVK_XGBOOSTJOB",
    "GVK_JAXJOB",
]
