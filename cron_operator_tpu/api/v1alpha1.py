"""``apps.kubedl.io/v1alpha1`` resource types.

Capability parity with the reference CRD types
(``/root/reference/api/v1alpha1/cron_types.go:40-182``), re-designed as
dataclasses that round-trip to k8s-style unstructured dicts (camelCase keys,
RFC3339 timestamps). The workload template stays an opaque dict — the analog
of the reference's ``runtime.RawExtension`` with
``x-kubernetes-preserve-unknown-fields`` (``cron_types.go:110-119``) — so any
GVK can be scheduled without compile-time knowledge of it.

The JobStatus condition convention (``JobConditionType`` strings
Created/Running/Restarting/Succeeded/Suspended/Failed) is deliberately
compatible with Kubeflow's ``training-operator`` so Kubeflow-style workloads
(PyTorchJob/TFJob/MPIJob/JAXJob) interoperate, without depending on it
(reference depends on the real module at ``go.mod:8``; our build re-states the
contract, see SURVEY.md §3.3).
"""

from __future__ import annotations

import copy
import dataclasses
from dataclasses import dataclass, field
from datetime import datetime, timezone
from enum import Enum
from typing import Any, Dict, List, Optional

GROUP = "apps.kubedl.io"
VERSION = "v1alpha1"
API_VERSION = f"{GROUP}/{VERSION}"
KIND_CRON = "Cron"

# Ownership-tracking label (reference: pkg/common/constants.go:20-24).
LABEL_PREFIX_KUBEDL = "kubedl.io"
LABEL_CRON_NAME = "kubedl.io/cron-name"


def rfc3339(dt: datetime) -> str:
    """Serialize a datetime as k8s RFC3339 (second precision, Z suffix)."""
    if dt.tzinfo is None:
        dt = dt.replace(tzinfo=timezone.utc)
    return dt.astimezone(timezone.utc).replace(microsecond=0).isoformat().replace(
        "+00:00", "Z"
    )


def parse_time(value: Optional[str]) -> Optional[datetime]:
    """Parse an RFC3339 timestamp; returns tz-aware UTC datetime."""
    if value is None or value == "":
        return None
    if isinstance(value, datetime):
        return value if value.tzinfo else value.replace(tzinfo=timezone.utc)
    text = value.replace("Z", "+00:00")
    dt = datetime.fromisoformat(text)
    if dt.tzinfo is None:
        dt = dt.replace(tzinfo=timezone.utc)
    return dt.astimezone(timezone.utc)


class ConcurrencyPolicy(str, Enum):
    """How to treat concurrent executions of a workload started by this cron.

    Reference: ``cron_types.go:121-139`` (enum + default Allow).
    """

    ALLOW = "Allow"
    FORBID = "Forbid"
    REPLACE = "Replace"


class JobConditionType(str, Enum):
    """Kubeflow-compatible workload condition types (SURVEY.md §3.3)."""

    CREATED = "Created"
    RUNNING = "Running"
    RESTARTING = "Restarting"
    SUCCEEDED = "Succeeded"
    SUSPENDED = "Suspended"
    FAILED = "Failed"


@dataclass
class JobCondition:
    """One entry of a workload's ``status.conditions``."""

    type: str
    status: str = "True"  # "True" | "False" | "Unknown"
    reason: str = ""
    message: str = ""
    last_update_time: Optional[datetime] = None
    last_transition_time: Optional[datetime] = None

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"type": str(self.type), "status": self.status}
        if self.reason:
            out["reason"] = self.reason
        if self.message:
            out["message"] = self.message
        if self.last_update_time:
            out["lastUpdateTime"] = rfc3339(self.last_update_time)
        if self.last_transition_time:
            out["lastTransitionTime"] = rfc3339(self.last_transition_time)
        return out

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "JobCondition":
        return cls(
            type=d.get("type", ""),
            status=d.get("status", ""),
            reason=d.get("reason", ""),
            message=d.get("message", ""),
            last_update_time=parse_time(d.get("lastUpdateTime")),
            last_transition_time=parse_time(d.get("lastTransitionTime")),
        )


@dataclass
class JobStatus:
    """The cross-workload status contract.

    Any workload kind whose ``status`` follows this convention can be
    scheduled and tracked (reference extracts it from unstructured objects at
    ``internal/controller/cron_util.go:92-114``).
    """

    conditions: List[JobCondition] = field(default_factory=list)
    start_time: Optional[datetime] = None
    completion_time: Optional[datetime] = None
    last_reconcile_time: Optional[datetime] = None

    def _has_true_condition(self, cond_type: JobConditionType) -> bool:
        for c in self.conditions:
            if c.type == cond_type.value and c.status == "True":
                return True
        return False

    def is_succeeded(self) -> bool:
        return self._has_true_condition(JobConditionType.SUCCEEDED)

    def is_failed(self) -> bool:
        return self._has_true_condition(JobConditionType.FAILED)

    def is_finished(self) -> bool:
        return self.is_succeeded() or self.is_failed()

    def last_condition_type(self) -> Optional[str]:
        """Type of the most recent condition (reference ``cron_util.go:85``
        records the *last* list element as the job's final status)."""
        if not self.conditions:
            return None
        return self.conditions[-1].type

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        if self.conditions:
            out["conditions"] = [c.to_dict() for c in self.conditions]
        if self.start_time:
            out["startTime"] = rfc3339(self.start_time)
        if self.completion_time:
            out["completionTime"] = rfc3339(self.completion_time)
        if self.last_reconcile_time:
            out["lastReconcileTime"] = rfc3339(self.last_reconcile_time)
        return out

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "JobStatus":
        d = d or {}
        raw_conds = d.get("conditions") or []
        conds = [JobCondition.from_dict(c) for c in raw_conds if isinstance(c, dict)]
        return cls(
            conditions=conds,
            start_time=parse_time(d.get("startTime")),
            completion_time=parse_time(d.get("completionTime")),
            last_reconcile_time=parse_time(d.get("lastReconcileTime")),
        )


@dataclass
class ObjectMeta:
    """Subset of k8s ObjectMeta the framework uses."""

    name: str = ""
    generate_name: str = ""
    namespace: str = ""
    uid: str = ""
    resource_version: str = ""
    creation_timestamp: Optional[datetime] = None
    deletion_timestamp: Optional[datetime] = None
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    owner_references: List[Dict[str, Any]] = field(default_factory=list)

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        if self.name:
            out["name"] = self.name
        if self.generate_name:
            out["generateName"] = self.generate_name
        if self.namespace:
            out["namespace"] = self.namespace
        if self.uid:
            out["uid"] = self.uid
        if self.resource_version:
            out["resourceVersion"] = self.resource_version
        if self.creation_timestamp:
            out["creationTimestamp"] = rfc3339(self.creation_timestamp)
        if self.deletion_timestamp:
            out["deletionTimestamp"] = rfc3339(self.deletion_timestamp)
        if self.labels:
            out["labels"] = dict(self.labels)
        if self.annotations:
            out["annotations"] = dict(self.annotations)
        if self.owner_references:
            out["ownerReferences"] = copy.deepcopy(self.owner_references)
        return out

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "ObjectMeta":
        d = d or {}
        return cls(
            name=d.get("name", "") or "",
            generate_name=d.get("generateName", "") or "",
            namespace=d.get("namespace", "") or "",
            uid=d.get("uid", "") or "",
            resource_version=str(d.get("resourceVersion", "") or ""),
            creation_timestamp=parse_time(d.get("creationTimestamp")),
            deletion_timestamp=parse_time(d.get("deletionTimestamp")),
            labels=dict(d.get("labels") or {}),
            annotations=dict(d.get("annotations") or {}),
            owner_references=copy.deepcopy(d.get("ownerReferences") or []),
        )


@dataclass
class ObjectReference:
    """corev1.ObjectReference subset used in ``status.active``
    (reference ``cron_types.go:143-146``, built at
    ``cron_controller.go:285-304``)."""

    api_version: str = ""
    kind: str = ""
    namespace: str = ""
    name: str = ""
    uid: str = ""
    resource_version: str = ""

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        if self.api_version:
            out["apiVersion"] = self.api_version
        if self.kind:
            out["kind"] = self.kind
        if self.namespace:
            out["namespace"] = self.namespace
        if self.name:
            out["name"] = self.name
        if self.uid:
            out["uid"] = self.uid
        if self.resource_version:
            out["resourceVersion"] = self.resource_version
        return out

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ObjectReference":
        return cls(
            api_version=d.get("apiVersion", ""),
            kind=d.get("kind", ""),
            namespace=d.get("namespace", ""),
            name=d.get("name", ""),
            uid=d.get("uid", ""),
            resource_version=str(d.get("resourceVersion", "") or ""),
        )


@dataclass
class TypedLocalObjectReference:
    """corev1.TypedLocalObjectReference used in history entries.

    Note: the reference populates ``apiGroup`` with the full ``group/version``
    string, not just the group (``cron_controller.go:330-334``) — replicated
    deliberately for status parity; see SURVEY.md §7 hard-part (5) discussion.
    """

    api_group: Optional[str] = None
    kind: str = ""
    name: str = ""

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"kind": self.kind, "name": self.name}
        if self.api_group is not None:
            out["apiGroup"] = self.api_group
        return out

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "TypedLocalObjectReference":
        return cls(
            api_group=d.get("apiGroup"),
            kind=d.get("kind", ""),
            name=d.get("name", ""),
        )


@dataclass
class CronHistory:
    """One finished (or observed) execution (reference ``cron_types.go:160-182``).

    One entry is one LOGICAL run: when a preempted workload is elastically
    resumed, every resume attempt collapses into the root attempt's entry —
    ``resumes`` counts the attempts after the first (``grows`` the subset
    that were planned fleet-grow reconfigures) and ``lastResumedAt``
    is the newest attempt's creation time. All serialize only when set, so
    non-elastic histories are byte-identical to before (the controller's
    no-op status elision depends on that)."""

    uid: str = ""
    object: TypedLocalObjectReference = field(default_factory=TypedLocalObjectReference)
    status: str = ""  # JobConditionType string
    created: Optional[datetime] = None
    finished: Optional[datetime] = None
    resumes: int = 0
    last_resumed_at: Optional[datetime] = None
    grows: int = 0

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"uid": self.uid, "object": self.object.to_dict()}
        if self.status:
            out["status"] = str(self.status)
        if self.created:
            out["created"] = rfc3339(self.created)
        if self.finished:
            out["finished"] = rfc3339(self.finished)
        if self.resumes:
            out["resumes"] = int(self.resumes)
        if self.grows:
            out["grows"] = int(self.grows)
        if self.last_resumed_at:
            out["lastResumedAt"] = rfc3339(self.last_resumed_at)
        return out

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "CronHistory":
        return cls(
            uid=d.get("uid", ""),
            object=TypedLocalObjectReference.from_dict(d.get("object") or {}),
            status=d.get("status", ""),
            created=parse_time(d.get("created")),
            finished=parse_time(d.get("finished")),
            resumes=int(d.get("resumes") or 0),
            last_resumed_at=parse_time(d.get("lastResumedAt")),
            grows=int(d.get("grows") or 0),
        )


@dataclass
class CronTemplateSpec:
    """The workload template. ``workload`` is an opaque unstructured object
    (apiVersion + kind + metadata + spec of ANY schedulable GVK) — the analog
    of the reference's RawExtension (``cron_types.go:110-119``)."""

    workload: Optional[Dict[str, Any]] = None

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        if self.workload is not None:
            out["workload"] = copy.deepcopy(self.workload)
        return out

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "CronTemplateSpec":
        d = d or {}
        wl = d.get("workload")
        if wl is None:
            return cls(workload=None)
        # A frozen template (store snapshot) is immutable, so it can be
        # SHARED instead of deep-copied — the reconciler hot path parses
        # one Cron per pass and every template consumer already copies
        # before mutating. Mutable input keeps the defensive deepcopy.
        from cron_operator_tpu.runtime.frozen import FrozenDict

        if type(wl) is FrozenDict:
            return cls(workload=wl)
        return cls(workload=copy.deepcopy(wl))


@dataclass
class CronSpec:
    """Desired cron behavior (reference ``cron_types.go:70-108``)."""

    schedule: str = ""
    template: CronTemplateSpec = field(default_factory=CronTemplateSpec)
    concurrency_policy: ConcurrencyPolicy = ConcurrencyPolicy.ALLOW
    suspend: Optional[bool] = None
    deadline: Optional[datetime] = None
    history_limit: Optional[int] = None
    # TPU-native extension: optional IANA timezone for schedule evaluation.
    # The reference can only inherit the container timezone via a hostPath
    # mount of /etc/localtime (chart `useHostTimezone`); a spec field is the
    # declarative version of the same capability.
    timezone: Optional[str] = None
    # CronJob-parity bound on missed-run catch-up: a tick more than this
    # many seconds in the past when the controller gets to it (downtime,
    # crash recovery, long suspension) is skipped instead of fired.
    # None = no deadline, every in-policy missed run fires.
    starting_deadline_seconds: Optional[int] = None

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "schedule": self.schedule,
            "template": self.template.to_dict(),
        }
        if self.concurrency_policy:
            out["concurrencyPolicy"] = str(
                self.concurrency_policy.value
                if isinstance(self.concurrency_policy, ConcurrencyPolicy)
                else self.concurrency_policy
            )
        if self.suspend is not None:
            out["suspend"] = self.suspend
        if self.deadline is not None:
            out["deadline"] = rfc3339(self.deadline)
        if self.history_limit is not None:
            out["historyLimit"] = self.history_limit
        if self.timezone is not None:
            out["timezone"] = self.timezone
        if self.starting_deadline_seconds is not None:
            out["startingDeadlineSeconds"] = self.starting_deadline_seconds
        return out

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "CronSpec":
        d = d or {}
        policy_raw = d.get("concurrencyPolicy") or ConcurrencyPolicy.ALLOW.value
        try:
            policy = ConcurrencyPolicy(policy_raw)
        except ValueError:
            policy = ConcurrencyPolicy.ALLOW
        hl = d.get("historyLimit")
        sds = d.get("startingDeadlineSeconds")
        return cls(
            schedule=d.get("schedule", ""),
            template=CronTemplateSpec.from_dict(d.get("template")),
            concurrency_policy=policy,
            suspend=d.get("suspend"),
            deadline=parse_time(d.get("deadline")),
            history_limit=int(hl) if hl is not None else None,
            timezone=d.get("timezone"),
            starting_deadline_seconds=int(sds) if sds is not None else None,
        )


@dataclass
class CronStatus:
    """Observed state (reference ``cron_types.go:142-157``)."""

    active: List[ObjectReference] = field(default_factory=list)
    history: List[CronHistory] = field(default_factory=list)
    last_schedule_time: Optional[datetime] = None

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        if self.active:
            out["active"] = [a.to_dict() for a in self.active]
        if self.history:
            out["history"] = [h.to_dict() for h in self.history]
        if self.last_schedule_time:
            out["lastScheduleTime"] = rfc3339(self.last_schedule_time)
        return out

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "CronStatus":
        d = d or {}
        return cls(
            active=[ObjectReference.from_dict(a) for a in d.get("active") or []],
            history=[CronHistory.from_dict(h) for h in d.get("history") or []],
            last_schedule_time=parse_time(d.get("lastScheduleTime")),
        )


@dataclass
class Cron:
    """The Cron resource (reference ``cron_types.go:40-51``)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: CronSpec = field(default_factory=CronSpec)
    status: CronStatus = field(default_factory=CronStatus)

    api_version: str = API_VERSION
    kind: str = KIND_CRON

    def deepcopy(self) -> "Cron":
        return copy.deepcopy(self)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "apiVersion": self.api_version,
            "kind": self.kind,
            "metadata": self.metadata.to_dict(),
            "spec": self.spec.to_dict(),
            "status": self.status.to_dict(),
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Cron":
        return cls(
            api_version=d.get("apiVersion", API_VERSION),
            kind=d.get("kind", KIND_CRON),
            metadata=ObjectMeta.from_dict(d.get("metadata")),
            spec=CronSpec.from_dict(d.get("spec")),
            status=CronStatus.from_dict(d.get("status")),
        )


def job_status_from_unstructured(obj: Dict[str, Any]) -> Optional[JobStatus]:
    """Extract the typed JobStatus from an unstructured workload.

    Reference: ``internal/controller/cron_util.go:92-114`` (unstructured →
    ``kubeflowv1.JobStatus`` conversion). Returns None when the workload has
    no status yet; raises ValueError when a status exists but fails
    conversion (the reference's converter error, which the reconciler
    answers by skipping the workload — ``cron_controller.go:139-143``).
    """
    status = obj.get("status")
    if status is None or status == {}:
        return None
    if not isinstance(status, dict):
        raise ValueError(f"workload status is not an object: {type(status).__name__}")
    conds = status.get("conditions")
    if conds is not None and not isinstance(conds, list):
        raise ValueError("workload status.conditions is not a list")
    return JobStatus.from_dict(status)


__all__ = [name for name in dir() if not name.startswith("_")]
