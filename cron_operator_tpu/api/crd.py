"""CustomResourceDefinition generation for the Cron API.

The reference ships a controller-gen-generated CRD manifest
(``/root/reference/charts/cron-operator/crds/apps.kubedl.io_crons.yaml``,
duplicated under ``config/crd/bases/``). Here the CRD is generated from the
API types in code — ``python -m cron_operator_tpu.api.crd`` regenerates
``deploy/crds/apps.kubedl.io_crons.yaml``, and a test pins the two in sync
(the analog of the reference CI's ``make manifests`` drift check,
``.github/workflows/integration.yaml``).

Schema parity notes (reference CRD properties):
- ``spec.schedule`` string (required),
- ``spec.template.workload`` object with
  ``x-kubernetes-preserve-unknown-fields`` (the RawExtension seam),
- ``spec.concurrencyPolicy`` enum Allow/Forbid/Replace,
- ``spec.suspend`` bool, ``spec.deadline`` date-time, ``spec.historyLimit``
  int (+ our ``spec.timezone`` and ``spec.startingDeadlineSeconds``
  extensions — the latter is batch/v1 CronJob parity, bounding how stale a
  missed run may be and still fire during catch-up),
- status subresource with active/history/lastScheduleTime,
- printcolumns Schedule/Suspend/Last Schedule/Age.
"""

from __future__ import annotations

from typing import Any, Dict

from cron_operator_tpu.api.v1alpha1 import GROUP, VERSION

PLURAL = "crons"
SINGULAR = "cron"
KIND = "Cron"
LIST_KIND = "CronList"


def _object_ref_schema() -> Dict[str, Any]:
    return {
        "type": "object",
        "properties": {
            "apiVersion": {"type": "string"},
            "kind": {"type": "string"},
            "name": {"type": "string"},
            "namespace": {"type": "string"},
            "uid": {"type": "string"},
            "resourceVersion": {"type": "string"},
            "fieldPath": {"type": "string"},
        },
        "x-kubernetes-map-type": "atomic",
    }


def _history_schema() -> Dict[str, Any]:
    return {
        "type": "object",
        "required": ["object", "uid"],
        "properties": {
            "uid": {"type": "string"},
            "object": {
                "type": "object",
                "required": ["kind", "name"],
                "properties": {
                    "apiGroup": {"type": "string"},
                    "kind": {"type": "string"},
                    "name": {"type": "string"},
                },
                "x-kubernetes-map-type": "atomic",
            },
            "status": {"type": "string"},
            "created": {"type": "string", "format": "date-time"},
            "finished": {"type": "string", "format": "date-time"},
            "resumes": {
                "type": "integer",
                "description": (
                    "Elastic resume attempts collapsed into this logical "
                    "run (preemption recovery on a smaller mesh)."
                ),
            },
            "lastResumedAt": {"type": "string", "format": "date-time"},
        },
    }


def crd_manifest() -> Dict[str, Any]:
    """The full CRD as an unstructured dict (YAML-serializable)."""
    spec_schema: Dict[str, Any] = {
        "type": "object",
        "required": ["schedule", "template"],
        "properties": {
            "schedule": {
                "type": "string",
                "description": (
                    "Standard 5-field cron schedule (minute hour dom month "
                    "dow), plus @descriptors and '@every <duration>'."
                ),
            },
            "template": {
                "type": "object",
                "properties": {
                    "workload": {
                        "type": "object",
                        "x-kubernetes-preserve-unknown-fields": True,
                        "description": (
                            "Workload object of any schedulable GVK; "
                            "opaque to the operator except apiVersion/kind "
                            "and the JobStatus condition convention."
                        ),
                    }
                },
            },
            "concurrencyPolicy": {
                "type": "string",
                "enum": ["Allow", "Forbid", "Replace"],
                "description": (
                    "How to treat concurrent executions; defaults to Allow."
                ),
            },
            "suspend": {
                "type": "boolean",
                "description": "Suspend subsequent executions.",
            },
            "deadline": {
                "type": "string",
                "format": "date-time",
                "description": "Timestamp after which no workload is started.",
            },
            "historyLimit": {
                "type": "integer",
                "format": "int64",
                "description": (
                    "Number of finished workloads to retain (oldest beyond "
                    "the limit are deleted)."
                ),
            },
            "timezone": {
                "type": "string",
                "description": (
                    "IANA timezone for schedule evaluation (extension; the "
                    "reference can only inherit the container timezone)."
                ),
            },
            "startingDeadlineSeconds": {
                "type": "integer",
                "format": "int64",
                "minimum": 1,
                "description": (
                    "Deadline in seconds for starting a missed run; a tick "
                    "older than this when the controller catches up (after "
                    "downtime or crash recovery) is skipped as a missed "
                    "run instead of fired (batch/v1 CronJob parity)."
                ),
            },
        },
    }
    status_schema: Dict[str, Any] = {
        "type": "object",
        "properties": {
            "active": {"type": "array", "items": _object_ref_schema()},
            "history": {"type": "array", "items": _history_schema()},
            "lastScheduleTime": {"type": "string", "format": "date-time"},
        },
    }
    return {
        "apiVersion": "apiextensions.k8s.io/v1",
        "kind": "CustomResourceDefinition",
        "metadata": {"name": f"{PLURAL}.{GROUP}"},
        "spec": {
            "group": GROUP,
            "names": {
                "kind": KIND,
                "listKind": LIST_KIND,
                "plural": PLURAL,
                "singular": SINGULAR,
            },
            "scope": "Namespaced",
            "versions": [
                {
                    "name": VERSION,
                    "served": True,
                    "storage": True,
                    "subresources": {"status": {}},
                    "additionalPrinterColumns": [
                        {
                            "jsonPath": ".spec.schedule",
                            "name": "Schedule",
                            "type": "string",
                        },
                        {
                            "jsonPath": ".spec.suspend",
                            "name": "Suspend",
                            "type": "boolean",
                        },
                        {
                            "jsonPath": ".status.lastScheduleTime",
                            "name": "Last Schedule",
                            "type": "date",
                        },
                        {
                            "jsonPath": ".metadata.creationTimestamp",
                            "name": "Age",
                            "type": "date",
                        },
                    ],
                    "schema": {
                        "openAPIV3Schema": {
                            "type": "object",
                            "description": (
                                "Cron launches an ML training workload on a "
                                "cron schedule."
                            ),
                            "properties": {
                                "apiVersion": {"type": "string"},
                                "kind": {"type": "string"},
                                "metadata": {"type": "object"},
                                "spec": spec_schema,
                                "status": status_schema,
                            },
                        }
                    },
                }
            ],
        },
    }


def render_yaml() -> str:
    import yaml

    return yaml.safe_dump(crd_manifest(), sort_keys=True, width=80)


def main() -> None:
    """Regenerate every shipped copy of the CRD (``deploy/crds``, the Helm
    chart's ``charts/cron-operator-tpu/crds``, and the kustomize base
    ``config/crd/bases`` — the reference keeps the same duplication between
    config/crd/bases and its chart's crds/). ``make manifests`` analog;
    drift is pinned by tests/test_deploy.py and tests/test_chart.py and
    checked by the CI gate."""
    import pathlib

    root = pathlib.Path(__file__).resolve().parents[2]
    text = render_yaml()
    for rel in ("deploy/crds", "charts/cron-operator-tpu/crds",
                "config/crd/bases"):
        out = root / rel
        out.mkdir(parents=True, exist_ok=True)
        path = out / f"{GROUP}_{PLURAL}.yaml"
        path.write_text(text)
        print(f"wrote {path}")


if __name__ == "__main__":
    main()


__all__ = ["crd_manifest", "render_yaml"]
