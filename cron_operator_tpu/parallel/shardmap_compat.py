"""``shard_map`` across jax versions.

``jax.shard_map`` (with the ``check_vma`` kwarg) is the stable spelling on
newer jax; older runtimes only ship ``jax.experimental.shard_map.shard_map``
and spell the same replication-check toggle ``check_rep``. Import
``shard_map`` from here so every caller (ring/pipeline/attention) runs on
both without touching the deprecated alias when the stable one exists.
"""

from __future__ import annotations

import jax

if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:
    from jax.experimental.shard_map import shard_map as _legacy_shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True, **kw):
        kw.setdefault("check_rep", check_vma)
        return _legacy_shard_map(
            f, mesh, in_specs=in_specs, out_specs=out_specs, **kw
        )

__all__ = ["shard_map"]
