"""Pipeline parallelism — GPipe-style microbatch pipelining over a
``pipe`` mesh axis.

The reference delegates every parallelism strategy to its workload
containers (SURVEY.md §2.3 marks PP "absent — delegated"); here it is a
framework primitive, built the TPU way: no scheduler process and no
point-to-point sends — the whole pipeline is ONE jitted SPMD program under
``shard_map`` where each pipe shard holds one stage's weights and
activations hop stages via ``lax.ppermute`` over the ICI ring. Control flow
is a ``lax.scan`` over ticks (static trip count → XLA unrolls/fuses and the
loop is reverse-mode differentiable, so the backward pipeline falls out of
autodiff instead of a hand-built 1F1B schedule).

Schedule: fill-drain (GPipe). With S stages and M microbatches the loop
runs T = M + S - 1 ticks; at tick t stage s processes microbatch t - s.
Bubble fraction = (S-1)/T — pick M ≥ 4·S to keep it under ~20%.

Usage::

    params = stack_pipeline_stages([p_stage0, p_stage1, ...])  # [S, ...]
    mesh = mesh_for_devices(pipe=4)           # optionally × data
    y = spmd_pipeline(stage_fn, params, x, mesh=mesh, n_microbatches=8)

``stage_fn(stage_params, x) -> y`` must map activations to activations of
the SAME shape/dtype (the inter-stage buffer is one rotating tensor); wrap
unequal-width stages in projections or pad to a common width.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, List

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from cron_operator_tpu.parallel.mesh import BATCH_AXES, PIPE_AXIS
from cron_operator_tpu.parallel.shardmap_compat import shard_map


def stack_pipeline_stages(stage_params: List[Any]) -> Any:
    """Stack per-stage parameter pytrees along a new leading dim [S, ...].

    Every stage must share one tree structure and leaf shapes (same-width
    stages — the GPipe regime). The stacked tree is what
    :func:`spmd_pipeline` consumes, sharded ``P('pipe')`` on dim 0 so each
    pipe shard materializes only its own stage's weights.
    """
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves, axis=0), *stage_params
    )


def pipeline_param_sharding(tree: Any, mesh: Mesh) -> Any:
    """NamedShardings placing stacked stage params: dim 0 on ``pipe``."""
    spec = P(PIPE_AXIS)
    return jax.tree_util.tree_map(
        lambda _: NamedSharding(mesh, spec), tree
    )


def _pipeline_loop(
    stage_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
    n_microbatches: int,
    params_local: Any,
    x_local: jnp.ndarray,
) -> jnp.ndarray:
    """Per-device body (runs inside shard_map over the pipe axis)."""
    n_stages = lax.psum(1, PIPE_AXIS)
    stage_id = lax.axis_index(PIPE_AXIS)
    # This shard's stage weights: [1, ...] slice of the stacked tree.
    p = jax.tree_util.tree_map(lambda a: a[0], params_local)

    batch = x_local.shape[0]
    mb = x_local.reshape(n_microbatches, batch // n_microbatches,
                         *x_local.shape[1:])

    ticks = n_microbatches + n_stages - 1
    # Rotate stage→stage+1; the wrap edge (last→0) carries junk that tick
    # arithmetic never reads (stage 0 only consumes fresh microbatches).
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def tick(carry, t):
        state, outputs = carry
        # Stage 0 injects microbatch t (clamped; beyond M the pipeline is
        # draining and the injected value is never collected).
        inject = lax.dynamic_index_in_dim(
            mb, jnp.clip(t, 0, n_microbatches - 1), axis=0, keepdims=False
        )
        x_in = jnp.where(stage_id == 0, inject, state)
        y = stage_fn(p, x_in)
        # Collect finished microbatch t-(S-1) at the last stage.
        out_idx = t - (n_stages - 1)
        collected = lax.dynamic_update_index_in_dim(
            outputs, y, jnp.clip(out_idx, 0, n_microbatches - 1), axis=0
        )
        outputs = jnp.where(
            (stage_id == n_stages - 1) & (out_idx >= 0), collected, outputs
        )
        state = lax.ppermute(y, PIPE_AXIS, perm)
        return (state, outputs), None

    state0 = jnp.zeros_like(mb[0])
    out0 = jnp.zeros_like(mb)
    (_, outputs), _ = lax.scan(
        tick, (state0, out0), jnp.arange(ticks)
    )
    # Only the last pipe shard holds real outputs (zeros elsewhere); psum
    # over the pipe axis replicates them so the out_spec is honest. One
    # [M, mb, ...] broadcast per step — noise next to the per-tick traffic.
    outputs = lax.psum(outputs, PIPE_AXIS)
    return outputs.reshape(batch, *x_local.shape[1:])


def spmd_pipeline(
    stage_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
    stacked_params: Any,
    x: jnp.ndarray,
    *,
    mesh: Mesh,
    n_microbatches: int,
) -> jnp.ndarray:
    """Run ``x`` through ``n_stages`` pipelined stages (see module doc).

    ``stacked_params``: pytree with leading dim ``n_stages`` on every leaf
    (:func:`stack_pipeline_stages`). ``x``: [batch, ...] with batch
    divisible by ``n_microbatches``; the batch dim is additionally split
    over any data/fsdp axes present in the mesh. Fully differentiable.
    """
    if PIPE_AXIS not in mesh.axis_names:
        raise ValueError(f"mesh has no {PIPE_AXIS!r} axis: {mesh.axis_names}")
    n_stages = mesh.shape[PIPE_AXIS]
    for leaf in jax.tree_util.tree_leaves(stacked_params):
        if leaf.shape[0] != n_stages:
            # shard_map would happily split any divisible leading dim and
            # _pipeline_loop would then use only leaf[0] per shard —
            # silently running a pipeline that ignores stages.
            raise ValueError(
                f"stacked params have {leaf.shape[0]} stage(s) but the "
                f"mesh {PIPE_AXIS!r} axis has {n_stages}"
            )
    batch_axes = tuple(a for a in BATCH_AXES if a in mesh.axis_names)
    # The reshape happens INSIDE shard_map, so it is the per-data-shard
    # batch that must divide into microbatches, not the global one.
    shards = 1
    for a in batch_axes:
        shards *= mesh.shape[a]
    if x.shape[0] % shards:
        raise ValueError(
            f"batch {x.shape[0]} not divisible by the mesh's batch-axis "
            f"product {shards}"
        )
    if (x.shape[0] // shards) % n_microbatches:
        raise ValueError(
            f"per-shard batch {x.shape[0] // shards} (global {x.shape[0]} "
            f"over {shards} data shard(s)) not divisible by "
            f"n_microbatches={n_microbatches}"
        )
    x_spec = P(batch_axes if batch_axes else None)
    fn = shard_map(
        partial(_pipeline_loop, stage_fn, n_microbatches),
        mesh=mesh,
        in_specs=(P(PIPE_AXIS), x_spec),
        out_specs=x_spec,
        check_vma=False,
    )
    return fn(stacked_params, x)


__all__ = [
    "spmd_pipeline",
    "stack_pipeline_stages",
    "pipeline_param_sharding",
]
