"""Ulysses sequence parallelism: all-to-all head-scatter attention.

The second exact long-context strategy next to :mod:`parallel.ring`
(SURVEY.md §2.3 names "ring attention, Ulysses, blockwise" as the
delegated-to-workloads menu; here both exact variants are framework
primitives). Where ring attention keeps heads whole and rotates K/V
blocks around the ICI ring, Ulysses redistributes ONCE each way:

    [b, seq/P, heads, d]  --all_to_all-->  [b, seq, heads/P, d]
        full attention over the complete sequence per local head subset
    [b, seq, heads/P, d]  --all_to_all-->  [b, seq/P, heads, d]

Two collectives total (vs ``P`` ppermute hops), at the cost of needing
``heads % P == 0`` and moving Q as well as K/V. Rule of thumb on TPU:
Ulysses wins when heads are plentiful and sequence blocks are small
enough that the single large all-to-all beats P overlapped hops; ring
wins at extreme sequence lengths (its per-hop traffic is K/V only and
overlaps with compute). Both are exact — same math as full attention —
so they are interchangeable per workload via ``param.attention``.
"""

from __future__ import annotations

from functools import partial

import jax
from jax import lax
from jax.sharding import Mesh

from cron_operator_tpu.parallel.mesh import SEQ_AXIS
from cron_operator_tpu.parallel.ring import (
    _single_device_attention,
    seq_sharded_call,
)


def ulysses_attention_local(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str,
    causal: bool = False,
) -> jax.Array:
    """Per-device body (under ``shard_map``; q/k/v are seq-local blocks).

    ``[b, seq_local, h, d]`` → all_to_all → full-sequence attention on
    ``h/P`` local heads (causal masking needs no block offsets — the
    sequence is complete here) → all_to_all back.
    """
    # Scatter heads (axis 2), gather sequence (axis 1).
    def a2a_in(x):
        return lax.all_to_all(
            x, axis_name, split_axis=2, concat_axis=1, tiled=True
        )

    def a2a_out(x):
        return lax.all_to_all(
            x, axis_name, split_axis=1, concat_axis=2, tiled=True
        )

    qg, kg, vg = a2a_in(q), a2a_in(k), a2a_in(v)  # [b, S, h/P, d]
    out = _single_device_attention(qg, kg, vg, causal=causal)
    return a2a_out(out)  # [b, seq_local, h, d]


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    causal: bool = False,
    seq_axis: str = SEQ_AXIS,
) -> jax.Array:
    """Sequence-parallel attention on global ``[batch, seq, heads,
    head_dim]`` arrays via head-scatter all-to-alls. Call inside ``jit``;
    mirrors :func:`parallel.ring.ring_attention`'s guards and fallbacks.
    """
    par = mesh.shape.get(seq_axis, 1)
    heads = q.shape[2]
    if par > 1 and heads % par != 0:
        # Ulysses-specific constraint (the shared scaffolding handles the
        # seq-divisibility and fallback cases).
        raise ValueError(
            f"ulysses_attention: {heads} heads do not divide the {par}-way "
            f"{seq_axis!r} axis — use ring attention (head-count-free) or "
            "resize the mesh"
        )
    fn = partial(ulysses_attention_local, axis_name=seq_axis, causal=causal)
    return seq_sharded_call(
        fn, q, k, v, mesh, seq_axis=seq_axis, causal=causal,
        op_name="ulysses_attention",
    )


__all__ = ["ulysses_attention", "ulysses_attention_local"]
