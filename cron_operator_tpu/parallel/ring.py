"""Ring attention: exact sequence-parallel attention over an ICI ring.

Long sequences are split over the mesh's ``seq`` axis; each device holds a
local block of Q, K, V. K/V blocks rotate around the ring with
``lax.ppermute`` (nearest-neighbor — rides ICI links, never DCN) while each
device folds every block into its local queries' attention with a
numerically-stable online softmax (flash-attention style running max /
normalizer). After ``ring_size`` steps every Q block has seen every K/V
block exactly once: the result is *bitwise-equivalent math* to full
attention, with O(seq/ring) memory per device and communication overlapped
with compute by XLA.

This is the capability the reference delegates entirely to workload
containers (SURVEY.md §2.3: "sequence/context parallelism — absent,
delegated"); here it is a framework primitive the BERT workload composes
via ``shard_map``.
"""

from __future__ import annotations

from functools import partial
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from cron_operator_tpu.parallel.mesh import BATCH_AXES, SEQ_AXIS
from cron_operator_tpu.parallel.shardmap_compat import shard_map


def ring_attention_local(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str,
    causal: bool = False,
) -> jax.Array:
    """Per-device body (call under ``shard_map`` with ``q/k/v`` local blocks).

    Args:
      q, k, v: ``[batch, seq_local, heads, head_dim]`` — this device's block
        of the sequence.
      axis_name: the mesh axis forming the ring.
      causal: apply a causal mask in *global* sequence coordinates (block
        offsets are derived from ``lax.axis_index``).

    Returns ``[batch, seq_local, heads, head_dim]`` in ``q.dtype``.
    """
    ring = lax.psum(1, axis_name)
    my_block = lax.axis_index(axis_name)
    b, t, h, d = q.shape
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))

    qf = q.astype(jnp.float32) * scale
    q_pos = my_block * t + lax.broadcasted_iota(jnp.int32, (t, 1), 0)

    # One hop around the ring: i → i+1 (nearest neighbor).
    perm = [(i, (i + 1) % ring) for i in range(ring)]

    def step(carry, step_idx):
        o, m, l, k_cur, v_cur = carry
        # The block this device holds after `step_idx` hops originated at
        # device (my_block - step_idx) mod ring.
        src = (my_block - step_idx) % ring

        s = jnp.einsum(
            "bqhd,bkhd->bhqk", qf, k_cur.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )
        if causal:
            k_pos = src * t + lax.broadcasted_iota(jnp.int32, (1, t), 1)
            mask = (k_pos <= q_pos)[None, None, :, :]  # [1,1,q,k]
            s = jnp.where(mask, s, -jnp.inf)

        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        # With full masking a row can be all -inf on this block; keep the
        # running max finite so exp() stays well-defined.
        m_safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
        p = jnp.exp(s - m_safe[..., None])
        p = jnp.where(jnp.isneginf(s), 0.0, p)
        alpha = jnp.exp(jnp.where(jnp.isneginf(m), m_safe, m) - m_safe)
        alpha = jnp.where(jnp.isneginf(m), 0.0, alpha)

        l_new = l * alpha + jnp.sum(p, axis=-1)
        o_new = o * alpha[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p, v_cur.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )

        k_next = lax.ppermute(k_cur, axis_name, perm)
        v_next = lax.ppermute(v_cur, axis_name, perm)
        return (o_new, m_new, l_new, k_next, v_next), None

    o0 = jnp.zeros((b, h, t, d), jnp.float32)
    m0 = jnp.full((b, h, t), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, h, t), jnp.float32)
    (o, _, l, _, _), _ = lax.scan(
        step, (o0, m0, l0, k, v), jnp.arange(ring)
    )
    l = jnp.where(l == 0.0, 1.0, l)  # fully-masked rows → zeros, not NaN
    out = (o / l[..., None]).transpose(0, 2, 1, 3)  # [b,t,h,d]
    return out.astype(q.dtype)


def seq_sharded_call(
    local_fn,
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    seq_axis: str,
    causal: bool,
    op_name: str,
):
    """Shared scaffolding for sequence-parallel attention variants (ring,
    ulysses): divisibility guards, init-trace fallbacks, batch-axis spec
    derivation, and the ``shard_map`` call. One place to fix, not three.

    ``local_fn(q, k, v)`` is the per-device body (already bound to the
    axis name and causal flag). Returns the sharded result, or the plain
    single-device attention on the fallback paths.
    """
    par = mesh.shape.get(seq_axis, 1)
    if par <= 1:
        return _single_device_attention(q, k, v, causal=causal)
    if q.shape[1] % par != 0:
        if q.shape[0] > 1:
            # A real batch with an indivisible sequence would silently
            # materialize full S×S attention — exactly the OOM/perf cliff
            # these ops exist to avoid. Fail loudly; pad upstream.
            raise ValueError(
                f"{op_name}: seq len {q.shape[1]} does not divide the "
                f"{par}-way {seq_axis!r} axis; pad the sequence or resize "
                "the mesh (silent fallback is allowed only for batch-of-1 "
                "init traces)"
            )
        # Batch-of-1 trace during model.init: plain local attention.
        return _single_device_attention(q, k, v, causal=causal)

    batch_axes = tuple(a for a in BATCH_AXES if a in mesh.axis_names)
    batch_size = 1
    for a in batch_axes:
        batch_size *= mesh.shape[a]
    # Keep the batch replicated when it doesn't divide (init-time traces).
    lead = batch_axes if batch_axes and q.shape[0] % batch_size == 0 else None
    spec = P(lead, seq_axis, None, None)

    return shard_map(
        local_fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )(q, k, v)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    causal: bool = False,
    seq_axis: str = SEQ_AXIS,
) -> jax.Array:
    """Sequence-parallel attention on global ``[batch, seq, heads, head_dim]``
    arrays. Call inside ``jit``; ``shard_map`` splits the sequence over
    ``seq_axis`` (and batch over the data axes) and runs the ring body.

    Falls back to a single-block ring (plain attention) when the mesh has no
    ``seq_axis`` — same code path either way.
    """
    fn = partial(ring_attention_local, axis_name=seq_axis, causal=causal)
    return seq_sharded_call(
        fn, q, k, v, mesh, seq_axis=seq_axis, causal=causal,
        op_name="ring_attention",
    )


def _single_device_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, *, causal: bool
) -> jax.Array:
    """Plain attention reference ([b,s,h,d] layout), f32 accumulation."""
    d = q.shape[-1]
    s = jnp.einsum(
        "bqhd,bkhd->bhqk",
        q.astype(jnp.float32), k.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    ) / jnp.sqrt(jnp.asarray(d, jnp.float32))
    if causal:
        t_q, t_k = q.shape[1], k.shape[1]
        mask = lax.broadcasted_iota(jnp.int32, (t_q, t_k), 1) <= (
            lax.broadcasted_iota(jnp.int32, (t_q, t_k), 0)
        )
        s = jnp.where(mask[None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum(
        "bhqk,bkhd->bqhd", p, v.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )
    return out.astype(q.dtype)


__all__ = ["ring_attention", "ring_attention_local", "seq_sharded_call"]
