"""Expert parallelism — Switch/GShard-style mixture-of-experts FFN.

Absent from the reference (SURVEY.md §2.3 "EP: delegated to workload");
here a framework primitive, built for how the MXU and GSPMD want it:

- **Dense dispatch, static shapes.** Routing is expressed as two einsums
  with a [tokens, experts, capacity] one-hot dispatch/combine tensor (the
  GShard formulation) instead of gather/scatter: every shape is static,
  everything lands on the MXU, and nothing blocks XLA fusion.
- **Sharding does the communication.** Expert weights carry
  ``P('expert')`` on their leading dim and the dispatched activations
  ``[E, capacity, d]`` shard the same axis — GSPMD lowers the dispatch/
  combine einsums to all-to-alls over ICI. No hand-written collective.
- **Top-1 (Switch) routing** with a capacity factor: per-expert buffers
  hold ``capacity = ceil(tokens/E · factor)`` tokens; overflow tokens are
  dropped (combine weight 0 — they pass through the residual). The
  standard Switch load-balancing auxiliary loss is returned for the
  trainer to add.

Usage::

    params = init_moe_params(key, d_model=..., d_ff=..., n_experts=8)
    y, aux = moe_ffn(params, x)               # x: [tokens, d_model]
    shardings = moe_param_sharding(params, mesh)   # expert dim on 'expert'
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from cron_operator_tpu.parallel.mesh import EXPERT_AXIS, expert_stacked


def init_moe_params(
    key: jax.Array, *, d_model: int, d_ff: int, n_experts: int
) -> Dict[str, jax.Array]:
    k_r, k_i, k_o = jax.random.split(key, 3)
    return {
        "router": jax.random.normal(k_r, (d_model, n_experts)) * 0.02,
        "wi": jax.random.normal(k_i, (n_experts, d_model, d_ff))
        / np.sqrt(d_model),
        "wo": jax.random.normal(k_o, (n_experts, d_ff, d_model))
        / np.sqrt(d_ff),
    }


def _capacity(tokens: int, n_experts: int, capacity_factor: float) -> int:
    return max(1, int(np.ceil(tokens / n_experts * capacity_factor)))


def router_top1(
    logits: jnp.ndarray, capacity: int
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Switch top-1 router.

    ``logits``: [T, E]. Returns (combine [T, E, C], dispatch [T, E, C]
    one-hot, aux load-balance loss). Position within an expert's buffer is
    the token's rank among tokens routed to that expert (cumsum order);
    rank ≥ capacity ⇒ dropped.
    """
    T, E = logits.shape
    probs = jax.nn.softmax(logits, axis=-1)
    expert_index = jnp.argmax(probs, axis=-1)  # [T]
    expert_mask = jax.nn.one_hot(expert_index, E, dtype=probs.dtype)  # [T,E]

    # Switch aux loss: E · Σ_e (token fraction on e) · (mean router prob e).
    density = expert_mask.mean(axis=0)
    density_proxy = probs.mean(axis=0)
    aux_loss = E * jnp.sum(density * density_proxy)

    # Buffer slot = 0-based rank of this token among its expert's tokens
    # (non-selected entries contribute 0 to the sum, so the one-hot picks
    # out the selected expert's rank).
    position_in_expert = (
        (jnp.cumsum(expert_mask, axis=0) - 1.0) * expert_mask
    ).sum(axis=-1).astype(jnp.int32)  # [T]
    kept = position_in_expert < capacity

    gate = (probs * expert_mask).sum(axis=-1) * kept  # [T]
    slot_one_hot = jax.nn.one_hot(
        jnp.where(kept, position_in_expert, capacity),  # overflow → C (oob)
        capacity, dtype=probs.dtype,
    )  # [T, C]
    dispatch = expert_mask[:, :, None] * slot_one_hot[:, None, :]  # [T,E,C]
    combine = gate[:, None, None] * dispatch
    return combine, dispatch, aux_loss


def moe_ffn(
    params: Dict[str, jnp.ndarray],
    x: jnp.ndarray,
    *,
    capacity_factor: float = 1.25,
    compute_dtype: Any = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Mixture-of-experts FFN over a flat token batch.

    ``x``: [T, d_model] → ([T, d_model], aux_loss). Dropped (overflow)
    tokens produce zeros — compose with a residual connection.

    Routing (logits, softmax, aux loss) always runs f32 — small tensors,
    numerically sensitive. The expert matmuls — the FLOPs — run in
    ``compute_dtype`` (default: ``x.dtype``; pass bf16 for the MXU path).
    """
    T = x.shape[0]
    E = params["wi"].shape[0]
    C = _capacity(T, E, capacity_factor)
    cd = compute_dtype or x.dtype

    logits = x.astype(jnp.float32) @ params["router"].astype(jnp.float32)
    combine, dispatch, aux_loss = router_top1(logits, C)

    # Dispatch: [T,d],[T,E,C] → [E,C,d]; sharded on E ⇒ GSPMD all-to-all.
    expert_in = jnp.einsum("td,tec->ecd", x.astype(cd), dispatch.astype(cd))
    h = jax.nn.gelu(
        jnp.einsum("ecd,edf->ecf", expert_in, params["wi"].astype(cd))
    )
    expert_out = jnp.einsum("ecf,efd->ecd", h, params["wo"].astype(cd))
    # Combine back to token order with the gate applied.
    y = jnp.einsum("ecd,tec->td", expert_out, combine.astype(cd))
    return y, aux_loss


def moe_param_sharding(params: Any, mesh: Mesh) -> Any:
    """NamedShardings for MoE params: expert-stacked weights (the shared
    :func:`parallel.mesh.expert_stacked` rule) shard their leading dim on
    ``expert`` when the mesh has that axis; the router is replicated."""
    expert_size = mesh.shape.get(EXPERT_AXIS, 1)

    def _one(leaf: jnp.ndarray) -> NamedSharding:
        shape = tuple(getattr(leaf, "shape", ()) or ())
        if expert_stacked(shape, expert_size):
            return NamedSharding(mesh, P(EXPERT_AXIS))
        return NamedSharding(mesh, P())

    return jax.tree_util.tree_map(_one, params)


__all__ = [
    "init_moe_params",
    "router_top1",
    "moe_ffn",
    "moe_param_sharding",
]
