"""Device-mesh construction and shape-driven sharding rules.

TPU-first design: parallelism is expressed as a `jax.sharding.Mesh` with
named axes plus `NamedSharding` annotations; XLA GSPMD inserts the
collectives (all-gather/reduce-scatter/psum) that ride the ICI. Nothing here
issues a collective by hand — that is the scaling-book recipe (pick a mesh,
annotate shardings, let XLA do the rest).

Axis convention used across the framework:

- ``data``   — data parallelism (batch axis; gradients all-reduced).
- ``fsdp``   — parameter sharding (ZeRO-3 style; params/opt-state sharded,
  all-gathered per layer by GSPMD). Batches are also split over this axis
  (it is a second data axis from the batch's point of view).
- ``tensor`` — tensor parallelism (feature/head dimension of weight
  matrices).
- ``seq``    — sequence/context parallelism (ring attention over the
  sequence axis; see :mod:`cron_operator_tpu.parallel.ring`).

The reference operator's analog of this file is *nothing* — it delegates all
parallelism to workload containers (SURVEY.md §2.3); here the workloads are
part of the framework.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
FSDP_AXIS = "fsdp"
TENSOR_AXIS = "tensor"
SEQ_AXIS = "seq"
PIPE_AXIS = "pipe"  # pipeline stages (see parallel.pipeline)
EXPERT_AXIS = "expert"  # expert parallelism (see parallel.moe)

# Axes over which a batch's leading dimension is split (both are "data" from
# the input pipeline's perspective).
BATCH_AXES: Tuple[str, ...] = (DATA_AXIS, FSDP_AXIS)


@dataclass(frozen=True)
class MeshPlan:
    """A named-axis factorization of a device count."""

    axis_sizes: Dict[str, int] = field(default_factory=dict)

    @property
    def n_devices(self) -> int:
        n = 1
        for s in self.axis_sizes.values():
            n *= s
        return n

    def axis(self, name: str) -> int:
        return self.axis_sizes.get(name, 1)

    @property
    def axis_names(self) -> Tuple[str, ...]:
        return tuple(self.axis_sizes.keys())

    @property
    def shape(self) -> Tuple[int, ...]:
        return tuple(self.axis_sizes.values())


def plan_for_devices(
    n_devices: int,
    *,
    tensor: int = 1,
    seq: int = 1,
    fsdp: int = 1,
    pipe: int = 1,
    expert: int = 1,
    data: Optional[int] = None,
) -> MeshPlan:
    """Factor ``n_devices`` into the standard axes.

    ``data`` is inferred as the remainder unless given. Raises ValueError if
    the factorization does not multiply out to ``n_devices``. Axis order
    (outer→inner): pipe, data, fsdp, expert, seq, tensor — the chattiest
    collectives (tensor/seq) land innermost on ICI-adjacent chips, the
    per-tick ppermute of the pipeline outermost (it moves one activation
    per microbatch tick, the least bandwidth-hungry traffic).
    """
    model_par = tensor * seq * fsdp * pipe * expert
    if n_devices % model_par != 0:
        raise ValueError(
            f"{n_devices} devices not divisible by "
            f"tensor*seq*fsdp*pipe*expert={model_par}"
        )
    inferred_data = n_devices // model_par
    if data is not None and data != inferred_data:
        raise ValueError(
            f"data={data} inconsistent: {n_devices} devices / {model_par} = "
            f"{inferred_data}"
        )
    sizes: Dict[str, int] = {}
    if pipe > 1:
        sizes[PIPE_AXIS] = pipe
    sizes[DATA_AXIS] = inferred_data
    if fsdp > 1:
        sizes[FSDP_AXIS] = fsdp
    if expert > 1:
        sizes[EXPERT_AXIS] = expert
    if seq > 1:
        sizes[SEQ_AXIS] = seq
    if tensor > 1:
        sizes[TENSOR_AXIS] = tensor
    return MeshPlan(sizes)


def replan(
    old_plan: MeshPlan,
    surviving_devices: Any,
    *,
    allow_grow: bool = False,
    original_plan: Optional[MeshPlan] = None,
) -> MeshPlan:
    """Recompute a plan after the device pool changed size.

    ``surviving_devices`` is a device count or a sequence of devices.

    **Shrink** (the preemption path): the ``data`` axis absorbs the
    shrink first — data parallelism is the one axis a training job can
    lose without changing what any single device computes (the global
    batch shrinks; the Tenplex reconfiguration-plan restriction we
    implement). Model axes (pipe/fsdp/expert/seq/tensor) keep their
    sizes whenever the surviving count stays divisible by their product;
    otherwise they are reduced largest-first by prime factors until a
    valid factorization exists (VirtualFlow's virtual-node remap,
    collapsed onto our named axes).

    **Grow** (the fleet scale-up path, ``allow_grow=True``): the exact
    mirror. The ``data`` axis widens first; when ``original_plan`` is
    given (the mesh the job was first launched on), model axes that a
    previous shrink reduced are restored toward their original sizes —
    largest deficit first, one prime factor at a time — whenever the
    target count stays divisible. Without ``allow_grow`` a larger pool
    raises, so every existing shrink-only caller keeps its guarantee:
    growing is a scale-up decision the caller must make explicitly
    (:func:`regrow` is the convenience wrapper).

    Raises ValueError when nothing survives, when the pool grew without
    ``allow_grow``, or when a grow target is not divisible by the model
    parallelism that survives restoration.
    """
    try:
        surviving = int(surviving_devices)
    except (TypeError, ValueError):
        surviving = len(surviving_devices)
    if surviving <= 0:
        raise ValueError("no surviving devices to replan onto")
    if surviving > old_plan.n_devices and not allow_grow:
        raise ValueError(
            f"replan is shrink-only: {surviving} surviving > "
            f"{old_plan.n_devices} planned"
        )
    if surviving == old_plan.n_devices:
        return old_plan
    model = {
        name: old_plan.axis(name)
        for name in (PIPE_AXIS, EXPERT_AXIS, SEQ_AXIS, FSDP_AXIS, TENSOR_AXIS)
    }

    def _model_par() -> int:
        n = 1
        for s in model.values():
            n *= s
        return n

    if surviving > old_plan.n_devices:
        # Grow: restore previously-shrunk model axes toward the original
        # plan while divisibility holds; the data axis absorbs the rest.
        if original_plan is not None:
            while True:
                deficits = {
                    a: original_plan.axis(a) // model[a]
                    for a in model
                    if original_plan.axis(a) > model[a]
                    and original_plan.axis(a) % model[a] == 0
                }
                restorable = None
                for a in sorted(deficits, key=lambda a: -deficits[a]):
                    f = deficits[a]
                    p = next(q for q in range(2, f + 1) if f % q == 0)
                    if surviving % (_model_par() * p) == 0:
                        restorable = (a, p)
                        break
                if restorable is None:
                    break
                model[restorable[0]] *= restorable[1]
        if surviving % _model_par():
            raise ValueError(
                f"cannot grow onto {surviving} devices: not divisible by "
                f"model parallelism {_model_par()}"
            )
    else:
        while surviving % _model_par():
            name = max((a for a in model if model[a] > 1),
                       key=lambda a: model[a])
            size = model[name]
            factor = next(p for p in range(2, size + 1) if size % p == 0)
            model[name] //= factor
    return plan_for_devices(
        surviving,
        tensor=model[TENSOR_AXIS],
        seq=model[SEQ_AXIS],
        fsdp=model[FSDP_AXIS],
        pipe=model[PIPE_AXIS],
        expert=model[EXPERT_AXIS],
    )


def regrow(
    old_plan: MeshPlan,
    devices: Any,
    original_plan: Optional[MeshPlan] = None,
) -> MeshPlan:
    """Explicit grow: widen ``old_plan`` onto a larger device pool
    (sibling of the shrink default — see :func:`replan` with
    ``allow_grow=True``). ``original_plan``, when given, lets a
    previously-shrunk job recover its original model-axis sizes."""
    return replan(
        old_plan, devices, allow_grow=True, original_plan=original_plan
    )


def make_mesh(plan: MeshPlan, devices: Optional[Sequence[Any]] = None) -> Mesh:
    """Build a Mesh from a plan over the given (or all local) devices.

    Device order follows ``jax.devices()`` reshaped row-major; on real TPU
    slices that order is topology-contiguous, so the innermost mesh axis
    lands on ICI-adjacent chips (put ``tensor``/``seq`` innermost — they
    carry the chattiest collectives).
    """
    devices = list(devices if devices is not None else jax.devices())
    if plan.n_devices != len(devices):
        raise ValueError(
            f"mesh plan needs {plan.n_devices} devices, got {len(devices)}"
        )
    arr = np.array(devices, dtype=object).reshape(plan.shape)
    return Mesh(arr, plan.axis_names)


def mesh_for_devices(
    devices: Optional[Sequence[Any]] = None,
    *,
    tensor: int = 1,
    seq: int = 1,
    fsdp: int = 1,
    pipe: int = 1,
    expert: int = 1,
) -> Mesh:
    """One-call helper: factor the local devices and build the mesh."""
    devices = list(devices if devices is not None else jax.devices())
    plan = plan_for_devices(len(devices), tensor=tensor, seq=seq, fsdp=fsdp,
                            pipe=pipe, expert=expert)
    return make_mesh(plan, devices)


def mesh_for_slice(
    slice_spec: Any,
    *,
    tensor: int = 1,
    seq: int = 1,
    fsdp: int = 1,
    pipe: int = 1,
    expert: int = 1,
    devices: Optional[Sequence[Any]] = None,
) -> Mesh:
    """Mesh over the chips of a :class:`backends.tpu.SliceSpec`.

    The operator side resolves a Cron's TPU annotation into a SliceSpec
    (hosts × chips/host); the workload side turns the same spec into the
    mesh its train step is jitted over — one source of truth for topology.
    """
    devices = list(devices if devices is not None else jax.devices())
    if len(devices) != slice_spec.chips:
        raise ValueError(
            f"slice {slice_spec.topology!r} has {slice_spec.chips} chips but "
            f"{len(devices)} devices are visible"
        )
    plan = plan_for_devices(
        slice_spec.chips, tensor=tensor, seq=seq, fsdp=fsdp,
        pipe=pipe, expert=expert,
    )
    return make_mesh(plan, devices)


def group_devices_by_slice(
    devices: Sequence[Any], n_slices: int
) -> "list[list[Any]]":
    """Partition devices into their TPU slices.

    Real multi-slice TPU devices carry ``slice_index`` (the PJRT attribute
    GKE multislice exposes); grouped by it when present. CPU devices (and
    single-slice tests) don't — fallback is contiguous equal chunks of the
    ``jax.devices()`` order, which is slice-contiguous on real hardware
    anyway.
    """
    if len(devices) % n_slices:
        raise ValueError(
            f"{len(devices)} devices not divisible into {n_slices} slices"
        )
    indices = [getattr(d, "slice_index", None) for d in devices]
    if all(i is not None for i in indices):
        groups: Dict[Any, list] = {}
        for d in devices:
            groups.setdefault(d.slice_index, []).append(d)
        if len(groups) != n_slices:
            raise ValueError(
                f"devices span {len(groups)} slice(s), expected {n_slices}"
            )
        sizes = {len(g) for g in groups.values()}
        if len(sizes) != 1:
            raise ValueError(f"uneven slice sizes: {sorted(sizes)}")
        return [groups[k] for k in sorted(groups)]
    per = len(devices) // n_slices
    return [list(devices[i * per:(i + 1) * per]) for i in range(n_slices)]


def hybrid_mesh_for_slices(
    n_slices: int,
    *,
    tensor: int = 1,
    seq: int = 1,
    fsdp: int = 1,
    pipe: int = 1,
    expert: int = 1,
    devices: Optional[Sequence[Any]] = None,
) -> Mesh:
    """Multi-slice (DCN × ICI) mesh — the scaling-book multislice recipe.

    The ``data`` axis is OUTERMOST and slice-major: consecutive data
    indices stay within one slice and the axis crosses a slice boundary
    every ``per_slice_data`` entries, so the only collectives that ride
    the (slow) DCN are the data-parallel gradient reductions; every model
    axis (pipe/fsdp/expert/seq/tensor) lives inside one slice's ICI.
    Note this differs from :func:`plan_for_devices`' order (which puts
    ``pipe`` outermost for the single-slice case) — across slices,
    pipelining the per-tick ppermute over DCN would serialize on the slow
    link, so the hybrid mesh confines it to ICI.
    """
    devices = list(devices if devices is not None else jax.devices())
    groups = group_devices_by_slice(devices, n_slices)
    per_slice = len(groups[0])
    model_par = tensor * seq * fsdp * pipe * expert
    if per_slice % model_par:
        raise ValueError(
            f"per-slice device count {per_slice} not divisible by "
            f"tensor*seq*fsdp*pipe*expert={model_par}"
        )
    per_slice_data = per_slice // model_par

    sizes: Dict[str, int] = {}
    if pipe > 1:
        sizes[PIPE_AXIS] = pipe
    if fsdp > 1:
        sizes[FSDP_AXIS] = fsdp
    if expert > 1:
        sizes[EXPERT_AXIS] = expert
    if seq > 1:
        sizes[SEQ_AXIS] = seq
    if tensor > 1:
        sizes[TENSOR_AXIS] = tensor
    inner_shape = (per_slice_data, *sizes.values())
    arrs = [
        np.array(g, dtype=object).reshape(inner_shape) for g in groups
    ]
    full = np.concatenate(arrs, axis=0)  # data axis: slice-major
    return Mesh(full, (DATA_AXIS, *sizes.keys()))


# ---- sharding rules --------------------------------------------------------


def batch_pspec(mesh: Mesh, *, seq_dim: Optional[int] = None) -> P:
    """PartitionSpec for a batch: leading dim over data axes, optionally a
    sequence dim over the seq axis."""
    batch_axes = tuple(a for a in BATCH_AXES if a in mesh.axis_names)
    lead = batch_axes if batch_axes else None
    if seq_dim is None:
        return P(lead)
    if seq_dim <= 0:
        raise ValueError("seq_dim must be a positive dim index")
    entries: list = [lead] + [None] * seq_dim
    if SEQ_AXIS in mesh.axis_names:
        entries[seq_dim] = SEQ_AXIS
    return P(*entries)


def pspec_for_shape(shape: Tuple[int, ...], mesh: Mesh) -> P:
    """Shape-driven parameter sharding rule.

    - rank 0/1 leaves (biases, scales, scalars): replicated;
    - if the mesh has a ``tensor`` axis and the last dim divides by it:
      shard last dim on ``tensor`` (megatron-style column split; GSPMD
      derives the matching row split and psum for the next matmul);
    - if the mesh has an ``fsdp`` axis: shard the largest remaining dim
      divisible by it (ZeRO-3 parameter sharding).

    Deliberately metadata-free: works for any pytree of arrays (params AND
    optimizer state, which mirrors param shapes), so a model needs no
    per-layer annotations to scale. Models can still override hot tensors
    with explicit ``with_sharding_constraint``.
    """
    spec: list = [None] * len(shape)
    if len(shape) >= 2:
        t = mesh.shape.get(TENSOR_AXIS, 1)
        if t > 1 and shape[-1] % t == 0:
            spec[-1] = TENSOR_AXIS
        f = mesh.shape.get(FSDP_AXIS, 1)
        if f > 1:
            for i in sorted(range(len(shape)), key=lambda i: -shape[i]):
                if spec[i] is None and shape[i] % f == 0:
                    spec[i] = FSDP_AXIS
                    break
    return P(*spec)


def expert_stacked(shape: Tuple[int, ...], expert_size: int) -> bool:
    """Shape test for expert-stacked ``[E, ...]`` weights — the ONE rule
    shared by :func:`sharding_for_tree` (which additionally requires the
    ``"moe"`` tree-key convention) and ``moe.moe_param_sharding`` (which
    owns its whole param dict, so the shape alone suffices there)."""
    return (
        expert_size > 1
        and len(shape) >= 3
        and shape[0] % expert_size == 0
    )


def sharding_for_tree(tree: Any, mesh: Mesh) -> Any:
    """Map a pytree of arrays/ShapeDtypeStructs to NamedShardings via
    :func:`pspec_for_shape`. Use with ``jax.jit(in_shardings=...)`` or
    ``jax.device_put``.

    One path-aware rule on top of the shape rules: when the mesh has an
    ``expert`` axis, leaves living under a tree key named ``"moe"``
    (models.gpt's MoE block; optimizer state mirrors the same paths) with
    rank ≥ 3 and a leading dim divisible by the axis are expert-stacked
    ``[E, ...]`` weights — sharded ``P('expert')`` so GSPMD lowers the MoE
    dispatch/combine einsums to all-to-alls. A pure shape rule can't see
    this (any rank-3+ tensor might coincidentally divide), hence the
    naming convention.
    """
    expert = mesh.shape.get(EXPERT_AXIS, 1)

    def _path_one(path, leaf: Any) -> NamedSharding:
        shape = tuple(getattr(leaf, "shape", ()) or ())
        if expert_stacked(shape, expert) and any(
            getattr(k, "key", None) == "moe" for k in path
        ):
            return NamedSharding(mesh, P(EXPERT_AXIS))
        return NamedSharding(mesh, pspec_for_shape(shape, mesh))

    return jax.tree_util.tree_map_with_path(_path_one, tree)


__all__ = [
    "DATA_AXIS",
    "FSDP_AXIS",
    "TENSOR_AXIS",
    "SEQ_AXIS",
    "PIPE_AXIS",
    "EXPERT_AXIS",
    "BATCH_AXES",
    "MeshPlan",
    "plan_for_devices",
    "replan",
    "regrow",
    "make_mesh",
    "mesh_for_devices",
    "mesh_for_slice",
    "group_devices_by_slice",
    "hybrid_mesh_for_slices",
    "batch_pspec",
    "pspec_for_shape",
    "expert_stacked",
    "sharding_for_tree",
]
