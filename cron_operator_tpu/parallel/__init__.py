"""SPMD parallelism layer: device meshes, sharding rules, and sequence
parallelism (ring attention) for the JAX workloads this framework schedules.

The reference operator contains no parallelism code of its own (SURVEY.md
§2.3) — DP/TP/SP live inside the workload containers it launches. In the
TPU-native build those workloads are first-class framework citizens, so the
parallel layer lives here: mesh construction from TPU slice topologies,
shape-driven parameter sharding (FSDP/TP), and ring attention over an ICI
ring for long-context sequence parallelism.
"""

from cron_operator_tpu.parallel.mesh import (
    MeshPlan,
    batch_pspec,
    hybrid_mesh_for_slices,
    make_mesh,
    mesh_for_devices,
    mesh_for_slice,
    plan_for_devices,
    pspec_for_shape,
    sharding_for_tree,
)
from cron_operator_tpu.parallel.overlap import (
    DoubleBuffer,
    chain_steps,
    chunk_schedule,
    stacked_shardings,
)
from cron_operator_tpu.parallel.moe import (
    init_moe_params,
    moe_ffn,
    moe_param_sharding,
)
from cron_operator_tpu.parallel.pipeline import (
    spmd_pipeline,
    stack_pipeline_stages,
)
from cron_operator_tpu.parallel.ring import ring_attention, ring_attention_local
from cron_operator_tpu.parallel.ulysses import (
    ulysses_attention,
    ulysses_attention_local,
)

__all__ = [
    "MeshPlan",
    "batch_pspec",
    "make_mesh",
    "mesh_for_devices",
    "mesh_for_slice",
    "hybrid_mesh_for_slices",
    "plan_for_devices",
    "pspec_for_shape",
    "sharding_for_tree",
    "ring_attention",
    "ring_attention_local",
    "ulysses_attention",
    "ulysses_attention_local",
    "spmd_pipeline",
    "stack_pipeline_stages",
    "init_moe_params",
    "moe_ffn",
    "moe_param_sharding",
    "DoubleBuffer",
    "chain_steps",
    "chunk_schedule",
    "stacked_shardings",
]
