"""Overlap primitives: background staging and scan-chained dispatch.

The step-speed finding (PERF.md finding 3) is that steady-state training
loses its margin to per-step HOST work — batch generation, ``device_put``,
python dispatch — not to device compute. The collective-heavy attention
paths already overlap internally (ring rotates K/V behind the current
block's compute, ulysses pipelines its all-to-alls); this module exposes
the same discipline to the Trainer's outer loop:

- :class:`DoubleBuffer` — a bounded background pipeline that runs a
  ``stage`` callable (typically host batch build + sharded ``device_put``)
  over an iterator from a producer thread, so item N+1 is staged while
  item N computes. ``workloads.data.Prefetcher`` (single batches) and
  ``workloads.data.ChunkStager`` (stacked scan chunks) are thin facades
  over it.
- :func:`chain_steps` — the scan-chained K-steps-per-dispatch program
  builder: one jitted ``lax.scan`` of the step body, state donated
  through, so K optimizer steps cost one python dispatch + one
  host↔device round trip. Fused mode scans with no xs (the body derives
  its batch from ``state.step``); external mode scans over a stacked
  batch (leading axis = step index).
- :func:`stacked_shardings` — the placement rule for those stacked
  batches: the per-step sharding with the scan axis replicated
  (``P(None, *spec)``), so every device holds its shard of each step's
  slice and the scan body consumes bytes that are already laid out
  exactly as ``steps_per_call=1`` would have placed them.

No jax import at module scope on the DoubleBuffer path: the staging
machinery is plain threads + queues and stays importable from host-only
contexts.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Iterable, Optional


class DoubleBuffer:
    """Background staging: overlap ``stage(item)`` with the consumer.

    The producer thread pulls from ``items``, applies ``stage`` (device
    placement happens on that thread), and parks the result in a bounded
    queue (``depth`` caps memory spent on staged-ahead work). The consumer
    iterates staged results; with ``depth >= 2`` the next item is already
    staged while the current one is being consumed — classic
    double-buffering.

    Must be :meth:`close`'d (the Trainer does, in ``run``'s finally) — the
    producer thread of an infinite generator would otherwise park forever
    per job in a long-lived executor process. A ``stage``/generator
    exception is re-raised on the consumer at the point of ``next()``;
    after exhaustion or :meth:`close` the iterator keeps raising
    ``StopIteration`` (never parks on a dead producer).
    """

    _DONE = object()

    def __init__(
        self,
        items: Iterable[Any],
        stage: Callable[[Any], Any],
        depth: int = 2,
        name: str = "stage-ahead",
    ):
        import queue as _queue
        import threading as _threading

        self._q: "_queue.Queue" = _queue.Queue(maxsize=max(1, depth))
        self._stop = _threading.Event()
        self._exc: Optional[Exception] = None
        self._finished = False  # terminal: next() keeps raising StopIteration
        self._items = items
        self._stage = stage
        self._thread = _threading.Thread(
            target=self._fill, name=name, daemon=True
        )
        self._thread.start()

    def _fill(self) -> None:
        import queue as _queue

        def offer(item) -> bool:
            while not self._stop.is_set():
                try:
                    self._q.put(item, timeout=0.1)
                    return True
                except _queue.Full:
                    continue
            return False

        try:
            for item in self._items:
                if not offer(self._stage(item)):
                    return
                if self._stop.is_set():
                    return
        except Exception as exc:  # noqa: BLE001 — re-raised on the consumer
            self._exc = exc
        offer(self._DONE)

    def __iter__(self):
        return self

    def __next__(self):
        if self._finished:
            # Iterator protocol: repeated next() after exhaustion (or
            # after close()) must keep raising, never park on q.get()
            # waiting for a producer that already exited.
            raise StopIteration
        item = self._q.get()
        if item is self._DONE:
            self._finished = True
            if self._exc is not None:
                exc, self._exc = self._exc, None
                raise exc
            raise StopIteration
        return item

    def close(self) -> None:
        import logging as _logging
        import queue as _queue

        self._stop.set()
        self._finished = True
        # Unblock a producer parked on a full queue. Only Empty ends the
        # drain — anything else is a real bug and must surface, not be
        # swallowed into a silent thread leak.
        try:
            while True:
                self._q.get_nowait()
        except _queue.Empty:
            pass
        self._thread.join(timeout=5.0)
        if self._thread.is_alive():
            _logging.getLogger("parallel.overlap").warning(
                "stage-ahead producer thread still alive 5s after close(); "
                "a stage()/generator call is blocked — leaking the thread"
            )


def chain_steps(
    step_fn: Callable[[Any, Dict[str, Any]], Any],
    *,
    length: Optional[int] = None,
    over_batch: bool = False,
    jit_kwargs: Optional[dict] = None,
):
    """Build the jitted K-steps-per-dispatch program for ``step_fn``
    (``(state, batch) -> (state, loss)``).

    ``over_batch=False`` (fused data): scan ``length`` times with no xs —
    the body regenerates its batch from the live ``state.step``, so the
    data stream is identical to ``steps_per_call=1``. ``over_batch=True``
    (external data): scan over ``batch`` whose leaves carry a leading
    step axis (see :func:`stacked_shardings`) — step i consumes slice i,
    exactly the batch it would have received as its own dispatch.

    Returns ``(state, last_loss)`` — the chunk's final step's loss, the
    one a synced dispatch fetches. ``jit_kwargs`` carries the Trainer's
    in/out shardings and ``donate_argnums=(0,)`` so the state buffers are
    donated through the chain (no K-step live-copy spike).
    """
    import jax
    from jax import lax

    def chained(state, batch):
        if over_batch:
            def body(s, b):
                return step_fn(s, b)

            state, losses = lax.scan(body, state, batch)
        else:
            def body(s, _):
                return step_fn(s, batch)

            state, losses = lax.scan(body, state, None, length=length)
        return state, losses[-1]

    return jax.jit(chained, **(jit_kwargs or {}))


def stacked_shardings(batch_shardings: Dict[str, Any]) -> Dict[str, Any]:
    """Shardings for a scan-stacked batch: each per-step sharding with the
    new leading step axis replicated (``P(None, *spec)``) — the scan body
    then consumes per-step slices laid out exactly like single-step
    batches, so GSPMD inserts no relayout inside the chain."""
    from jax.sharding import NamedSharding, PartitionSpec

    out: Dict[str, Any] = {}
    for k, sh in batch_shardings.items():
        out[k] = NamedSharding(sh.mesh, PartitionSpec(None, *sh.spec))
    return out


def chunk_schedule(
    start: int, target: int, steps_per_call: int, boundary: int = 0
) -> list:
    """Chunk sizes for a scan-chained run from ``start`` to ``target``
    total steps: each dispatch carries up to ``steps_per_call`` steps but
    never crosses a ``boundary`` multiple (checkpoint ``save_every`` — a
    save must land ON its step, not up to K-1 late) and never overshoots
    ``target``. ``boundary=0`` disables snapping."""
    out = []
    done = max(0, int(start))
    target = int(target)
    spc = max(1, int(steps_per_call))
    while done < target:
        chunk = min(spc, target - done)
        if boundary and boundary > 0:
            to_boundary = boundary - (done % boundary)
            chunk = min(chunk, to_boundary)
        out.append(chunk)
        done += chunk
    return out


__all__ = [
    "DoubleBuffer",
    "chain_steps",
    "stacked_shardings",
    "chunk_schedule",
]
