"""Autoregressive generation — the serving path for the GPT family.

One compiled program per (shapes, steps): the prompt is consumed by a
single batched causal pass that also populates the KV caches (prefill),
then a ``lax.scan`` over a single-token decode step samples the
continuation — the whole generation is one XLA computation with static
shapes (the TPU-idiomatic decode: no Python loop per token, no
recompilation per step, KV cache carried as scan state).

The KV cache is the model's flax ``"cache"`` collection
(:class:`models.gpt.GPT` with ``decode=True``): ``[b, max_len, h, d]``
per layer plus write indices, created on the first mutable apply and
threaded through the scans as a plain pytree.

Decode is bandwidth-bound (one [1, max_len] attention row per head per
step); batch is the throughput lever, exactly as on any accelerator.

MoE caveat: cached decode raises expert capacity to no-drop (a single
token must never be dropped by its own router), while prefill/training
keep the configured ``moe_capacity_factor``. The two paths are therefore
only bitwise-identical when ``moe_capacity_factor >= num_experts``; with
a drop-capable capacity a token dropped during prefill but routed during
decode (or vice versa) can legitimately diverge. Operators comparing
decode output against a full forward should pin capacity accordingly.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from dataclasses import replace
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from cron_operator_tpu.models.gpt import GPT, GPTConfig

# (cfg, max_new, greedy) → jitted fn. LRU-bounded: a long-lived serving
# operator fed varying max_new/configs must not accumulate compiled
# executables forever (ADVICE r4). Evicting a jitted fn drops its
# compiled programs with it; a re-encountered key recompiles (or hits the
# persistent XLA cache). Each entry can still hold multiple shape
# specializations — that is jit's own per-fn cache, bounded by the entry
# count here.
_COMPILED_CAP = 8
_COMPILED: "OrderedDict" = OrderedDict()
# The local backend runs workloads on threads; get/insert/evict/
# move_to_end must be atomic or a concurrent eviction between a hit and
# its move_to_end raises KeyError.
_COMPILED_LOCK = threading.Lock()


def generate(
    config: GPTConfig,
    params,
    prompt_ids: jax.Array,
    max_new_tokens: int,
    *,
    temperature: float = 0.0,
    rng: Optional[jax.Array] = None,
) -> jax.Array:
    """Greedy (``temperature=0``) or sampled continuation of each prompt.

    ``prompt_ids`` is ``[batch, prompt_len]`` int32; returns
    ``[batch, prompt_len + max_new_tokens]``. Compiled once per
    (config, shapes, steps) and cached.
    """
    b, p = prompt_ids.shape
    if p < 1:
        raise ValueError("empty prompt")
    if max_new_tokens < 1:
        raise ValueError("max_new_tokens must be >= 1")
    if p + max_new_tokens > config.max_len:
        raise ValueError(
            f"prompt {p} + {max_new_tokens} new tokens exceeds "
            f"max_len {config.max_len}"
        )
    if temperature < 0:
        raise ValueError("temperature must be >= 0")
    greedy = temperature == 0.0
    if not greedy and rng is None:
        raise ValueError("sampling (temperature > 0) needs an rng key")
    if rng is None:
        rng = jax.random.PRNGKey(0)  # unused in greedy mode

    # jit specializes per input shape on its own; keying the wrapper by
    # shapes too would just grow an unbounded duplicate cache.
    key = (config, max_new_tokens, greedy)
    with _COMPILED_LOCK:
        fn = _COMPILED.get(key)
        if fn is not None:
            _COMPILED.move_to_end(key)
    if fn is None:
        # Build outside the lock (tracing is slow); worst case two
        # threads build the same fn and one insert wins — harmless.
        fn = _build(config, max_new_tokens, greedy)
        with _COMPILED_LOCK:
            fn = _COMPILED.setdefault(key, fn)
            _COMPILED.move_to_end(key)
            while len(_COMPILED) > _COMPILED_CAP:
                _COMPILED.popitem(last=False)
    return fn(params, prompt_ids, jnp.float32(max(temperature, 1e-6)), rng)


def _build(config: GPTConfig, max_new: int, greedy: bool):
    # Serving always wants logits (return_hidden is a training-loss
    # fusion); MoE/aux outputs are ignored at decode time.
    cfg = replace(config, return_hidden=False)
    prefill_model = GPT(cfg, prefill=True)
    decode_model = GPT(cfg, decode=True)

    def step(params, cache, token):
        """One decode step: [b, 1] token → ([b, vocab] logits, cache')."""
        (logits, _), mut = decode_model.apply(
            {"params": params, "cache": cache}, token, mutable=["cache"]
        )
        return logits[:, -1], mut["cache"]

    def run(params, prompt, temperature, rng):
        # Prefill: ONE batched causal pass consumes the whole prompt,
        # creating and filling every layer's KV cache (a token-at-a-time
        # prefill would stream the full parameter set p times).
        (logits, _), mut = prefill_model.apply(
            {"params": params}, prompt, mutable=["cache"]
        )
        cache = mut["cache"]

        def sample(logits, key):
            if greedy:
                return jnp.argmax(logits, axis=-1)
            return jax.random.categorical(key, logits / temperature)

        keys = jax.random.split(rng, max_new)
        first = sample(logits[:, -1], keys[0])

        # Step-then-sample: each iteration feeds the previous token and
        # samples from the fresh logits — exactly max_new − 1 decode
        # forwards after the prefill (the final sampled token never needs
        # a forward of its own).
        def gen_body(carry, key):
            prev, cache = carry
            logits, cache = step(params, cache, prev[:, None])
            nxt = sample(logits, key)
            return (nxt, cache), nxt

        _, rest = lax.scan(gen_body, (first, cache), keys[1:])
        toks = jnp.concatenate([first[None], rest], axis=0)  # [max_new, b]
        return jnp.concatenate([prompt, toks.T.astype(prompt.dtype)], axis=1)

    return jax.jit(run)


__all__ = ["generate"]
