"""No-JAX smoke entrypoint for control-plane end-to-end probes.

Referenced as ``cron_operator_tpu.workloads.smoke:run`` (the
``module:function`` form of the entrypoint annotation), so resolving it
never imports :mod:`cron_operator_tpu.workloads.entrypoints` — and with
it jax/flax — into a runner subprocess whose only job is to prove the
control-plane path: the distributed obs_report leg runs one cron tick
through router → shard → executor → THIS process and asserts the trace
spans all of them.

The progress contract matches the real trainers: ``started_at`` /
``first_step_at`` / ``first_step_latency_s`` feed the executor's
tick→first-step histogram and its ``first_step`` span, and ``step``
beats the watchdog path exactly like a training loop would.
"""

from __future__ import annotations

import time

from cron_operator_tpu.backends.registry import JobContext


def run(ctx: JobContext) -> None:
    """Complete ``steps`` (default 3) instant steps, then return."""
    steps = max(1, int(ctx.params.get("steps", 3) or 3))
    t0 = time.monotonic()
    ctx.progress["started_at"] = time.time()
    # Give the first "step" real width (it stands in for a compile +
    # dispatch) so the first_step hop owns a visible slice of the
    # critical-path decomposition instead of a zero-width point.
    time.sleep(0.02)
    ctx.progress["first_step_at"] = time.time()
    ctx.progress["first_step_latency_s"] = time.monotonic() - t0
    ctx.progress["step"] = 1
    if ctx.publish is not None:
        ctx.publish()
    for step in range(2, steps + 1):
        if ctx.should_stop():
            break
        if ctx.watchdog is not None:
            ctx.watchdog.beat()
        ctx.progress["step"] = step
    ctx.progress["steps_total"] = steps
    if ctx.publish is not None:
        ctx.publish()
