"""Standalone workload runner — the container/subprocess entrypoint.

When a JAXJob runs as real pods on a GKE TPU slice (rather than in-process
under the embedded LocalExecutor), each host pod executes
``python -m cron_operator_tpu.workloads.runner <entrypoint>``. The runner:

1. initializes ``jax.distributed`` from the env the operator rendered at
   admission (``JAX_COORDINATOR_ADDRESS`` / ``JAX_NUM_PROCESSES`` /
   ``JAX_PROCESS_ID`` — backends/tpu.py ``render_coordinator_env``; the
   analog of the training-operator's ``MASTER_ADDR`` rendering for the GPU
   path, SURVEY.md §5 "Distributed communication backend"),
2. builds a JobContext from ``TPU_JOB_*`` env + CLI params,
3. runs the registered entrypoint across all hosts (ICI collectives inside
   the slice, DCN between slices — all via XLA; no comm code here).

The same runner is the LocalExecutor's **subprocess isolation mode**: the
executor launches it per job and reads progress from stdout as prefixed
JSON lines (``@@CRON_TPU@@ {...}``). Subprocess isolation is what makes a
timed-out/cancelled job killable without tearing down the operator process
mid-XLA-compile (round-1 postmortem: killing a compile thread in-process
wedged the TPU runtime for every later run). SIGTERM requests a graceful
stop (the trainer exits between steps); the parent escalates to SIGKILL
only after a grace period.

Params come as ``key=value`` args or ``TPU_PARAM_<KEY>`` env vars.
"""

from __future__ import annotations

import json
import logging
import os
import signal
import sys
from typing import Dict, List

logger = logging.getLogger("workloads.runner")

# Prefix for machine-readable progress lines on stdout (everything else the
# workload prints is passed through untouched).
PROGRESS_PREFIX = "@@CRON_TPU@@ "


def _gather_params(argv: List[str]) -> Dict[str, str]:
    from cron_operator_tpu.backends.tpu import normalize_param_key

    params: Dict[str, str] = {}
    for key, value in os.environ.items():
        if key.startswith("TPU_PARAM_"):
            params[normalize_param_key(key[len("TPU_PARAM_"):])] = value
    for arg in argv:
        if "=" in arg:
            k, v = arg.split("=", 1)
            params[normalize_param_key(k)] = v  # same normalization as env
    return params


def _maybe_pin_platform(params: Dict[str, str]) -> None:
    """``param.platform`` pins jax_platforms before first backend init.

    Needed because some images register extra platforms at interpreter
    startup (e.g. a tunneled TPU plugin) whose client init can block; a job
    that asked for ``platform=cpu`` must never dial them.
    """
    platform = params.get("platform")
    if not platform:
        return
    import jax

    jax.config.update("jax_platforms", platform)


def _maybe_init_distributed() -> None:
    """Multi-host wiring: coordinator env present → jax.distributed."""
    coordinator = os.environ.get("JAX_COORDINATOR_ADDRESS")
    n = int(os.environ.get("JAX_NUM_PROCESSES", "1") or 1)
    if not coordinator or n <= 1:
        return
    import jax

    pid = int(os.environ.get("JAX_PROCESS_ID", "0") or 0)
    logger.info(
        "initializing jax.distributed: coordinator=%s processes=%d id=%d",
        coordinator, n, pid,
    )
    jax.distributed.initialize(
        coordinator_address=coordinator, num_processes=n, process_id=pid
    )


def _emit(kind: str, payload: Dict) -> None:
    print(PROGRESS_PREFIX + json.dumps({"type": kind, **payload}), flush=True)


def main(argv: List[str] | None = None) -> int:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s\t%(levelname)s\t%(name)s\t%(message)s",
    )
    # Runner messages stay INFO; the ML stack's own loggers are capped at
    # WARNING — with basicConfig(INFO) a chatty backend (the experimental
    # tunneled-TPU plugin in particular) can log on the per-dispatch hot
    # path, and stderr formatting there is pure overhead per train step.
    for noisy in ("jax", "jaxlib", "axon", "flax", "orbax"):
        logging.getLogger(noisy).setLevel(logging.WARNING)
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv:
        print(
            "usage: python -m cron_operator_tpu.workloads.runner "
            "<entrypoint> [key=value ...]",
            file=sys.stderr,
        )
        return 2
    entry_name, rest = argv[0], argv[1:]

    from cron_operator_tpu.backends.registry import (
        JobContext,
        resolve_entrypoint,
    )

    from cron_operator_tpu.telemetry import ENV_TRACE_ID

    params = _gather_params(rest)
    _maybe_pin_platform(params)
    _maybe_init_distributed()
    fn = resolve_entrypoint(entry_name)
    ctx = JobContext(
        name=os.environ.get("TPU_JOB_NAME", entry_name),
        namespace=os.environ.get("TPU_JOB_NAMESPACE", "default"),
        job={"metadata": {"name": os.environ.get("TPU_JOB_NAME", entry_name)}},
        params=params,
        # Trace id the creating tick minted (rendered into the pod env by
        # backends.tpu.render_job_env) — telemetry this process emits is
        # attributable to its tick even across the process boundary.
        trace_id=os.environ.get(ENV_TRACE_ID) or None,
    )
    # Stream progress to the parent (executor folds it into
    # status.trainingProgress; a k8s sidecar could do the same).
    ctx.publish = lambda: _emit("progress", {"progress": ctx.progress})

    # SIGTERM = graceful stop request: the trainer exits between steps and
    # the PJRT client tears down cleanly (never yank a live compile).
    signal.signal(signal.SIGTERM, lambda *_: ctx.cancel.set())

    import time as _time

    t_run = _time.time()
    try:
        fn(ctx)
    except Exception as err:  # noqa: BLE001 — report, then non-zero exit
        import traceback

        _emit("error", {
            "error": f"{type(err).__name__}: {err}",
            "traceback": traceback.format_exc(),
            "progress": ctx.progress,
        })
        return 1
    if ctx.trace_id:
        # Ship this process's span home over the progress stream: the
        # executor ingests it (Tracer.ingest), making the runner the
        # third distinct process on the tick's distributed trace.
        from cron_operator_tpu.telemetry import new_span_id

        _emit("spans", {"spans": [{
            "name": "runner",
            "trace_id": ctx.trace_id,
            "span_id": new_span_id(),
            "parent_id": None,
            "start_s": t_run,
            "end_s": _time.time(),
            "attrs": {
                "pid": os.getpid(),
                "proc": "runner",
                "entrypoint": entry_name,
            },
        }]})
    _emit("done", {"progress": ctx.progress, "cancelled": ctx.should_stop()})
    return 0


if __name__ == "__main__":
    sys.exit(main())
