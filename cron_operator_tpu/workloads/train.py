"""Sharded training harness: one jitted step over a named mesh.

The scaling-book recipe end to end: build a mesh
(:func:`parallel.mesh.make_mesh`), derive NamedShardings for the train
state from shapes (:func:`parallel.mesh.sharding_for_tree`) and for batches
(:func:`parallel.mesh.batch_pspec`), jit the step with those shardings and
donated state — XLA GSPMD inserts every collective (gradient psum over
``data``, param all-gather / grad reduce-scatter over ``fsdp``, activation
collectives over ``tensor``/``seq``). No hand-written collectives anywhere
in the training path.

The train step is a pure function of (state, batch): Trainer carries no
mutable device state besides the TrainState it returns.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, Iterator, List, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax.training import train_state
from jax.sharding import Mesh, NamedSharding

from cron_operator_tpu.parallel.mesh import batch_pspec, sharding_for_tree
from cron_operator_tpu.parallel.overlap import (
    chain_steps,
    chunk_schedule,
    stacked_shardings,
)

# "auto" steps_per_call resolves to at most this many optimizer steps per
# dispatched scan. 8 amortizes the per-dispatch host cost to ~1/8 (already
# deep in diminishing returns vs a ~ms dispatch) while bounding the
# overshoot an external stop (preemption, budget) can suffer — a stop
# lands between dispatches, up to K-1 steps late.
_AUTO_MAX_CHUNK = 8


def cross_entropy_loss(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Mean softmax cross-entropy; labels are int classes, any leading dims
    (works for both classification [b] and MLM [b, s])."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    ll = jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
    return -jnp.mean(ll)


@dataclass
class TrainConfig:
    learning_rate: float = 1e-3
    weight_decay: float = 1e-4
    optimizer: str = "adamw"  # adamw | sgd
    # Learning-rate schedule: "constant", "cosine" (decay to 0 over
    # schedule_steps), or "warmup_cosine" (linear 0→lr over warmup_steps,
    # then cosine to 0 at schedule_steps). Schedules are optax functions
    # evaluated on the optimizer step count, so checkpoint resume lands at
    # the right point of the curve for free (step travels in TrainState).
    lr_schedule: str = "constant"
    warmup_steps: int = 0
    schedule_steps: int = 0  # decay horizon; entrypoints default it to
    # the run's total-step target
    # Clip gradients to this global norm before the optimizer (0 = off).
    # Both this and decay_mask alter the optimizer-state pytree when
    # enabled, so flipping them breaks checkpoint-resume into runs that
    # started without them (same rule as switching optimizers).
    grad_clip_norm: float = 0.0
    # AdamW weight decay only on rank>=2 params (kernels/embeddings) —
    # decaying biases and norm scales is the classic silent regression.
    decay_mask: bool = False
    remat: bool = False  # jax.checkpoint the forward (HBM ↔ FLOPs trade)
    seq_dim_in_batch: Optional[int] = None  # dim of x sharded over `seq`
    labels_follow_seq: bool = False  # labels carry the seq dim too (MLM)
    save_every: int = 0  # checkpoint cadence in steps (0 = never)
    # Model returns (logits, aux_loss) instead of bare logits; the scalar
    # aux (e.g. MoE router balance loss, already weighted by the model) is
    # added to the task loss.
    aux_loss_in_output: bool = False
    # Batches ahead to place on device from a background thread (0 = off).
    # Hides host→device transfer behind compute (workloads.data.Prefetcher).
    prefetch: int = 0
    # Seed for FUSED in-step data generation (Trainer sample_fn): the
    # batch key is fold_in(PRNGKey(data_seed), state.step), so resume
    # continues the data stream instead of replaying it.
    data_seed: int = 0
    # Optimizer steps per dispatched program (lax.scan of the step body).
    # >1 amortizes the per-dispatch host/link cost K× — on a tunneled
    # device whose dispatch latency drifts (PERF.md finding 5) this pins
    # the measured rate to the chip. FUSED data scans with no xs (each
    # in-scan step derives its batch from the live state.step); EXTERNAL
    # data scans over a chunk of K batches stacked along a leading step
    # axis (Trainer.put_chunk), staged ahead by a background thread when
    # stage_async is on. Either way the data stream and the math are
    # IDENTICAL to steps_per_call=1 — run() snaps chunks to checkpoint
    # save_every multiples and the step target, so saves land on their
    # exact step and the run never overshoots its target.
    #
    # "auto" picks the chunk length (min(8, save_every when
    # checkpointing)) — the default execution mode for the registered
    # entrypoints (param.steps_per_call).
    #
    # Stop granularity: a dispatched K-step program runs to completion —
    # an external stop (preemption, budget, deadline) lands between
    # dispatches, so the run can overshoot the stop point by up to K-1
    # optimizer steps.
    steps_per_call: Union[int, str] = 1
    # Background double-buffered staging for EXTERNAL data (on by
    # default): batch/chunk N+1 is built and device_put (sharded) by a
    # producer thread while N computes, so steady-state steps stop paying
    # host time (PERF.md finding 3 — host work, not the model, dominated
    # the step). prefetch > 0 overrides the staging depth; stage_async =
    # False forces fully synchronous staging (the pre-overlap behavior,
    # and the A-side of hack/step_bench.py). Only ARMED when the batch
    # shardings span ONE device: on a multi-device mesh the staging
    # thread would be a second program dispatcher racing the step
    # program's collectives across the per-device queues (XLA rendezvous
    # deadlock — the in-job analog of the gang_slots hazard), so run()
    # silently stages inline there.
    stage_async: bool = True
    # Block on the loss every N steps (1 = every step). Fetching a scalar
    # is a full host↔device round trip — ~80 ms on a tunneled device,
    # swamping a ~20 ms train step — so steady-state throughput needs the
    # sync amortized: intermediate steps dispatch async (their StepStats
    # carry loss=None), and the periodic synced step's wall time absorbs
    # the queued device work, keeping the *average* step time honest.
    sync_every: int = 1

    def lr_at(self):
        """The learning rate as an optax schedule (callable on the step
        count) — what make_optimizer feeds the optimizer for decaying
        schedules, and directly evaluable for tests/logging."""
        if self.lr_schedule == "constant":
            return optax.constant_schedule(self.learning_rate)
        if self.lr_schedule not in ("cosine", "warmup_cosine"):
            raise ValueError(f"unknown lr_schedule {self.lr_schedule!r}")
        if self.schedule_steps <= 0:
            # The registered entrypoints default this to the run's step
            # target; a direct Trainer user who forgets it would silently
            # train at ~0 LR from step 1 (cosine fully decayed).
            raise ValueError(
                f"lr_schedule={self.lr_schedule!r} needs schedule_steps > 0"
            )
        if self.lr_schedule == "cosine":
            return optax.cosine_decay_schedule(
                self.learning_rate, self.schedule_steps
            )
        return optax.warmup_cosine_decay_schedule(
            init_value=0.0,
            peak_value=self.learning_rate,
            warmup_steps=max(1, self.warmup_steps),
            decay_steps=max(self.warmup_steps + 1, self.schedule_steps),
        )

    def make_optimizer(self) -> optax.GradientTransformation:
        # A constant LR stays a plain float: wrapping it in a schedule
        # would add ScaleByScheduleState to the optimizer-state pytree and
        # break Orbax restore of every checkpoint saved before schedules
        # existed (structure mismatch), for zero behavioral gain.
        lr = (
            self.learning_rate if self.lr_schedule == "constant"
            else self.lr_at()
        )
        if self.decay_mask and self.optimizer != "adamw":
            # SGD has no weight decay to mask — accepting the flag would
            # leave an operator believing masked decay is active.
            raise ValueError(
                "decay_mask requires the adamw optimizer "
                f"(got {self.optimizer!r})"
            )
        mask = (
            (lambda params: jax.tree_util.tree_map(
                lambda p: p.ndim >= 2, params
            ))
            if self.decay_mask else None
        )
        if self.optimizer == "adamw":
            tx = optax.adamw(lr, weight_decay=self.weight_decay, mask=mask)
        elif self.optimizer == "sgd":
            tx = optax.sgd(lr, momentum=0.9)
        else:
            raise ValueError(f"unknown optimizer {self.optimizer!r}")
        if self.grad_clip_norm > 0:
            tx = optax.chain(
                optax.clip_by_global_norm(self.grad_clip_norm), tx
            )
        return tx


@dataclass
class StepStats:
    step: int
    loss: Optional[float]  # None on async (non-synced) steps
    step_time_s: float  # PER-STEP (dispatch wall / chunk)
    chunk: int = 1  # optimizer steps this dispatch carried
    # Phase breakdown of the dispatch (whole-chunk walls, seconds) —
    # the profiler-timeline inputs. data = host put_batch, dispatch =
    # jitted-call return (host work + queueing), sync = device wait for
    # the loss (0.0 on async steps), ckpt = checkpoint-save stall
    # (charged after the dispatch, excluded from step_time_s).
    data_s: float = 0.0
    dispatch_s: float = 0.0
    sync_s: float = 0.0
    ckpt_s: float = 0.0
    compiled: bool = False  # this dispatch traced+compiled (first call)


class Trainer:
    """Owns a model's sharded TrainState and jitted step.

    ``apply_fn(params, x) -> logits``; loss defaults to cross-entropy.
    """

    def __init__(
        self,
        apply_fn: Callable[[Any, jax.Array], jax.Array],
        params: Any,
        mesh: Mesh,
        config: Optional[TrainConfig] = None,
        loss_fn: Callable[[jax.Array, jax.Array], jax.Array] = cross_entropy_loss,
        checkpoint: Optional[Any] = None,  # workloads.checkpoint.CheckpointStore
        sample_fn: Optional[Callable[[jax.Array], Dict[str, jax.Array]]] = None,
    ):
        """``sample_fn`` (``key → batch dict``, e.g. ``data.imagenet_sample``)
        switches the trainer to FUSED data mode: the batch is generated
        INSIDE the jitted step from ``fold_in(PRNGKey(data_seed),
        state.step)`` — one dispatch per step and zero per-step
        host→device traffic. On a tunneled/remote device this is the
        difference between the chain-timed device step and the measured
        one (r5: 53 ms device vs 76-98 ms with a separate per-step
        batch-generation dispatch; PERF.md). Callers then feed ``run``
        empty-dict batches (``itertools.repeat({})``)."""
        self.mesh = mesh
        self.config = config or TrainConfig()
        self.checkpoint = checkpoint
        self.sample_fn = sample_fn
        tx = self.config.make_optimizer()

        fwd = apply_fn
        if self.config.remat:
            fwd = jax.checkpoint(apply_fn)

        aux_in_output = self.config.aux_loss_in_output
        data_seed = self.config.data_seed

        def step_fn(state: train_state.TrainState, batch: Dict[str, jax.Array]):
            if sample_fn is not None:
                key = jax.random.fold_in(
                    jax.random.PRNGKey(data_seed), state.step
                )
                # Pin the generated batch to the training layout so GSPMD
                # shards generation the same way an external batch would
                # arrive (self.batch_sharding exists by first trace).
                batch = {
                    k: jax.lax.with_sharding_constraint(
                        v, self.batch_sharding[k]
                    )
                    for k, v in sample_fn(key).items()
                }

            def loss_of(p):
                out = fwd(p, batch["x"])
                if aux_in_output:
                    logits, aux = out
                    return loss_fn(logits, batch["y"]) + aux
                return loss_fn(out, batch["y"])

            loss, grads = jax.value_and_grad(loss_of)(state.params)
            return state.apply_gradients(grads=grads), loss

        state = train_state.TrainState.create(apply_fn=apply_fn,
                                              params=params, tx=tx)
        self.state_sharding = sharding_for_tree(state, mesh)
        # Lay the state out per the sharding plan before the first step.
        self.state = jax.device_put(state, self.state_sharding)
        self.steps_done = 0
        if self.checkpoint is not None:
            latest = self.checkpoint.latest_step()
            if latest is not None:
                # Resume: restore directly into the mesh layout (no host
                # gather) and continue from the recorded step. The chain
                # walks back to an older retained step if the newest save
                # is truncated (torn async save at preemption time).
                _, self.state = self.checkpoint.restore_latest(self.state)
                self.steps_done = int(self.state.step)

        x_spec = batch_pspec(mesh, seq_dim=self.config.seq_dim_in_batch)
        y_spec = (
            batch_pspec(mesh, seq_dim=self.config.seq_dim_in_batch)
            if self.config.labels_follow_seq
            else batch_pspec(mesh)
        )
        self.batch_sharding = {
            "x": NamedSharding(mesh, x_spec),
            "y": NamedSharding(mesh, y_spec),
        }
        # Fused mode takes an EMPTY batch dict (the data comes from the
        # in-step PRNG); the in_shardings pytree must match it.
        in_batch_sharding = {} if sample_fn is not None else self.batch_sharding
        self._jit_kwargs = dict(
            in_shardings=(self.state_sharding, in_batch_sharding),
            out_shardings=(self.state_sharding,
                           NamedSharding(mesh, jax.sharding.PartitionSpec())),
            donate_argnums=(0,),
        )
        self._step_fn = step_fn
        self._step = jax.jit(step_fn, **self._jit_kwargs)
        spc = self.config.steps_per_call
        if not (spc == "auto" or isinstance(spc, int)):
            raise ValueError(
                f"steps_per_call must be an int or 'auto' (got {spc!r})"
            )
        # Chunk length → jitted scan program (fused mode). Bounded: a
        # steady run uses at most two lengths (full chunk + snapped/tail
        # chunk), but a caller driving step(chunk=) with varying lengths
        # would otherwise accumulate one compiled program per distinct
        # length for the process lifetime. LRU-evict beyond the cap —
        # recompiling a rare length is cheap next to leaking compiled
        # executables.
        self._multi: Dict[int, Any] = {}
        self._multi_cap = 8
        # External scan-chained program (one jitted fn; jax.jit caches
        # per stacked shape internally, so chunk lengths don't need the
        # _multi bookkeeping).
        self._ext_step = None
        self._batch_struct = None  # set on first put_batch (flops_per_step)
        self._flops_per_step: Optional[float] = None
        # Wall-clock of this process's first dispatch (XLA compile + first
        # step execution). The compile-time telemetry record: entrypoints
        # forward it as progress["compile_time_s"], decomposing the
        # tick→first-step latency into its compile component on /metrics.
        self.first_dispatch_time_s: Optional[float] = None

    @property
    def resolved_steps_per_call(self) -> int:
        """``config.steps_per_call`` with ``"auto"`` resolved: chunks of
        ``min(8, save_every)`` when checkpointing (run() snaps chunks to
        save_every multiples, so a longer chunk would only fragment into
        the same pieces), plain ``min(8, ·)`` otherwise."""
        spc = self.config.steps_per_call
        if spc == "auto":
            se = self.config.save_every
            spc = (
                min(_AUTO_MAX_CHUNK, se)
                if (self.checkpoint is not None and se > 0)
                else _AUTO_MAX_CHUNK
            )
        return max(1, int(spc))

    def _stepper(self, chunk: int):
        """The jitted FUSED program for ``chunk`` optimizer steps per
        dispatch (1 → the plain step). Cached per length under an LRU cap
        — a snapped schedule alternates steady and boundary/tail lengths,
        and an eviction keyed on insertion age (the old FIFO) would
        recompile the steady program on every other call once the cap was
        hit; re-inserting on hit keeps every length in active rotation
        cached."""
        if chunk <= 1:
            return self._step
        if self.sample_fn is None:
            # The public step(chunk=) path must not silently replay one
            # external batch for every step of the scan — external chunks
            # go through put_chunk (a stacked _PlacedChunk), which
            # carries one REAL batch per scan step.
            raise ValueError(
                "chunk > 1 requires fused data (sample_fn): external "
                "batches cannot be replayed inside the scan — stage a "
                "stacked chunk via put_chunk instead"
            )
        fn = self._multi.get(chunk)
        if fn is not None:
            self._multi[chunk] = self._multi.pop(chunk)  # LRU touch
            return fn
        fn = chain_steps(
            self._step_fn, length=chunk, jit_kwargs=self._jit_kwargs
        )
        while len(self._multi) >= self._multi_cap:
            self._multi.pop(next(iter(self._multi)))
        self._multi[chunk] = fn
        return fn

    def _chunk_stepper(self):
        """The jitted EXTERNAL scan-chained program: scans over a stacked
        chunk (leading step axis), state donated through. One function for
        every chunk length — jit specializes per stacked shape in its own
        cache."""
        if self._ext_step is None:
            self._ext_step = chain_steps(
                self._step_fn,
                over_batch=True,
                jit_kwargs=dict(
                    in_shardings=(
                        self.state_sharding,
                        stacked_shardings(self.batch_sharding),
                    ),
                    out_shardings=self._jit_kwargs["out_shardings"],
                    donate_argnums=(0,),
                ),
            )
        return self._ext_step

    def _staging_devices(self) -> int:
        """Device count under the batch shardings — the async stager is
        only spawned when this is 1 (see the single-controller rule in
        :meth:`run`)."""
        for s in (self.batch_sharding or {}).values():
            try:
                return len(s.device_set)
            except (AttributeError, TypeError):
                return 1
        return 1

    def put_batch(self, batch: Dict[str, Any]) -> Dict[str, jax.Array]:
        placed = {
            k: jax.device_put(jnp.asarray(v), self.batch_sharding[k])
            for k, v in batch.items()
        }
        if self._batch_struct is None:
            self._batch_struct = jax.tree_util.tree_map(
                lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), placed
            )
        return placed

    def put_chunk(self, group: List[Dict[str, Any]]) -> "_PlacedChunk":
        """Stack K external batches along a new leading (step) axis and
        place them in ONE sharded transfer (scan axis replicated, per-step
        layout unchanged — parallel.overlap.stacked_shardings). The
        scan-chained program consumes slice i at step i, so the data
        stream is identical to K single dispatches. This is the
        ChunkStager's ``place`` callable — it runs on the staging thread,
        overlapping the whole host cost of the next chunk with the
        current chunk's device compute."""
        if not group:
            raise ValueError("put_chunk needs a non-empty batch group")
        shardings = stacked_shardings(self.batch_sharding)
        stacked = {}
        for name in group[0]:
            parts = [b[name] for b in group]
            if all(isinstance(p, np.ndarray) for p in parts):
                arr = np.stack(parts)
            else:
                arr = jnp.stack([jnp.asarray(p) for p in parts])
            stacked[name] = jax.device_put(arr, shardings[name])
        if self._batch_struct is None:
            # ONE step's batch struct (leading axis stripped): the MFU /
            # flops_per_step numerator is per optimizer step, not per
            # dispatched chunk.
            self._batch_struct = {
                k: jax.ShapeDtypeStruct(a.shape[1:], a.dtype)
                for k, a in stacked.items()
            }
        return _PlacedChunk(stacked, len(group))

    def flops_per_step(self) -> Optional[float]:
        """XLA's own flop count for ONE compiled train step (fwd + bwd +
        optimizer + any in-step data generation) via cost analysis of the
        jitted step at the shapes actually trained.

        This is the honest MFU numerator: analytic per-model tables
        undercount (the classic "ResNet-50 = 4.1 GFLOPs" figure counts
        multiply-ADDS; XLA counts a MAC as 2 flops — measured 8.03 vs
        4.1 GFLOP fwd at 224², a 2× MFU error, hack/mfu_attrib.py).
        Returns None before the first step or when the backend offers no
        cost analysis.
        """
        if self._batch_struct is None:
            return None
        if self._flops_per_step is None:
            try:
                struct = jax.tree_util.tree_map(
                    lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
                    self.state,
                )
                ca = (
                    self._step.lower(struct, self._batch_struct)
                    .compile()
                    .cost_analysis()
                )
                ca = ca[0] if isinstance(ca, (list, tuple)) else ca
                flops = (ca or {}).get("flops")
                self._flops_per_step = float(flops) if flops else None
            except Exception:  # noqa: BLE001 — diagnostics must not
                self._flops_per_step = None  # fail training
        return self._flops_per_step

    def step(
        self,
        batch: Union[Dict[str, Any], "_PlacedChunk"],
        sync: bool = True,
        chunk: int = 1,
    ) -> StepStats:
        """One dispatch of ``chunk`` optimizer steps (see
        TrainConfig.steps_per_call). ``step_time_s`` is normalized PER
        STEP (dispatch wall / chunk) so throughput math is
        chunk-agnostic; ``loss`` is the chunk's last step's. A
        pre-staged :meth:`put_chunk` result dispatches the external
        scan-chained program (its length IS the chunk)."""
        compiled = self.first_dispatch_time_s is None
        t0 = time.perf_counter()
        if isinstance(batch, _PlacedChunk):
            chunk = batch.chunk
            device_batch = batch.arrays
            stepper = self._chunk_stepper()
        else:
            device_batch = self.put_batch(batch)
            stepper = self._stepper(chunk)
        t_data = time.perf_counter()
        self.state, loss = stepper(self.state, device_batch)
        t_disp = time.perf_counter()
        # Blocking keeps the step-time numbers honest; sync=False lets the
        # caller amortize the round trip (see TrainConfig.sync_every).
        loss = float(loss) if sync else None
        wall = time.perf_counter() - t0
        sync_s = time.perf_counter() - t_disp if sync else 0.0
        if compiled:
            # Compile-laden by construction: a fresh process always traces
            # + compiles on its first dispatch (even after checkpoint
            # resume), so this wall time IS the compile measurement —
            # meaningful only when the caller synced the call (run()
            # always syncs the first).
            self.first_dispatch_time_s = wall
        before = self.steps_done
        self.steps_done += chunk
        ckpt_s = 0.0
        if (
            self.checkpoint is not None
            and self.config.save_every > 0
            # Crossing a save_every boundary anywhere inside the chunk.
            and self.steps_done // self.config.save_every
            > before // self.config.save_every
        ):
            t_ckpt = time.perf_counter()
            self.checkpoint.save(self.steps_done, self.state)
            ckpt_s = time.perf_counter() - t_ckpt
        return StepStats(
            self.steps_done, loss,
            wall / max(1, chunk),
            chunk=max(1, chunk),
            data_s=t_data - t0,
            dispatch_s=t_disp - t_data,
            sync_s=sync_s,
            ckpt_s=ckpt_s,
            compiled=compiled,
        )

    @staticmethod
    def per_step_stats(s: StepStats) -> List[StepStats]:
        """A dispatch's StepStats divided into per-STEP records — what
        run() feeds ``on_step`` so the step-phase timeline and rolling
        MFU stay per-step truthful under scan-chained dispatch. The
        chunk's phase walls are split evenly (the scan gives no per-step
        brackets), the loss rides the last step (the only one the
        dispatch fetched), and the checkpoint stall lands on the last
        step (chunks snap to save_every, so the save step IS the chunk's
        last)."""
        k = s.chunk
        if k <= 1:
            return [s]
        out = []
        for i in range(k):
            last = i == k - 1
            out.append(StepStats(
                step=s.step - (k - 1 - i),
                loss=s.loss if last else None,
                step_time_s=s.step_time_s,  # already per-step
                chunk=1,
                data_s=s.data_s / k,
                dispatch_s=s.dispatch_s / k,
                sync_s=s.sync_s / k,
                ckpt_s=s.ckpt_s if last else 0.0,
                compiled=s.compiled,
            ))
        return out

    def run(
        self,
        batches: Iterator[Dict[str, Any]],
        steps: int,
        should_stop: Optional[Callable[[], bool]] = None,
        on_step: Optional[Callable[[StepStats], None]] = None,
    ) -> list:
        """Train until ``steps_done`` reaches ``steps`` (a TOTAL-step
        target, so a checkpoint-restored trainer only runs the remainder —
        preempted work is not repeated).

        Execution mode is picked from the config: external data with
        ``steps_per_call`` > 1 (or ``"auto"``) runs scan-chained chunks
        staged ahead by a background ChunkStager (double-buffered:
        chunk N+1 is stacked + device_put while chunk N computes);
        external single-step runs stage batch-ahead via the Prefetcher
        (on by default — ``stage_async``); fused data scans in-step.
        Chunk sizes come from :func:`parallel.overlap.chunk_schedule`,
        snapped to checkpoint ``save_every`` multiples and the step
        target. ``on_step`` receives PER-STEP stats (chunk aggregates
        divided — :meth:`per_step_stats`); the returned list stays
        per-dispatch.
        """
        se = max(1, self.config.sync_every)
        spc = self.resolved_steps_per_call
        external = self.sample_fn is None
        boundary = (
            self.config.save_every
            if (self.checkpoint is not None and self.config.save_every > 0)
            else 0
        )
        depth = (
            self.config.prefetch if self.config.prefetch > 0
            else (2 if self.config.stage_async else 0)
        )
        if depth > 0 and self._staging_devices() > 1:
            # Single-controller rule: a staging thread is a SECOND program
            # dispatcher. On a >1-device mesh its jitted work (device-side
            # batch generators, stack-and-reshard placements) interleaves
            # program enqueue with the step program's collectives across
            # the per-device queues — the same XLA rendezvous deadlock
            # gang_slots serializes between jobs, now inside one job.
            # Stage inline instead; scan-chained dispatch (the dominant
            # win) is thread-free and keeps.
            depth = 0
        stager = None
        prefetcher = None
        chunks = None  # iterator of _PlacedChunk (external chunked mode)
        sched: List[int] = []
        # Lazy: a no-op run (target already reached after checkpoint
        # restore, or an immediate stop) must not consume + device-place
        # staged batches it will never use.
        pending = self.steps_done < steps
        if pending and external and spc > 1:
            from cron_operator_tpu.workloads.data import ChunkStager, grouped

            schedule = chunk_schedule(self.steps_done, steps, spc, boundary)
            if depth > 0:
                stager = ChunkStager(
                    batches, schedule, self.put_chunk, depth
                )
                chunks = stager
            else:
                # Synchronous staging (stage_async=False): same chunked
                # program, stack + place on the consumer thread — the
                # A-side of the step bench's overlap A/B.
                chunks = (
                    self.put_chunk(g) for g in grouped(batches, schedule)
                )
        elif pending and depth > 0 and (external or self.config.prefetch > 0):
            from cron_operator_tpu.workloads.data import Prefetcher

            prefetcher = Prefetcher(batches, self.put_batch, depth)
            batches = prefetcher  # step's put_batch is a no-op re-place
        elif pending and not external and spc > 1:
            sched = chunk_schedule(self.steps_done, steps, spc, boundary)
        first = self.steps_done + 1
        stats = []
        try:
            while self.steps_done < steps:
                if should_stop is not None and should_stop():
                    break
                nxt = self.steps_done + 1
                placed = None
                wait_s = 0.0
                if chunks is not None:
                    t_wait = time.perf_counter()
                    placed = next(chunks)  # StopIteration = stream ended
                    wait_s = time.perf_counter() - t_wait
                    chunk = placed.chunk
                elif sched:
                    chunk = min(sched.pop(0), steps - self.steps_done)
                else:
                    chunk = min(spc, steps - self.steps_done)
                last_of_call = self.steps_done + chunk
                # Always sync the first call (the tick→first-step anchor
                # must be device-completed, not merely dispatched) and the
                # last (so run() returns with the device drained); between
                # them, sync whenever the call crosses a sync_every
                # boundary (counted in steps from `first`, so the cadence
                # is chunk-agnostic).
                sync = (
                    nxt == first or last_of_call >= steps
                    or (last_of_call - first + 1) // se
                    > (nxt - first) // se
                )
                if placed is not None:
                    s = self.step(placed, sync=sync)
                    if wait_s:
                        # The stager wait is the UN-hidden remainder of
                        # host data work (≈0 when staging keeps up) —
                        # charge it where put_batch time used to go so
                        # throughput stays honest.
                        s.data_s += wait_s
                        s.step_time_s += wait_s / s.chunk
                else:
                    s = self.step(next(batches), sync=sync, chunk=chunk)
                stats.append(s)
                if on_step is not None:
                    for ps in self.per_step_stats(s):
                        on_step(ps)
        finally:
            if stats and stats[-1].loss is None:
                # Exited (should_stop / exception) behind async steps:
                # drain the device before teardown — never leave programs
                # in flight (chip hygiene) — and charge the drain to the
                # last step so avg_step_time_s stays honest instead of
                # averaging dispatch-only times.
                t0 = time.perf_counter()
                jax.block_until_ready(self.state)
                # step_time_s is per-step: normalize the drain by the
                # final call's chunk too.
                stats[-1].step_time_s += (
                    (time.perf_counter() - t0) / stats[-1].chunk
                )
            if stager is not None:
                stager.close()
            if prefetcher is not None:
                prefetcher.close()
        if self.checkpoint is not None:
            self.checkpoint.wait()
        return stats


class _PlacedChunk:
    """Device-resident stacked chunk from :meth:`Trainer.put_chunk`: K
    external batches stacked along a leading step axis, placed with the
    scan-axis-replicated sharding. Recognized by :meth:`Trainer.step` as
    pre-staged input for the scan-chained program — a plain dict with
    ``chunk > 1`` still raises (one external batch cannot be replayed
    across the scan)."""

    __slots__ = ("arrays", "chunk")

    def __init__(self, arrays: Dict[str, jax.Array], chunk: int):
        self.arrays = arrays
        self.chunk = int(chunk)


__all__ = ["Trainer", "TrainConfig", "StepStats", "cross_entropy_loss"]
