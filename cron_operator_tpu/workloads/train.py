"""Sharded training harness: one jitted step over a named mesh.

The scaling-book recipe end to end: build a mesh
(:func:`parallel.mesh.make_mesh`), derive NamedShardings for the train
state from shapes (:func:`parallel.mesh.sharding_for_tree`) and for batches
(:func:`parallel.mesh.batch_pspec`), jit the step with those shardings and
donated state — XLA GSPMD inserts every collective (gradient psum over
``data``, param all-gather / grad reduce-scatter over ``fsdp``, activation
collectives over ``tensor``/``seq``). No hand-written collectives anywhere
in the training path.

The train step is a pure function of (state, batch): Trainer carries no
mutable device state besides the TrainState it returns.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, Iterator, Optional

import jax
import jax.numpy as jnp
import optax
from flax.training import train_state
from jax.sharding import Mesh, NamedSharding

from cron_operator_tpu.parallel.mesh import batch_pspec, sharding_for_tree


def cross_entropy_loss(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Mean softmax cross-entropy; labels are int classes, any leading dims
    (works for both classification [b] and MLM [b, s])."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    ll = jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
    return -jnp.mean(ll)


@dataclass
class TrainConfig:
    learning_rate: float = 1e-3
    weight_decay: float = 1e-4
    optimizer: str = "adamw"  # adamw | sgd
    # Learning-rate schedule: "constant", "cosine" (decay to 0 over
    # schedule_steps), or "warmup_cosine" (linear 0→lr over warmup_steps,
    # then cosine to 0 at schedule_steps). Schedules are optax functions
    # evaluated on the optimizer step count, so checkpoint resume lands at
    # the right point of the curve for free (step travels in TrainState).
    lr_schedule: str = "constant"
    warmup_steps: int = 0
    schedule_steps: int = 0  # decay horizon; entrypoints default it to
    # the run's total-step target
    # Clip gradients to this global norm before the optimizer (0 = off).
    # Both this and decay_mask alter the optimizer-state pytree when
    # enabled, so flipping them breaks checkpoint-resume into runs that
    # started without them (same rule as switching optimizers).
    grad_clip_norm: float = 0.0
    # AdamW weight decay only on rank>=2 params (kernels/embeddings) —
    # decaying biases and norm scales is the classic silent regression.
    decay_mask: bool = False
    remat: bool = False  # jax.checkpoint the forward (HBM ↔ FLOPs trade)
    seq_dim_in_batch: Optional[int] = None  # dim of x sharded over `seq`
    labels_follow_seq: bool = False  # labels carry the seq dim too (MLM)
    save_every: int = 0  # checkpoint cadence in steps (0 = never)
    # Model returns (logits, aux_loss) instead of bare logits; the scalar
    # aux (e.g. MoE router balance loss, already weighted by the model) is
    # added to the task loss.
    aux_loss_in_output: bool = False
    # Batches ahead to place on device from a background thread (0 = off).
    # Hides host→device transfer behind compute (workloads.data.Prefetcher).
    prefetch: int = 0
    # Seed for FUSED in-step data generation (Trainer sample_fn): the
    # batch key is fold_in(PRNGKey(data_seed), state.step), so resume
    # continues the data stream instead of replaying it.
    data_seed: int = 0
    # Optimizer steps per dispatched program (lax.scan of the step body;
    # requires fused data — external batches can't be replayed inside
    # the scan). >1 amortizes the per-dispatch host/link cost K× — on a
    # tunneled device whose dispatch latency drifts (PERF.md finding 5)
    # this pins the measured rate to the chip. The data stream is
    # IDENTICAL to steps_per_call=1: each in-scan step derives its batch
    # from the live state.step.
    #
    # Stop granularity: a dispatched K-step program runs to completion —
    # an external stop (preemption, budget, deadline) lands between
    # dispatches, so the run can overshoot the stop point by up to K-1
    # optimizer steps. Pick K against checkpoint/stop granularity, not
    # just dispatch amortization.
    steps_per_call: int = 1
    # Block on the loss every N steps (1 = every step). Fetching a scalar
    # is a full host↔device round trip — ~80 ms on a tunneled device,
    # swamping a ~20 ms train step — so steady-state throughput needs the
    # sync amortized: intermediate steps dispatch async (their StepStats
    # carry loss=None), and the periodic synced step's wall time absorbs
    # the queued device work, keeping the *average* step time honest.
    sync_every: int = 1

    def lr_at(self):
        """The learning rate as an optax schedule (callable on the step
        count) — what make_optimizer feeds the optimizer for decaying
        schedules, and directly evaluable for tests/logging."""
        if self.lr_schedule == "constant":
            return optax.constant_schedule(self.learning_rate)
        if self.lr_schedule not in ("cosine", "warmup_cosine"):
            raise ValueError(f"unknown lr_schedule {self.lr_schedule!r}")
        if self.schedule_steps <= 0:
            # The registered entrypoints default this to the run's step
            # target; a direct Trainer user who forgets it would silently
            # train at ~0 LR from step 1 (cosine fully decayed).
            raise ValueError(
                f"lr_schedule={self.lr_schedule!r} needs schedule_steps > 0"
            )
        if self.lr_schedule == "cosine":
            return optax.cosine_decay_schedule(
                self.learning_rate, self.schedule_steps
            )
        return optax.warmup_cosine_decay_schedule(
            init_value=0.0,
            peak_value=self.learning_rate,
            warmup_steps=max(1, self.warmup_steps),
            decay_steps=max(self.warmup_steps + 1, self.schedule_steps),
        )

    def make_optimizer(self) -> optax.GradientTransformation:
        # A constant LR stays a plain float: wrapping it in a schedule
        # would add ScaleByScheduleState to the optimizer-state pytree and
        # break Orbax restore of every checkpoint saved before schedules
        # existed (structure mismatch), for zero behavioral gain.
        lr = (
            self.learning_rate if self.lr_schedule == "constant"
            else self.lr_at()
        )
        if self.decay_mask and self.optimizer != "adamw":
            # SGD has no weight decay to mask — accepting the flag would
            # leave an operator believing masked decay is active.
            raise ValueError(
                "decay_mask requires the adamw optimizer "
                f"(got {self.optimizer!r})"
            )
        mask = (
            (lambda params: jax.tree_util.tree_map(
                lambda p: p.ndim >= 2, params
            ))
            if self.decay_mask else None
        )
        if self.optimizer == "adamw":
            tx = optax.adamw(lr, weight_decay=self.weight_decay, mask=mask)
        elif self.optimizer == "sgd":
            tx = optax.sgd(lr, momentum=0.9)
        else:
            raise ValueError(f"unknown optimizer {self.optimizer!r}")
        if self.grad_clip_norm > 0:
            tx = optax.chain(
                optax.clip_by_global_norm(self.grad_clip_norm), tx
            )
        return tx


@dataclass
class StepStats:
    step: int
    loss: Optional[float]  # None on async (non-synced) steps
    step_time_s: float  # PER-STEP (dispatch wall / chunk)
    chunk: int = 1  # optimizer steps this dispatch carried
    # Phase breakdown of the dispatch (whole-chunk walls, seconds) —
    # the profiler-timeline inputs. data = host put_batch, dispatch =
    # jitted-call return (host work + queueing), sync = device wait for
    # the loss (0.0 on async steps), ckpt = checkpoint-save stall
    # (charged after the dispatch, excluded from step_time_s).
    data_s: float = 0.0
    dispatch_s: float = 0.0
    sync_s: float = 0.0
    ckpt_s: float = 0.0
    compiled: bool = False  # this dispatch traced+compiled (first call)


class Trainer:
    """Owns a model's sharded TrainState and jitted step.

    ``apply_fn(params, x) -> logits``; loss defaults to cross-entropy.
    """

    def __init__(
        self,
        apply_fn: Callable[[Any, jax.Array], jax.Array],
        params: Any,
        mesh: Mesh,
        config: Optional[TrainConfig] = None,
        loss_fn: Callable[[jax.Array, jax.Array], jax.Array] = cross_entropy_loss,
        checkpoint: Optional[Any] = None,  # workloads.checkpoint.CheckpointStore
        sample_fn: Optional[Callable[[jax.Array], Dict[str, jax.Array]]] = None,
    ):
        """``sample_fn`` (``key → batch dict``, e.g. ``data.imagenet_sample``)
        switches the trainer to FUSED data mode: the batch is generated
        INSIDE the jitted step from ``fold_in(PRNGKey(data_seed),
        state.step)`` — one dispatch per step and zero per-step
        host→device traffic. On a tunneled/remote device this is the
        difference between the chain-timed device step and the measured
        one (r5: 53 ms device vs 76-98 ms with a separate per-step
        batch-generation dispatch; PERF.md). Callers then feed ``run``
        empty-dict batches (``itertools.repeat({})``)."""
        self.mesh = mesh
        self.config = config or TrainConfig()
        self.checkpoint = checkpoint
        self.sample_fn = sample_fn
        tx = self.config.make_optimizer()

        fwd = apply_fn
        if self.config.remat:
            fwd = jax.checkpoint(apply_fn)

        aux_in_output = self.config.aux_loss_in_output
        data_seed = self.config.data_seed

        def step_fn(state: train_state.TrainState, batch: Dict[str, jax.Array]):
            if sample_fn is not None:
                key = jax.random.fold_in(
                    jax.random.PRNGKey(data_seed), state.step
                )
                # Pin the generated batch to the training layout so GSPMD
                # shards generation the same way an external batch would
                # arrive (self.batch_sharding exists by first trace).
                batch = {
                    k: jax.lax.with_sharding_constraint(
                        v, self.batch_sharding[k]
                    )
                    for k, v in sample_fn(key).items()
                }

            def loss_of(p):
                out = fwd(p, batch["x"])
                if aux_in_output:
                    logits, aux = out
                    return loss_fn(logits, batch["y"]) + aux
                return loss_fn(out, batch["y"])

            loss, grads = jax.value_and_grad(loss_of)(state.params)
            return state.apply_gradients(grads=grads), loss

        state = train_state.TrainState.create(apply_fn=apply_fn,
                                              params=params, tx=tx)
        self.state_sharding = sharding_for_tree(state, mesh)
        # Lay the state out per the sharding plan before the first step.
        self.state = jax.device_put(state, self.state_sharding)
        self.steps_done = 0
        if self.checkpoint is not None:
            latest = self.checkpoint.latest_step()
            if latest is not None:
                # Resume: restore directly into the mesh layout (no host
                # gather) and continue from the recorded step.
                self.state = self.checkpoint.restore(latest, self.state)
                self.steps_done = int(self.state.step)

        x_spec = batch_pspec(mesh, seq_dim=self.config.seq_dim_in_batch)
        y_spec = (
            batch_pspec(mesh, seq_dim=self.config.seq_dim_in_batch)
            if self.config.labels_follow_seq
            else batch_pspec(mesh)
        )
        self.batch_sharding = {
            "x": NamedSharding(mesh, x_spec),
            "y": NamedSharding(mesh, y_spec),
        }
        # Fused mode takes an EMPTY batch dict (the data comes from the
        # in-step PRNG); the in_shardings pytree must match it.
        in_batch_sharding = {} if sample_fn is not None else self.batch_sharding
        self._jit_kwargs = dict(
            in_shardings=(self.state_sharding, in_batch_sharding),
            out_shardings=(self.state_sharding,
                           NamedSharding(mesh, jax.sharding.PartitionSpec())),
            donate_argnums=(0,),
        )
        self._step_fn = step_fn
        self._step = jax.jit(step_fn, **self._jit_kwargs)
        if self.config.steps_per_call > 1 and sample_fn is None:
            raise ValueError(
                "steps_per_call > 1 requires fused data (sample_fn): "
                "external batches cannot be replayed inside the scan"
            )
        # Chunk length → jitted scan program. Bounded: a steady run uses
        # at most two lengths (full chunk + partial tail), but a caller
        # driving step(chunk=) with varying lengths would otherwise
        # accumulate one compiled program per distinct length for the
        # process lifetime. FIFO-evict beyond the cap — recompiling a
        # rare length is cheap next to leaking compiled executables.
        self._multi: Dict[int, Any] = {}
        self._multi_cap = 8
        self._batch_struct = None  # set on first put_batch (flops_per_step)
        self._flops_per_step: Optional[float] = None
        # Wall-clock of this process's first dispatch (XLA compile + first
        # step execution). The compile-time telemetry record: entrypoints
        # forward it as progress["compile_time_s"], decomposing the
        # tick→first-step latency into its compile component on /metrics.
        self.first_dispatch_time_s: Optional[float] = None

    def _stepper(self, chunk: int):
        """The jitted program for ``chunk`` optimizer steps per dispatch
        (1 → the plain step). Cached per length — a partial final chunk
        compiles its own (second, at most) program."""
        if chunk <= 1:
            return self._step
        if self.sample_fn is None:
            # Same guard as __init__ for config.steps_per_call — the
            # public step(chunk=) path must not silently replay one
            # external batch for every step of the scan.
            raise ValueError(
                "chunk > 1 requires fused data (sample_fn): external "
                "batches cannot be replayed inside the scan"
            )
        fn = self._multi.get(chunk)
        if fn is None:
            step_fn = self._step_fn

            def multi(state, batch):
                def body(s, _):
                    s2, loss = step_fn(s, batch)
                    return s2, loss

                state, losses = jax.lax.scan(
                    body, state, None, length=chunk
                )
                return state, losses[-1]

            fn = jax.jit(multi, **self._jit_kwargs)
            while len(self._multi) >= self._multi_cap:
                self._multi.pop(next(iter(self._multi)))
            self._multi[chunk] = fn
        return fn

    def put_batch(self, batch: Dict[str, Any]) -> Dict[str, jax.Array]:
        placed = {
            k: jax.device_put(jnp.asarray(v), self.batch_sharding[k])
            for k, v in batch.items()
        }
        if self._batch_struct is None:
            self._batch_struct = jax.tree_util.tree_map(
                lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), placed
            )
        return placed

    def flops_per_step(self) -> Optional[float]:
        """XLA's own flop count for ONE compiled train step (fwd + bwd +
        optimizer + any in-step data generation) via cost analysis of the
        jitted step at the shapes actually trained.

        This is the honest MFU numerator: analytic per-model tables
        undercount (the classic "ResNet-50 = 4.1 GFLOPs" figure counts
        multiply-ADDS; XLA counts a MAC as 2 flops — measured 8.03 vs
        4.1 GFLOP fwd at 224², a 2× MFU error, hack/mfu_attrib.py).
        Returns None before the first step or when the backend offers no
        cost analysis.
        """
        if self._batch_struct is None:
            return None
        if self._flops_per_step is None:
            try:
                struct = jax.tree_util.tree_map(
                    lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
                    self.state,
                )
                ca = (
                    self._step.lower(struct, self._batch_struct)
                    .compile()
                    .cost_analysis()
                )
                ca = ca[0] if isinstance(ca, (list, tuple)) else ca
                flops = (ca or {}).get("flops")
                self._flops_per_step = float(flops) if flops else None
            except Exception:  # noqa: BLE001 — diagnostics must not
                self._flops_per_step = None  # fail training
        return self._flops_per_step

    def step(
        self, batch: Dict[str, Any], sync: bool = True, chunk: int = 1
    ) -> StepStats:
        """One dispatch of ``chunk`` optimizer steps (see
        TrainConfig.steps_per_call). ``step_time_s`` is normalized PER
        STEP (dispatch wall / chunk) so throughput math is
        chunk-agnostic; ``loss`` is the chunk's last step's."""
        compiled = self.first_dispatch_time_s is None
        t0 = time.perf_counter()
        device_batch = self.put_batch(batch)
        t_data = time.perf_counter()
        self.state, loss = self._stepper(chunk)(self.state, device_batch)
        t_disp = time.perf_counter()
        # Blocking keeps the step-time numbers honest; sync=False lets the
        # caller amortize the round trip (see TrainConfig.sync_every).
        loss = float(loss) if sync else None
        wall = time.perf_counter() - t0
        sync_s = time.perf_counter() - t_disp if sync else 0.0
        if compiled:
            # Compile-laden by construction: a fresh process always traces
            # + compiles on its first dispatch (even after checkpoint
            # resume), so this wall time IS the compile measurement —
            # meaningful only when the caller synced the call (run()
            # always syncs the first).
            self.first_dispatch_time_s = wall
        before = self.steps_done
        self.steps_done += chunk
        ckpt_s = 0.0
        if (
            self.checkpoint is not None
            and self.config.save_every > 0
            # Crossing a save_every boundary anywhere inside the chunk.
            and self.steps_done // self.config.save_every
            > before // self.config.save_every
        ):
            t_ckpt = time.perf_counter()
            self.checkpoint.save(self.steps_done, self.state)
            ckpt_s = time.perf_counter() - t_ckpt
        return StepStats(
            self.steps_done, loss,
            wall / max(1, chunk),
            chunk=max(1, chunk),
            data_s=t_data - t0,
            dispatch_s=t_disp - t_data,
            sync_s=sync_s,
            ckpt_s=ckpt_s,
            compiled=compiled,
        )

    def run(
        self,
        batches: Iterator[Dict[str, Any]],
        steps: int,
        should_stop: Optional[Callable[[], bool]] = None,
        on_step: Optional[Callable[[StepStats], None]] = None,
    ) -> list:
        """Train until ``steps_done`` reaches ``steps`` (a TOTAL-step
        target, so a checkpoint-restored trainer only runs the remainder —
        preempted work is not repeated)."""
        prefetcher = None
        # Lazy: a no-op run (target already reached after checkpoint
        # restore, or an immediate stop) must not consume + device-place
        # depth+1 batches it will never use.
        if self.config.prefetch > 0 and self.steps_done < steps:
            from cron_operator_tpu.workloads.data import Prefetcher

            prefetcher = Prefetcher(
                batches, self.put_batch, self.config.prefetch
            )
            batches = prefetcher  # step's put_batch is a no-op re-place
        se = max(1, self.config.sync_every)
        spc = max(1, self.config.steps_per_call)
        first = self.steps_done + 1
        stats = []
        try:
            while self.steps_done < steps:
                if should_stop is not None and should_stop():
                    break
                nxt = self.steps_done + 1
                chunk = min(spc, steps - self.steps_done)
                last_of_call = self.steps_done + chunk
                # Always sync the first call (the tick→first-step anchor
                # must be device-completed, not merely dispatched) and the
                # last (so run() returns with the device drained); between
                # them, sync whenever the call crosses a sync_every
                # boundary (counted in steps from `first`, so the cadence
                # is chunk-agnostic).
                sync = (
                    nxt == first or last_of_call >= steps
                    or (last_of_call - first + 1) // se
                    > (nxt - first) // se
                )
                s = self.step(next(batches), sync=sync, chunk=chunk)
                stats.append(s)
                if on_step is not None:
                    on_step(s)
        finally:
            if stats and stats[-1].loss is None:
                # Exited (should_stop / exception) behind async steps:
                # drain the device before teardown — never leave programs
                # in flight (chip hygiene) — and charge the drain to the
                # last step so avg_step_time_s stays honest instead of
                # averaging dispatch-only times.
                t0 = time.perf_counter()
                jax.block_until_ready(self.state)
                # step_time_s is per-step: normalize the drain by the
                # final call's chunk too.
                stats[-1].step_time_s += (
                    (time.perf_counter() - t0) / stats[-1].chunk
                )
            if prefetcher is not None:
                prefetcher.close()
        if self.checkpoint is not None:
            self.checkpoint.wait()
        return stats


__all__ = ["Trainer", "TrainConfig", "StepStats", "cross_entropy_loss"]
