"""Synthetic data pipelines for the benchmark/acceptance workloads.

Deterministic host-side numpy generation (seeded per workload), shaped like
the real datasets (MNIST images, ImageNet crops, tokenized text). Synthetic
data keeps ``bench.py`` hermetic — the metric under test is the scheduling
and training machinery, not dataset IO — matching how the reference's CI
exercises jobs without real training (SURVEY.md §4: jobs are created and
listed but never run).
"""

from __future__ import annotations

from typing import Dict, Iterator

import numpy as np


def mnist_batches(batch_size: int, seed: int = 0) -> Iterator[Dict[str, np.ndarray]]:
    """28×28 grayscale images, 10 classes."""
    rng = np.random.default_rng(seed)
    while True:
        yield {
            "x": rng.standard_normal((batch_size, 28, 28, 1), dtype=np.float32),
            "y": rng.integers(0, 10, size=(batch_size,), dtype=np.int32),
        }


def imagenet_batches(
    batch_size: int, image_size: int = 224, num_classes: int = 1000,
    seed: int = 0,
) -> Iterator[Dict[str, np.ndarray]]:
    """NHWC float images, ImageNet-shaped."""
    rng = np.random.default_rng(seed)
    while True:
        yield {
            "x": rng.standard_normal(
                (batch_size, image_size, image_size, 3), dtype=np.float32
            ),
            "y": rng.integers(0, num_classes, size=(batch_size,), dtype=np.int32),
        }


def token_batches(
    batch_size: int, seq_len: int, vocab_size: int, seed: int = 0
) -> Iterator[Dict[str, np.ndarray]]:
    """Token-id sequences with MLM-style targets (predict every position)."""
    rng = np.random.default_rng(seed)
    while True:
        ids = rng.integers(0, vocab_size, size=(batch_size, seq_len),
                           dtype=np.int32)
        yield {"x": ids, "y": ids}


def causal_token_batches(
    batch_size: int, seq_len: int, vocab_size: int, seed: int = 0
) -> Iterator[Dict[str, np.ndarray]]:
    """Next-token pairs for causal LMs: draw ``seq_len + 1`` tokens and
    shift — ``y[t] = x[t + 1]`` — so the objective is actual next-token
    prediction, not the copy task causal attention can read off directly."""
    rng = np.random.default_rng(seed)
    while True:
        ids = rng.integers(0, vocab_size, size=(batch_size, seq_len + 1),
                           dtype=np.int32)
        yield {"x": ids[:, :-1], "y": ids[:, 1:]}


__all__ = ["mnist_batches", "imagenet_batches", "token_batches",
           "causal_token_batches"]
