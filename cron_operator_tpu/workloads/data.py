"""Synthetic data pipelines for the benchmark/acceptance workloads.

Deterministic host-side numpy generation (seeded per workload), shaped like
the real datasets (MNIST images, ImageNet crops, tokenized text). Synthetic
data keeps ``bench.py`` hermetic — the metric under test is the scheduling
and training machinery, not dataset IO — matching how the reference's CI
exercises jobs without real training (SURVEY.md §4: jobs are created and
listed but never run).
"""

from __future__ import annotations

from typing import Dict, Iterator

import numpy as np


def mnist_batches(batch_size: int, seed: int = 0) -> Iterator[Dict[str, np.ndarray]]:
    """28×28 grayscale images, 10 classes."""
    rng = np.random.default_rng(seed)
    while True:
        yield {
            "x": rng.standard_normal((batch_size, 28, 28, 1), dtype=np.float32),
            "y": rng.integers(0, 10, size=(batch_size,), dtype=np.int32),
        }


def imagenet_batches(
    batch_size: int, image_size: int = 224, num_classes: int = 1000,
    seed: int = 0,
) -> Iterator[Dict[str, np.ndarray]]:
    """NHWC float images, ImageNet-shaped."""
    rng = np.random.default_rng(seed)
    while True:
        yield {
            "x": rng.standard_normal(
                (batch_size, image_size, image_size, 3), dtype=np.float32
            ),
            "y": rng.integers(0, num_classes, size=(batch_size,), dtype=np.int32),
        }


def token_batches(
    batch_size: int, seq_len: int, vocab_size: int, seed: int = 0
) -> Iterator[Dict[str, np.ndarray]]:
    """Token-id sequences with MLM-style targets (predict every position)."""
    rng = np.random.default_rng(seed)
    while True:
        ids = rng.integers(0, vocab_size, size=(batch_size, seq_len),
                           dtype=np.int32)
        yield {"x": ids, "y": ids}


def causal_token_batches(
    batch_size: int, seq_len: int, vocab_size: int, seed: int = 0
) -> Iterator[Dict[str, np.ndarray]]:
    """Next-token pairs for causal LMs: draw ``seq_len + 1`` tokens and
    shift — ``y[t] = x[t + 1]`` — so the objective is actual next-token
    prediction, not the copy task causal attention can read off directly."""
    rng = np.random.default_rng(seed)
    while True:
        ids = rng.integers(0, vocab_size, size=(batch_size, seq_len + 1),
                           dtype=np.int32)
        yield {"x": ids[:, :-1], "y": ids[:, 1:]}


class Prefetcher:
    """Background batch placement: overlap host→device transfer with
    compute.

    ``Trainer.step`` used to build + ``device_put`` each batch on the
    critical path; with a prefetcher the NEXT batch is already placed
    (sharded onto the mesh) while the current step runs — the standard
    double-buffering that hides input latency behind the device. The
    ``place`` callable is ``Trainer.put_batch`` (device placement happens
    on this thread); ``depth`` bounds device memory spent on staged
    batches.

    Must be :meth:`close`'d (Trainer does, in ``run``'s finally) — the
    producer thread of an infinite generator would otherwise park forever
    per job in a long-lived executor process.
    """

    _DONE = object()

    def __init__(self, batches, place, depth: int = 2):
        import queue as _queue
        import threading as _threading

        self._q: "_queue.Queue" = _queue.Queue(maxsize=max(1, depth))
        self._stop = _threading.Event()
        self._exc: Exception | None = None
        self._finished = False  # terminal: next() keeps raising StopIteration
        self._batches = batches
        self._place = place
        self._thread = _threading.Thread(
            target=self._fill, name="batch-prefetch", daemon=True
        )
        self._thread.start()

    def _fill(self) -> None:
        import queue as _queue

        def offer(item) -> bool:
            while not self._stop.is_set():
                try:
                    self._q.put(item, timeout=0.1)
                    return True
                except _queue.Full:
                    continue
            return False

        try:
            for batch in self._batches:
                if not offer(self._place(batch)):
                    return
                if self._stop.is_set():
                    return
        except Exception as exc:  # noqa: BLE001 — re-raised on the consumer
            self._exc = exc
        offer(self._DONE)

    def __iter__(self):
        return self

    def __next__(self):
        if self._finished:
            # Iterator protocol: repeated next() after exhaustion (or
            # after close()) must keep raising, never park on q.get()
            # waiting for a producer that already exited.
            raise StopIteration
        item = self._q.get()
        if item is self._DONE:
            self._finished = True
            if self._exc is not None:
                exc, self._exc = self._exc, None
                raise exc
            raise StopIteration
        return item

    def close(self) -> None:
        self._stop.set()
        self._finished = True
        # Unblock a producer parked on a full queue.
        try:
            while True:
                self._q.get_nowait()
        except Exception:
            pass
        self._thread.join(timeout=5.0)


__all__ = ["mnist_batches", "imagenet_batches", "token_batches",
           "causal_token_batches", "Prefetcher"]
