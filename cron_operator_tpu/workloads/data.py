"""Synthetic data pipelines for the benchmark/acceptance workloads.

Deterministic host-side numpy generation (seeded per workload), shaped like
the real datasets (MNIST images, ImageNet crops, tokenized text), plus
``device_*`` variants that generate the same shapes on-device via jitted
PRNG programs (see :func:`device_batches`). Synthetic data keeps
``bench.py`` hermetic — the metric under test is the scheduling and
training machinery, not dataset IO — matching how the reference's CI
exercises jobs without real training (SURVEY.md §4: jobs are created and
listed but never run).
"""

from __future__ import annotations

from typing import Dict, Iterator

import numpy as np

from cron_operator_tpu.parallel.overlap import DoubleBuffer


def mnist_batches(batch_size: int, seed: int = 0) -> Iterator[Dict[str, np.ndarray]]:
    """28×28 grayscale images, 10 classes."""
    rng = np.random.default_rng(seed)
    while True:
        yield {
            "x": rng.standard_normal((batch_size, 28, 28, 1), dtype=np.float32),
            "y": rng.integers(0, 10, size=(batch_size,), dtype=np.int32),
        }


def imagenet_batches(
    batch_size: int, image_size: int = 224, num_classes: int = 1000,
    seed: int = 0,
) -> Iterator[Dict[str, np.ndarray]]:
    """NHWC float images, ImageNet-shaped."""
    rng = np.random.default_rng(seed)
    while True:
        yield {
            "x": rng.standard_normal(
                (batch_size, image_size, image_size, 3), dtype=np.float32
            ),
            "y": rng.integers(0, num_classes, size=(batch_size,), dtype=np.int32),
        }


def token_batches(
    batch_size: int, seq_len: int, vocab_size: int, seed: int = 0
) -> Iterator[Dict[str, np.ndarray]]:
    """Token-id sequences with MLM-style targets (predict every position)."""
    rng = np.random.default_rng(seed)
    while True:
        ids = rng.integers(0, vocab_size, size=(batch_size, seq_len),
                           dtype=np.int32)
        yield {"x": ids, "y": ids}


def causal_token_batches(
    batch_size: int, seq_len: int, vocab_size: int, seed: int = 0
) -> Iterator[Dict[str, np.ndarray]]:
    """Next-token pairs for causal LMs: draw ``seq_len + 1`` tokens and
    shift — ``y[t] = x[t + 1]`` — so the objective is actual next-token
    prediction, not the copy task causal attention can read off directly."""
    rng = np.random.default_rng(seed)
    while True:
        ids = rng.integers(0, vocab_size, size=(batch_size, seq_len + 1),
                           dtype=np.int32)
        yield {"x": ids[:, :-1], "y": ids[:, 1:]}


def mnist_sample(batch_size: int):
    """``key → batch`` for MNIST shapes — the jitted-PRNG sample fn shared
    by :func:`device_batches` (own-program-per-batch) and the Trainer's
    FUSED mode (generation inlined into the train step: zero per-step
    host→device traffic, see ``train.Trainer(sample_fn=...)``)."""
    import jax
    import jax.numpy as jnp

    def sample(key):
        kx, ky = jax.random.split(key)
        return {
            "x": jax.random.normal(kx, (batch_size, 28, 28, 1), jnp.float32),
            "y": jax.random.randint(ky, (batch_size,), 0, 10,
                                    dtype=jnp.int32),
        }

    return sample


def imagenet_sample(batch_size: int, image_size: int = 224,
                    num_classes: int = 1000):
    """``key → batch`` for ImageNet shapes (see :func:`mnist_sample`)."""
    import jax
    import jax.numpy as jnp

    def sample(key):
        kx, ky = jax.random.split(key)
        return {
            "x": jax.random.normal(
                kx, (batch_size, image_size, image_size, 3), jnp.float32
            ),
            "y": jax.random.randint(
                ky, (batch_size,), 0, num_classes, dtype=jnp.int32
            ),
        }

    return sample


def token_sample(batch_size: int, seq_len: int, vocab_size: int):
    """``key → batch`` of MLM-style token batches (see
    :func:`mnist_sample`)."""
    import jax
    import jax.numpy as jnp

    def sample(key):
        ids = jax.random.randint(
            key, (batch_size, seq_len), 0, vocab_size, dtype=jnp.int32
        )
        return {"x": ids, "y": ids}

    return sample


def causal_token_sample(batch_size: int, seq_len: int, vocab_size: int):
    """``key → batch`` of shifted next-token pairs (see
    :func:`mnist_sample`)."""
    import jax
    import jax.numpy as jnp

    def sample(key):
        ids = jax.random.randint(
            key, (batch_size, seq_len + 1), 0, vocab_size, dtype=jnp.int32
        )
        return {"x": ids[:, :-1], "y": ids[:, 1:]}

    return sample


def device_batches(sample_fn, shardings=None, seed: int = 0):
    """Synthetic batches generated ON the device by a jitted PRNG program.

    The host variants above ship ~tens of MB of numpy per step over
    host→device DMA — on a tunneled/remote device that transfer dominates
    the step (observed: ~3 s/step for ResNet-50@64×224² against a ~50 ms
    compute step). Device generation moves the per-step host traffic down
    to one folded PRNG key: ``sample_fn(key) -> {"x": ..., "y": ...}``
    runs as its own compiled program, placed directly into the training
    sharding (``shardings`` = ``Trainer.batch_sharding``), so the train
    step consumes device-resident buffers with no host round-trip. This is
    also the TPU-idiomatic shape for hermetic benchmarking: the metric is
    the training machinery, never dataset IO.
    """
    import jax

    gen = (
        jax.jit(sample_fn, out_shardings=shardings)
        if shardings is not None
        else jax.jit(sample_fn)
    )
    key = jax.random.PRNGKey(seed)
    i = 0
    while True:
        yield gen(jax.random.fold_in(key, i))
        i += 1


def device_mnist_batches(batch_size: int, seed: int = 0, shardings=None):
    return device_batches(mnist_sample(batch_size), shardings, seed)


def device_imagenet_batches(
    batch_size: int, image_size: int = 224, num_classes: int = 1000,
    seed: int = 0, shardings=None,
):
    return device_batches(
        imagenet_sample(batch_size, image_size, num_classes), shardings, seed
    )


def device_token_batches(
    batch_size: int, seq_len: int, vocab_size: int, seed: int = 0,
    shardings=None,
):
    return device_batches(
        token_sample(batch_size, seq_len, vocab_size), shardings, seed
    )


def device_causal_token_batches(
    batch_size: int, seq_len: int, vocab_size: int, seed: int = 0,
    shardings=None,
):
    return device_batches(
        causal_token_sample(batch_size, seq_len, vocab_size), shardings, seed
    )


class Prefetcher(DoubleBuffer):
    """Background batch placement: overlap host→device transfer with
    compute.

    ``Trainer.step`` used to build + ``device_put`` each batch on the
    critical path; with a prefetcher the NEXT batch is already placed
    (sharded onto the mesh) while the current step runs — the standard
    double-buffering that hides input latency behind the device. The
    ``place`` callable is ``Trainer.put_batch`` (device placement happens
    on this thread); ``depth`` bounds device memory spent on staged
    batches.

    Must be :meth:`close`'d (Trainer does, in ``run``'s finally) — the
    producer thread of an infinite generator would otherwise park forever
    per job in a long-lived executor process. The engine (bounded queue,
    producer thread, exception propagation, terminal-StopIteration close
    semantics) is :class:`parallel.overlap.DoubleBuffer`.
    """

    def __init__(self, batches, place, depth: int = 2):
        super().__init__(batches, place, depth, name="batch-prefetch")


def grouped(batches: Iterator[Dict[str, np.ndarray]], schedule) -> Iterator[list]:
    """Group a batch stream into lists sized by ``schedule`` (an iterable
    of chunk lengths, e.g. :func:`parallel.overlap.chunk_schedule`). A
    stream that exhausts mid-group yields the partial group and stops —
    the consumer trains what exists rather than dropping staged work."""
    it = iter(batches)
    for k in schedule:
        group = []
        # Explicit catch: inside a generator an escaping StopIteration
        # from next() is a RuntimeError (PEP 479), not normal exhaustion.
        try:
            for _ in range(max(1, k)):
                group.append(next(it))
        except StopIteration:
            if group:
                yield group
            return
        yield group


class ChunkStager(DoubleBuffer):
    """Background CHUNK staging for scan-chained dispatch: groups the
    batch stream into ``schedule``-sized chunks and runs ``place_chunk``
    (``Trainer.put_chunk`` — stack along a leading step axis + one
    sharded ``device_put``) on a producer thread, so chunk N+1 is built,
    stacked and device-resident while chunk N's K steps run in a single
    dispatched scan. ``depth`` bounds staged-ahead chunks (2 = classic
    double buffering); memory cost is ``depth × K`` batches."""

    def __init__(self, batches, schedule, place_chunk, depth: int = 2):
        super().__init__(
            grouped(batches, schedule), place_chunk, depth,
            name="chunk-stager",
        )


__all__ = ["mnist_batches", "imagenet_batches", "token_batches",
           "causal_token_batches", "mnist_sample", "imagenet_sample",
           "token_sample", "causal_token_sample", "device_batches",
           "device_mnist_batches", "device_imagenet_batches",
           "device_token_batches", "device_causal_token_batches",
           "Prefetcher", "ChunkStager", "grouped"]
