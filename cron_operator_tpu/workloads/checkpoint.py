"""Workload checkpoint/resume via Orbax.

The recovery half of the preemption story (SURVEY.md §5: "TPU preemption is
the big new case... job-level restartPolicy + JAX in-workload checkpoint
restore do the rest"; reference has NO model checkpointing — operator-level
state is only ``status.lastScheduleTime`` in etcd). Flow:

- the Trainer saves its full TrainState (params + optimizer state + step)
  every ``save_every`` steps through an Orbax CheckpointManager;
- after a slice preemption the executor re-admits the job
  (``backends/local.py`` Restarting path) or the training-operator restarts
  the pods; the entrypoint's Trainer restores the latest step and continues
  — steps already done are not repeated;
- checkpoints are sharding-aware: Orbax restores directly into the mesh
  layout the Trainer hands it (no host-side gather), which is what makes
  this viable for FSDP-sharded states on real slices;
- checkpoints are parallelism-INDEPENDENT (the Tenplex model): ``restore``
  accepts a template on a *different* mesh than the save — a job preempted
  on 8 chips resumes on the 4 that survive. The fast path reads shards
  straight into the new ``NamedSharding`` layout; if the saved layout
  can't be mapped directly, the fallback loads host-side and reshards
  leaf-by-leaf (:meth:`CheckpointStore.restore_resharded`).

Durability: every open store registers itself so :func:`flush_open_stores`
can drain in-flight async saves at preemption/SIGTERM time — the executor's
preempt path calls it before pod teardown, so the job loses at most one
checkpoint *interval*, never a completed ``save()``.

Directory convention: ``<root>/<namespace>/<lineage>``. Default lineage is
the FULL job name — preemption restarts re-run the same job name, so they
find their own checkpoints, while concurrent ticks (Allow/Replace) get
distinct directories and can never collide. Opt-in ``lineage="family"``
strips the per-tick unix suffix so successive Forbid ticks continue one
long training run (each tick resumes where the last stopped; once the
step target is reached further ticks are no-ops by design).
"""

from __future__ import annotations

import logging
import os
import re
import threading
import weakref
from typing import Any, Optional

logger = logging.getLogger("workloads.checkpoint")

# Every open store, so preempt/SIGTERM paths can drain async saves without
# holding a reference to the entrypoint's store (weak: a store that was
# garbage-collected has nothing in flight worth flushing).
_OPEN_LOCK = threading.Lock()
_OPEN_STORES: "weakref.WeakSet[CheckpointStore]" = weakref.WeakSet()

DEFAULT_ROOT = os.environ.get("TPU_CHECKPOINT_DIR", "/tmp/cron-operator-tpu/ckpt")

_TICK_SUFFIX = re.compile(r"-\d{9,11}$")  # "<cron>-<unixTs>" → "<cron>"


def job_family(name: str) -> str:
    """Strip the per-tick unix-timestamp suffix from a deterministic job
    name so successive runs share a checkpoint lineage."""
    return _TICK_SUFFIX.sub("", name) or name


class CheckpointStore:
    """Thin Orbax CheckpointManager wrapper bound to one job family."""

    def __init__(
        self,
        namespace: str,
        job_name: str,
        root: Optional[str] = None,
        max_to_keep: int = 3,
        lineage: str = "job",  # "job" | "family" — see module docstring
        create: bool = True,  # False = read-only open (serving): a
        # mistyped lineage must raise, not litter the shared checkpoint
        # root with empty directories
    ):
        import orbax.checkpoint as ocp

        if lineage not in ("job", "family"):
            raise ValueError(f"unknown checkpoint lineage {lineage!r}")
        key = job_family(job_name) if lineage == "family" else job_name
        self.directory = os.path.join(root or DEFAULT_ROOT, namespace, key)
        if create:
            os.makedirs(self.directory, exist_ok=True)
        elif not os.path.isdir(self.directory):
            raise FileNotFoundError(
                f"no checkpoint lineage at {self.directory}"
            )
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=create
            ),
        )
        self.namespace = namespace
        self.job_name = job_name
        #: Restores served from an older retained step after the newest
        #: one failed verification (truncated/corrupt on disk).
        self.fallbacks = 0
        self._metrics: Optional[Any] = None
        with _OPEN_LOCK:
            _OPEN_STORES.add(self)

    def instrument(self, metrics: Any) -> None:
        """Attach a metrics sink (``.inc(series)``) for fallback counts."""
        self._metrics = metrics

    def _count(self, series: str, value: int = 1) -> None:
        if self._metrics is not None:
            try:
                self._metrics.inc(series, value)
            except Exception:  # pragma: no cover - sink must never break IO
                logger.debug("metrics sink failed for %s", series)

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def all_steps(self) -> list:
        """Retained steps, oldest first."""
        return sorted(self._mgr.all_steps())

    def save(self, step: int, state: Any) -> None:
        import orbax.checkpoint as ocp

        self._mgr.save(step, args=ocp.args.StandardSave(state))

    def restore(self, step: int, like: Any) -> Any:
        """Restore ``step`` into the sharding/structure of ``like`` (an
        abstract or concrete TrainState pytree).

        ``like`` may live on a different mesh than the save — including a
        mesh with FEWER devices (elastic resume after preemption). Orbax
        reads the saved shards directly into ``like``'s ``NamedSharding``
        layout when it can; when the direct read fails (a layout it can't
        map), we fall back to :meth:`restore_resharded`.
        """
        import orbax.checkpoint as ocp

        try:
            return self._mgr.restore(
                step, args=ocp.args.StandardRestore(like)
            )
        except Exception:
            logger.warning(
                "direct sharded restore of step %s failed; resharding "
                "host-side", step, exc_info=True,
            )
            return self.restore_resharded(step, like)

    def restore_latest(self, like: Any) -> Any:
        """Restore the newest step that actually restores — the integrity
        fallback chain for the resume path.

        An async save torn by a preemption (or a disk fault under the
        checkpoint root) can leave the NEWEST retained step unreadable
        while older steps are intact; ``max_to_keep`` retains several
        precisely so resume never depends on a single on-disk artifact.
        Walk ``all_steps()`` newest→oldest: each candidate goes through
        :meth:`restore` (direct sharded read, then the host-side reshard
        fallback); the first success wins. Every skipped step counts a
        ``workload_checkpoint_fallbacks_total`` so a job that silently
        resumed N intervals back is visible on /metrics.

        Returns ``(step, state)``; raises ``FileNotFoundError`` when no
        steps exist and the last restore error when every step fails.
        """
        steps = self.all_steps()
        if not steps:
            raise FileNotFoundError(
                f"no checkpoint found under {self.directory}"
            )
        last_err: Optional[BaseException] = None
        for step in reversed(steps):
            try:
                return step, self.restore(step, like)
            except Exception as err:
                last_err = err
                self.fallbacks += 1
                self._count("workload_checkpoint_fallbacks_total")
                logger.warning(
                    "checkpoint step %s unreadable (%s); falling back to "
                    "an older retained step", step, err,
                )
        raise last_err  # type: ignore[misc]  # loop ran at least once

    def _restore_raw(self, step: int) -> Any:
        """Template-free restore: the checkpoint as saved (nested dicts of
        arrays in the save-time layout). The explicit empty
        ``StandardRestore`` matters — a freshly opened manager that has
        never saved has no handler registered for the item, and a bare
        ``restore(step)`` raises KeyError instead of reading it."""
        import orbax.checkpoint as ocp

        return self._mgr.restore(step, args=ocp.args.StandardRestore())

    def restore_resharded(self, step: int, like: Any) -> Any:
        """Cross-mesh restore via the host: load the checkpoint
        template-free (plain arrays in the save-time layout), then
        ``device_put`` each leaf into ``like``'s sharding. This is the
        Tenplex reconfiguration plan restricted to our save format — the
        checkpoint is treated as a parallelism-independent tensor
        collection keyed by tree path, so any source layout maps onto any
        target mesh whose shardings ``like`` declares."""
        import jax
        import numpy as np

        raw = self._restore_raw(step)  # save-time layout, host-addressable
        leaves = jax.tree_util.tree_flatten_with_path(like)[0]
        out = []
        for path, leaf in leaves:
            host = np.asarray(_lookup_by_path(raw, path))
            sharding = getattr(leaf, "sharding", None)
            out.append(
                jax.device_put(host, sharding) if sharding is not None
                else jax.numpy.asarray(host)
            )
        return jax.tree_util.tree_unflatten(
            jax.tree_util.tree_structure(like), out
        )

    def restore_params(self, step: Optional[int] = None) -> Any:
        """Params-only restore for SERVING — no optimizer-state template
        needed (the training job's optimizer config is unknown to a
        serving job). Template-free restore yields the checkpoint as
        plain nested dicts, from which the ``params`` subtree is
        returned (host arrays; the consumer device_puts into its own
        layout). For sharded multi-host serving a proper template
        restore would be required; this is the single-host path the
        ``generate`` entrypoint uses."""
        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(
                f"no checkpoint found under {self.directory}"
            )
        raw = self._restore_raw(step)
        return raw["params"]

    def wait(self) -> None:
        """Block until every async save issued so far is durable on disk."""
        self._mgr.wait_until_finished()

    def close(self) -> None:
        """Flush the async save pipeline, then release the manager.

        The flush-then-close order is the durability guarantee: a job torn
        down between ``save()`` and the writer-thread drain keeps its final
        step as long as ``close()`` (or :func:`flush_open_stores`) runs
        first."""
        try:
            self._mgr.wait_until_finished()
            self._mgr.close()
        except Exception:
            logger.warning("checkpoint manager close failed", exc_info=True)
        finally:
            with _OPEN_LOCK:
                _OPEN_STORES.discard(self)


def _lookup_by_path(raw: Any, path: Any) -> Any:
    """Walk a template-free Orbax restore (nested dict/list containers) by
    a jax keypath from the typed template — dataclass fields, dict keys and
    sequence indices all appear as string keys or indices in the raw
    tree."""
    node = raw
    for entry in path:
        if hasattr(entry, "key"):
            name = entry.key
        elif hasattr(entry, "name"):
            name = entry.name
        elif hasattr(entry, "idx"):
            name = entry.idx
        else:  # pragma: no cover - future keypath kinds
            name = str(entry)
        if isinstance(node, dict):
            node = node[name] if name in node else node[str(name)]
        elif isinstance(node, (list, tuple)):
            node = node[int(name)]
        else:
            node = getattr(node, str(name))
    return node


def flush_open_stores(
    namespace: Optional[str] = None, job_name: Optional[str] = None
) -> int:
    """Drain the async save pipeline of every open store, optionally
    filtered to one namespace and/or job. The executor's preempt path calls
    this before pod teardown (and SIGTERM handling may too) so the last
    ``save()`` is durable before the job dies; returns how many stores were
    flushed."""
    with _OPEN_LOCK:
        stores = [
            s for s in list(_OPEN_STORES)
            if (namespace is None or s.namespace == namespace)
            and (job_name is None or s.job_name == job_name)
        ]
    flushed = 0
    for store in stores:
        try:
            store.wait()
            flushed += 1
        except Exception:
            logger.warning(
                "checkpoint flush failed for %s", store.directory,
                exc_info=True,
            )
    return flushed


__all__ = [
    "CheckpointStore",
    "flush_open_stores",
    "job_family",
    "DEFAULT_ROOT",
]
