"""Workload checkpoint/resume via Orbax.

The recovery half of the preemption story (SURVEY.md §5: "TPU preemption is
the big new case... job-level restartPolicy + JAX in-workload checkpoint
restore do the rest"; reference has NO model checkpointing — operator-level
state is only ``status.lastScheduleTime`` in etcd). Flow:

- the Trainer saves its full TrainState (params + optimizer state + step)
  every ``save_every`` steps through an Orbax CheckpointManager;
- after a slice preemption the executor re-admits the job
  (``backends/local.py`` Restarting path) or the training-operator restarts
  the pods; the entrypoint's Trainer restores the latest step and continues
  — steps already done are not repeated;
- checkpoints are sharding-aware: Orbax restores directly into the mesh
  layout the Trainer hands it (no host-side gather), which is what makes
  this viable for FSDP-sharded states on real slices.

Directory convention: ``<root>/<namespace>/<lineage>``. Default lineage is
the FULL job name — preemption restarts re-run the same job name, so they
find their own checkpoints, while concurrent ticks (Allow/Replace) get
distinct directories and can never collide. Opt-in ``lineage="family"``
strips the per-tick unix suffix so successive Forbid ticks continue one
long training run (each tick resumes where the last stopped; once the
step target is reached further ticks are no-ops by design).
"""

from __future__ import annotations

import logging
import os
import re
from typing import Any, Optional

logger = logging.getLogger("workloads.checkpoint")

DEFAULT_ROOT = os.environ.get("TPU_CHECKPOINT_DIR", "/tmp/cron-operator-tpu/ckpt")

_TICK_SUFFIX = re.compile(r"-\d{9,11}$")  # "<cron>-<unixTs>" → "<cron>"


def job_family(name: str) -> str:
    """Strip the per-tick unix-timestamp suffix from a deterministic job
    name so successive runs share a checkpoint lineage."""
    return _TICK_SUFFIX.sub("", name) or name


class CheckpointStore:
    """Thin Orbax CheckpointManager wrapper bound to one job family."""

    def __init__(
        self,
        namespace: str,
        job_name: str,
        root: Optional[str] = None,
        max_to_keep: int = 3,
        lineage: str = "job",  # "job" | "family" — see module docstring
        create: bool = True,  # False = read-only open (serving): a
        # mistyped lineage must raise, not litter the shared checkpoint
        # root with empty directories
    ):
        import orbax.checkpoint as ocp

        if lineage not in ("job", "family"):
            raise ValueError(f"unknown checkpoint lineage {lineage!r}")
        key = job_family(job_name) if lineage == "family" else job_name
        self.directory = os.path.join(root or DEFAULT_ROOT, namespace, key)
        if create:
            os.makedirs(self.directory, exist_ok=True)
        elif not os.path.isdir(self.directory):
            raise FileNotFoundError(
                f"no checkpoint lineage at {self.directory}"
            )
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=create
            ),
        )

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def save(self, step: int, state: Any) -> None:
        import orbax.checkpoint as ocp

        self._mgr.save(step, args=ocp.args.StandardSave(state))

    def restore(self, step: int, like: Any) -> Any:
        """Restore ``step`` into the sharding/structure of ``like`` (an
        abstract or concrete TrainState pytree)."""
        import orbax.checkpoint as ocp

        return self._mgr.restore(step, args=ocp.args.StandardRestore(like))

    def restore_params(self, step: Optional[int] = None) -> Any:
        """Params-only restore for SERVING — no optimizer-state template
        needed (the training job's optimizer config is unknown to a
        serving job). Template-free restore yields the checkpoint as
        plain nested dicts, from which the ``params`` subtree is
        returned (host arrays; the consumer device_puts into its own
        layout). For sharded multi-host serving a proper template
        restore would be required; this is the single-host path the
        ``generate`` entrypoint uses."""
        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(
                f"no checkpoint found under {self.directory}"
            )
        raw = self._mgr.restore(step)
        return raw["params"]

    def wait(self) -> None:
        self._mgr.wait_until_finished()

    def close(self) -> None:
        try:
            self._mgr.wait_until_finished()
            self._mgr.close()
        except Exception:
            logger.warning("checkpoint manager close failed", exc_info=True)


__all__ = ["CheckpointStore", "job_family", "DEFAULT_ROOT"]
