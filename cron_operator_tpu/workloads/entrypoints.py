"""Standard registered entrypoints: the workloads the acceptance configs run.

Each entrypoint reads its hyperparameters from ``tpu.kubedl.io/param.*``
annotations (stripped into ``ctx.params`` by the executor), builds a mesh
over the visible devices, trains for ``steps`` steps on synthetic data, and
publishes progress into ``ctx.progress`` — the executor folds that into the
workload's ``status.trainingProgress`` so the tick→first-step north-star
metric is observable from the control plane (the reference has no analog;
its metrics stop at reconcile counts, SURVEY.md §5).

Common params (all optional, all strings): ``steps``, ``batch_size``,
``platform`` (force ``cpu`` for tests), ``tensor``/``seq``/``fsdp`` (mesh
axis sizes), ``data`` (``device`` default | ``host`` | ``fused`` — see
:func:`_batches`), ``lr``/``lr_schedule``/``warmup_steps``/
``schedule_steps``/``sync_every`` (see :func:`_train_kwargs`).
Model-specific params documented per entrypoint.

Execution mode: by default every training entrypoint runs the
overlap-aware executor — ``param.steps_per_call=auto`` scan-chains up to
8 optimizer steps per dispatched program (snapped to checkpoint
``save_every`` and the step target, bit-exact with single-step), and
``param.stage_async=1`` double-buffers external batches/chunks on a
background thread so steady-state steps stop paying host time (PERF.md
"Step speed"). ``param.steps_per_call=1`` + ``param.stage_async=0``
restores the pre-overlap synchronous loop; ``on_step`` telemetry
(``step_timeline``, rolling MFU) stays per-step in every mode.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any, Dict, Iterator, Optional

import jax

from cron_operator_tpu.backends.registry import JobContext, register_entrypoint
from cron_operator_tpu.backends.tpu import (
    ANNOTATION_ACCELERATOR,
    peak_flops_per_chip,
)
from cron_operator_tpu.models import (
    GPT,
    GPTConfig,
    MLP,
    Bert,
    BertConfig,
    ResNet50,
    ViT,
    ViTConfig,
)
from cron_operator_tpu.parallel.mesh import mesh_for_devices
from cron_operator_tpu.workloads import data as datasets
from cron_operator_tpu.workloads.train import StepStats, TrainConfig, Trainer


def _devices(ctx: JobContext):
    platform = ctx.params.get("platform")
    devs = jax.devices(platform) if platform else jax.devices()
    # param.devices caps the visible device set (first N) — the elastic
    # resume path resubmits preempted jobs with the surviving count so the
    # new mesh fits the shrunken capacity.
    want = int(ctx.params.get("devices", 0) or 0)
    if want > 0:
        if want > len(devs):
            raise ValueError(
                f"param.devices={want} but only {len(devs)} "
                f"device(s) visible"
            )
        devs = devs[:want]
    return devs


def _mesh(ctx: JobContext, devs=None):
    devs = devs if devs is not None else _devices(ctx)
    if int(ctx.params.get("pipe", 1)) > 1:
        # The standard entrypoints train one GSPMD step; none consumes a
        # pipe axis, so accepting it would silently run every pipe shard
        # redundantly. Pipeline parallelism is the spmd_pipeline primitive
        # (parallel.pipeline) for custom entrypoints that stage their
        # model.
        raise ValueError(
            "param.pipe is not supported by the standard entrypoints — "
            "pipeline parallelism requires a staged model via "
            "cron_operator_tpu.parallel.spmd_pipeline"
        )
    axes = dict(
        tensor=int(ctx.params.get("tensor", 1)),
        seq=int(ctx.params.get("seq", 1)),
        fsdp=int(ctx.params.get("fsdp", 1)),
        expert=int(ctx.params.get("expert", 1)),
    )
    slices = int(ctx.params.get("slices", 1))
    if slices > 1:
        # Multi-slice: DP over DCN, model axes within each slice's ICI.
        from cron_operator_tpu.parallel.mesh import hybrid_mesh_for_slices

        return hybrid_mesh_for_slices(slices, devices=devs, **axes)
    return mesh_for_devices(devs, **axes)


def _checkpoint_store(ctx: JobContext):
    """CheckpointStore when the job opts in via param.checkpoint=1; the
    preemption-recovery path (restart-on-preemption re-runs the entrypoint,
    which then resumes from the last saved step). param.checkpoint_lineage
    ("job" default, "family" to continue one run across Forbid ticks).
    param.checkpoint_job pins the store to another job's lineage — the
    elastic resume path sets it to the logical-run root so every resumed
    attempt reads (and keeps extending) one checkpoint chain.
    param.checkpoint_keep widens retention past the default 3 — an
    elastic run that reshards many times keeps its width-boundary steps
    auditable instead of garbage-collecting them."""
    if ctx.params.get("checkpoint", "0") not in ("1", "true", "yes"):
        return None
    from cron_operator_tpu.workloads.checkpoint import CheckpointStore

    return CheckpointStore(
        ctx.namespace or "default",
        ctx.params.get("checkpoint_job") or ctx.name,
        root=ctx.params.get("checkpoint_dir"),
        max_to_keep=int(ctx.params.get("checkpoint_keep", 3)),
        lineage=ctx.params.get("checkpoint_lineage", "job"),
    )


def _save_every(ctx: JobContext) -> int:
    return int(ctx.params.get("save_every", 10))


def _prefetch(ctx: JobContext) -> int:
    return int(ctx.params.get("prefetch", 0))


def _sync_every(ctx: JobContext) -> int:
    return int(ctx.params.get("sync_every", 1))


def _gqa_rope_kwargs(ctx: JobContext) -> dict:
    """param.kv_heads / param.rope — shared by every attention family
    (bert/gpt/vit training and the generate serving job), parsed once."""
    return {
        "num_kv_heads": int(ctx.params.get("kv_heads", 0)),
        "rope": ctx.params.get("rope", "0") in ("1", "true"),
    }


def _steps_per_call(ctx: JobContext):
    """param.steps_per_call — "auto" (the DEFAULT execution mode: the
    Trainer scan-chains min(8, save_every) optimizer steps per dispatch,
    snapped to checkpoint and target boundaries, bit-exact with 1) or an
    explicit int. A profiled run (param.profile_dir) pins it to 1: the
    profiler starts after the first dispatch, and a single fused chunk
    would leave the steady-state trace window empty."""
    raw = ctx.params.get("steps_per_call", "auto")
    if raw != "auto":
        return int(raw)
    if ctx.params.get("profile_dir"):
        return 1
    return "auto"


def _train_kwargs(ctx: JobContext, steps: int, **defaults) -> dict:
    """TrainConfig kwargs shared by every entrypoint: per-entrypoint
    defaults overridden by the common ``param.*`` surface — ``lr``,
    ``lr_schedule`` (constant|cosine|warmup_cosine), ``warmup_steps``,
    ``schedule_steps`` (defaults to the run's total-step target),
    ``grad_clip`` (global-norm clip, 0=off), ``decay_mask`` (AdamW decay
    only on rank≥2 params), ``save_every``, ``prefetch``,
    ``sync_every``, ``steps_per_call`` (="auto": scan-chained dispatch,
    the default execution mode), ``stage_async`` (="1": background
    double-buffered staging of external batches/chunks)."""
    kw = dict(defaults)
    kw.update(
        save_every=_save_every(ctx),
        prefetch=_prefetch(ctx),
        sync_every=_sync_every(ctx),
        # K optimizer steps per dispatched program — the host-roundtrip
        # amortizer; "auto" by default (scan-chained execution).
        steps_per_call=_steps_per_call(ctx),
        stage_async=ctx.params.get("stage_async", "1") in ("1", "true"),
        lr_schedule=ctx.params.get("lr_schedule", "constant"),
        warmup_steps=int(ctx.params.get("warmup_steps", 0)),
        schedule_steps=int(ctx.params.get("schedule_steps", steps)),
        grad_clip_norm=float(ctx.params.get("grad_clip", 0)),
        decay_mask=ctx.params.get("decay_mask", "0") in ("1", "true"),
    )
    if "lr" in ctx.params:
        kw["learning_rate"] = float(ctx.params["lr"])
    return kw


def _fused(ctx: JobContext) -> bool:
    return ctx.params.get("data", "device") == "fused"


def _batches(ctx: JobContext, trainer: Trainer, host_factory, device_factory):
    """``param.data`` selects where synthetic batches materialize:
    ``device`` (default) generates them on-device via a jitted PRNG program
    placed straight into the training sharding — per-step host traffic is
    one folded key instead of the whole batch (decisive on remote/tunneled
    devices); ``host`` keeps the numpy path (composes with
    ``param.prefetch`` to overlap the host→device transfer); ``fused``
    moves generation INSIDE the jitted train step (Trainer ``sample_fn``
    — one dispatch per step, zero per-step host traffic; the
    hermetic-benchmark mode, see PERF.md finding 3)."""
    mode = ctx.params.get("data", "device")
    if mode == "host":
        return host_factory()
    if mode == "fused":
        from itertools import repeat

        return repeat({})
    return device_factory(shardings=trainer.batch_sharding)


def _jit_init(model, rng, x):
    """``model.init`` under jit: eager init dispatches every conv/norm op
    separately (tens of seconds for ResNet-50 on a cold process); one
    compiled program is both faster and persistent-cacheable, which is how
    the tick→first-step path stays inside the 90 s budget."""
    return jax.jit(model.init)(rng, x)["params"]


def _run(
    ctx: JobContext,
    trainer: Trainer,
    batches: Iterator[Dict[str, Any]],
    steps: int,
    tokens_per_step: Optional[int] = None,
) -> None:
    """Drive ``trainer`` and stream progress telemetry through the ctx.

    ``tokens_per_step`` (token workloads: batch_size × seq_len) turns the
    step-time window into a live ``tokens_per_s`` throughput record; the
    executor forwards it into the operator registry as the
    ``workload_tokens_per_s`` gauge.
    """
    ctx.progress["started_at"] = time.time()
    # Execution-mode telemetry: the resolved scan-chain length and where
    # batches materialize — what the workload_steps_per_call gauge and a
    # perf triage read to see which mode a run actually trained under.
    ctx.progress["steps_per_call"] = trainer.resolved_steps_per_call
    ctx.progress["data_mode"] = ctx.params.get("data", "device")
    # Monotonic anchor for same-process latency deltas: the wall-clock
    # started_at/first_step_at pair stays for cross-process alignment,
    # but a wall jump (NTP slew) between them must not distort the
    # first_step phase histogram.
    started_mono = time.monotonic()
    if trainer.steps_done:
        ctx.progress["resumed_from_step"] = trainer.steps_done
        # The restored steps are DONE (they travel in state.step), so
        # publish them up front: a resume that restores at or past the
        # target is a no-op run and would otherwise report no progress
        # at all.
        ctx.progress["steps_done"] = trainer.steps_done
    last_publish = [0.0]
    # param.step_delay_s paces the loop (chaos/CI knob: synthetic steps on
    # host CPU finish in microseconds, far inside the publish throttle —
    # a paced job stays observably in flight long enough to be preempted
    # mid-run instead of racing to Succeeded).
    step_delay_s = float(ctx.params.get("step_delay_s", 0) or 0)
    # Optional profiling (SURVEY.md §5 "tracing/profiling: none in the
    # reference"): param.profile_dir=<path> captures a jax.profiler trace
    # of the steady-state steps (started after the compile-laden first
    # step) — the TensorBoard/XProf artifact for TPU perf work.
    profile_dir = ctx.params.get("profile_dir")
    profiling = [False]
    window = [0.0, 0]  # wall time and step count since the last synced step
    # Bounded per-run profiler timeline: one entry per dispatch with the
    # phase breakdown Trainer.step measured (data / host dispatch /
    # device sync / checkpoint stall). The newest param.timeline_steps
    # (=64) entries ride in trainingProgress; longer history belongs to
    # the /debug/timeline store.
    timeline: deque = deque(
        maxlen=max(1, int(ctx.params.get("timeline_steps", 64) or 64))
    )
    # Rolling MFU estimator (ROADMAP item 5). Opt-in via param.mfu=1:
    # the FLOPs numerator (Trainer.flops_per_step) re-lowers and
    # re-compiles the step once, at the first synced step. Denominator:
    # peak per-chip FLOPs from the slice's accelerator family — the
    # numerator is a per-device post-partitioning count, so the ratio
    # needs no device-count factor. param.peak_flops_per_chip overrides
    # for CPU/bench runs where no TPU family applies.
    mfu_flops = [None]  # type: list
    mfu_on = str(ctx.params.get("mfu", "0")).lower() in ("1", "true")
    peak_per_chip: Optional[float] = None
    if mfu_on:
        try:
            if ctx.params.get("peak_flops_per_chip"):
                peak_per_chip = float(ctx.params["peak_flops_per_chip"])
            else:
                spec = getattr(ctx, "slice_spec", None)
                accel = spec.accelerator if spec is not None else (
                    (ctx.job.get("metadata") or {}).get("annotations") or {}
                ).get(ANNOTATION_ACCELERATOR, "")
                peak_per_chip = peak_flops_per_chip(accel)
        except (TypeError, ValueError):
            peak_per_chip = None

    def _mfu(step_avg_s: float) -> Optional[float]:
        if not (mfu_on and peak_per_chip and step_avg_s > 0):
            return None
        if mfu_flops[0] is None:
            mfu_flops[0] = trainer.flops_per_step() or 0.0
        if not mfu_flops[0]:
            return None
        return round(mfu_flops[0] / (step_avg_s * peak_per_chip), 4)

    def on_step(s: StepStats) -> None:
        # Key-presence, not step equality: with steps_per_call > 1 the
        # first CALL completes several steps at once.
        first_call = "first_step_at" not in ctx.progress
        if first_call:
            # The north-star timestamp: first optimizer step finished
            # (device-synced — Trainer.step blocks on the loss).
            ctx.progress["first_step_at"] = time.time()
            ctx.progress["first_step_latency_s"] = round(
                time.monotonic() - started_mono, 6
            )
            if trainer.first_dispatch_time_s is not None:
                # The compile component of tick→first-step (the first
                # dispatch traces + XLA-compiles before executing).
                ctx.progress["compile_time_s"] = round(
                    trainer.first_dispatch_time_s, 4
                )
            if profile_dir:
                # The jax profiler is process-global; under thread
                # isolation a concurrent profiled job would raise
                # "already active". A diagnostic must never fail the
                # training run — skip and say so instead.
                try:
                    jax.profiler.start_trace(profile_dir)
                    profiling[0] = True
                    ctx.progress["profile_dir"] = profile_dir
                except Exception as exc:  # noqa: BLE001
                    ctx.progress["profile_error"] = str(exc)
        ctx.progress["steps_done"] = s.step
        timeline.append({
            "step": s.step,
            "t": round(time.monotonic() - started_mono, 4),
            "step_s": round(s.step_time_s, 6),
            "data_s": round(s.data_s, 6),
            "dispatch_s": round(s.dispatch_s, 6),
            "device_s": round(s.sync_s, 6),
            "ckpt_s": round(s.ckpt_s, 6),
            "compile": s.compiled,
        })
        # Under sync_every > 1, async steps record dispatch-only times and
        # the next synced step absorbs the whole window's device work —
        # neither is a per-step time by itself, so publish the window
        # average at each synced step (loss is only known there too).
        # Weighted by chunk: step_time_s is per-step, so a partial final
        # chunk must not count like a full one.
        window[0] += s.step_time_s * s.chunk
        window[1] += s.chunk
        if s.loss is not None:
            win_avg = window[0] / window[1]
            ctx.progress["last_loss"] = s.loss
            ctx.progress["last_step_time_s"] = round(win_avg, 4)
            if tokens_per_step and win_avg > 0:
                ctx.progress["tokens_per_s"] = round(
                    tokens_per_step / win_avg, 1
                )
            if not s.compiled:
                # Rolling MFU over the synced window; the compile-laden
                # first call would report a meaningless near-zero value.
                mfu = _mfu(win_avg)
                if mfu is not None:
                    ctx.progress["mfu"] = mfu
            window[0], window[1] = 0.0, 0
        if step_delay_s:
            time.sleep(step_delay_s)
        now = time.time()
        if ctx.publish is not None and (
            first_call or now - last_publish[0] > 1.0
        ):
            last_publish[0] = now
            ctx.progress["step_timeline"] = list(timeline)
            ctx.publish()
        # Hang-watchdog heartbeat: one monotonic read + float math (the
        # PERF.md ≤1µs/step budget); silence past the EMA budget is the
        # executor's hang verdict.
        # getattr: bare Ctx stubs (tests, external callers) predate both
        # fields — a missing watchdog/hang channel means "not armed".
        wd = getattr(ctx, "watchdog", None)
        if wd is not None:
            wd.beat()
        hang = getattr(ctx, "hang", None)
        if hang is not None and hang.is_set():
            # Injected gray failure (FaultInjector.inject_hang): wedge
            # cooperatively — alive, no error, no further progress —
            # until the watchdog's preemption cancels the run. Models a
            # host stuck in a collective that never returns.
            ctx.progress["hang_injected_at"] = time.time()
            ctx.cancel.wait()

    try:
        stats = trainer.run(
            batches, steps, should_stop=ctx.should_stop, on_step=on_step
        )
    finally:
        if profiling[0]:
            try:
                jax.profiler.stop_trace()
            except Exception as exc:  # noqa: BLE001 — see start_trace
                ctx.progress["profile_error"] = str(exc)
        if trainer.checkpoint is not None:
            # Orbax managers own background threads; a long-lived executor
            # runs many ticks, so every store must be released.
            trainer.checkpoint.close()
    if timeline:
        ctx.progress["step_timeline"] = list(timeline)
    # Steady-state throughput: drop the compile-laden first call.
    # Chunk-weighted: step_time_s is per-step, chunks can be non-uniform.
    tail = stats[1:] if len(stats) > 1 else stats
    n_steps = sum(s.chunk for s in tail)
    if tail and n_steps:
        avg = sum(s.step_time_s * s.chunk for s in tail) / n_steps
        ctx.progress["avg_step_time_s"] = round(avg, 4)
        ctx.progress["steps_per_s"] = round(1.0 / avg, 4) if avg > 0 else None
        if tokens_per_step and avg > 0:
            # Steady-state throughput (compile-laden first call excluded).
            ctx.progress["tokens_per_s"] = round(tokens_per_step / avg, 1)
        mfu = _mfu(avg)
        if mfu is not None:
            # Final steady-state MFU (same tail average as steps_per_s).
            ctx.progress["mfu"] = mfu
    # Dispatch-health diagnostic: async (non-synced) calls record pure
    # dispatch wall time (× chunk to undo the per-step normalization —
    # the DISPATCH is what the link taxes, however many steps it
    # carries); the median should be single-digit ms. A high p50 in an
    # artifact attributes a slow run to host/link dispatch overhead
    # (tunnel congestion, CPU starvation) rather than device compute
    # (PERF.md finding 3). The final call is excluded either way: on an
    # early exit Trainer.run charges the device drain to it, which would
    # masquerade as a giant "dispatch" sample.
    async_ms = sorted(
        s.step_time_s * s.chunk * 1e3 for s in tail[:-1] if s.loss is None
    )
    if async_ms:
        ctx.progress["async_dispatch_ms_p50"] = round(
            async_ms[len(async_ms) // 2], 2
        )
    # Per-step host data stall: with async staging this is the UN-hidden
    # remainder of batch build + device_put (≈0 when the stager keeps
    # up); synchronous staging pays the whole thing here. The companion
    # gauge to async_dispatch_ms_p50 for attributing a slow run to input
    # starvation vs dispatch overhead vs device compute.
    stall_ms = sorted(s.data_s / s.chunk * 1e3 for s in tail)
    if stall_ms:
        ctx.progress["data_stall_ms_p50"] = round(
            stall_ms[len(stall_ms) // 2], 3
        )
    # Opt-in (param.flops_accounting=1) because Trainer.flops_per_step
    # re-lowers + re-compiles the step for its cost analysis — a cache
    # hit under bench.py's persistent compile cache, but a duplicate
    # multi-ten-second XLA compile for an arbitrary scheduled job — and
    # runs AFTER training so the steps themselves never pay for it.
    if ctx.params.get("flops_accounting", "0") in ("1", "true"):
        flops = trainer.flops_per_step()
        if flops:
            # Per-device post-partitioning count: the honest MFU
            # numerator against a per-chip peak (bench.py).
            ctx.progress["xla_flops_per_step"] = flops


@register_entrypoint("mnist")
def mnist(ctx: JobContext) -> None:
    """MLP on synthetic MNIST. Params: steps(=20), batch_size(=256)."""
    steps = int(ctx.params.get("steps", 20))
    batch_size = int(ctx.params.get("batch_size", 256))
    devs = _devices(ctx)
    # default_device is thread-local; entrypoints run in executor worker
    # threads, so pin init/eager work to the requested platform here.
    with jax.default_device(devs[0]):
        mesh = _mesh(ctx, devs)
        model = MLP()
        params = _jit_init(model, jax.random.PRNGKey(0), _zeros((1, 28, 28, 1)))
        trainer = Trainer(
            lambda p, x: model.apply({"params": p}, x), params, mesh,
            TrainConfig(**_train_kwargs(
                ctx, steps, optimizer="sgd", learning_rate=0.01,
            )),
            checkpoint=_checkpoint_store(ctx),
            sample_fn=(datasets.mnist_sample(batch_size)
                       if _fused(ctx) else None),
        )
        _run(
            ctx, trainer,
            _batches(
                ctx, trainer,
                lambda: datasets.mnist_batches(batch_size),
                lambda shardings: datasets.device_mnist_batches(
                    batch_size, shardings=shardings
                ),
            ),
            steps,
        )


@register_entrypoint("resnet50")
def resnet50(ctx: JobContext) -> None:
    """ResNet-50 on synthetic ImageNet — the north-star benchmark workload.

    Params: steps(=10), batch_size(=128), image_size(=224).
    """
    steps = int(ctx.params.get("steps", 10))
    batch_size = int(ctx.params.get("batch_size", 128))
    image_size = int(ctx.params.get("image_size", 224))
    devs = _devices(ctx)
    with jax.default_device(devs[0]):
        mesh = _mesh(ctx, devs)
        model = ResNet50()
        params = _jit_init(
            model, jax.random.PRNGKey(0),
            _zeros((1, image_size, image_size, 3)),
        )
        trainer = Trainer(
            lambda p, x: model.apply({"params": p}, x), params, mesh,
            TrainConfig(**_train_kwargs(
                ctx, steps, optimizer="sgd", learning_rate=0.1,
            )),
            checkpoint=_checkpoint_store(ctx),
            sample_fn=(datasets.imagenet_sample(batch_size, image_size)
                       if _fused(ctx) else None),
        )
        _run(
            ctx, trainer,
            _batches(
                ctx, trainer,
                lambda: datasets.imagenet_batches(batch_size, image_size),
                lambda shardings: datasets.device_imagenet_batches(
                    batch_size, image_size, shardings=shardings
                ),
            ),
            steps,
        )


@register_entrypoint("bert")
def bert(ctx: JobContext) -> None:
    """BERT MLM on synthetic tokens — the long-context workload.

    Params: steps(=10), batch_size(=8), seq_len(=512), size(=base|tiny),
    attention(=auto|flash|xla|ring|ulysses), seq/tensor/fsdp mesh axes,
    remat(=0), kv_heads(=0: MHA), rope(=0|1). With ``seq`` > 1 the
    sequence axis is sharded over the mesh (ring rotates K/V, ulysses
    all-to-alls heads).
    """
    steps = int(ctx.params.get("steps", 10))
    batch_size = int(ctx.params.get("batch_size", 8))
    seq_len = int(ctx.params.get("seq_len", 512))
    size = ctx.params.get("size", "base")
    attention = ctx.params.get("attention", "auto")
    devs = _devices(ctx)
    with jax.default_device(devs[0]):
        mesh = _mesh(ctx, devs)
        maker = BertConfig.tiny if size == "tiny" else BertConfig.base
        cfg = maker(
            max_len=seq_len, attention_impl=attention,
            **_gqa_rope_kwargs(ctx),
        )
        model = Bert(cfg, mesh=mesh)
        params = _jit_init(
            model, jax.random.PRNGKey(0), _zeros((1, seq_len), dtype="int32")
        )
        trainer = Trainer(
            lambda p, x: model.apply({"params": p}, x), params, mesh,
            TrainConfig(**_train_kwargs(
                ctx, steps,
                remat=ctx.params.get("remat", "0") in ("1", "true"),
                seq_dim_in_batch=1,
                labels_follow_seq=True,
            )),
            checkpoint=_checkpoint_store(ctx),
            sample_fn=(
                datasets.token_sample(batch_size, seq_len, cfg.vocab_size)
                if _fused(ctx) else None
            ),
        )
        _run(
            ctx, trainer,
            _batches(
                ctx, trainer,
                lambda: datasets.token_batches(
                    batch_size, seq_len, cfg.vocab_size
                ),
                lambda shardings: datasets.device_token_batches(
                    batch_size, seq_len, cfg.vocab_size, shardings=shardings
                ),
            ),
            steps,
            tokens_per_step=batch_size * seq_len,
        )


@register_entrypoint("gpt")
def gpt(ctx: JobContext) -> None:
    """GPT causal LM on synthetic tokens — long-context + optional MoE.

    Params: steps(=10), batch_size(=8), seq_len(=1024), size(=base|tiny),
    attention(=auto|flash|xla|ring|ulysses), moe_every(=0: dense),
    num_experts(=8), seq/tensor/fsdp/expert mesh axes, remat(=0),
    fused_xent(=0: when 1 the loss is chunked_cross_entropy against the
    tied embedding — [b, s, vocab] logits are never materialized),
    kv_heads(=0: MHA; a divisor of num_heads enables grouped-query
    attention), rope(=0: learned absolute positions; 1 = rotary).
    Targets are next-token shifted (causal_token_batches).
    """
    steps = int(ctx.params.get("steps", 10))
    batch_size = int(ctx.params.get("batch_size", 8))
    seq_len = int(ctx.params.get("seq_len", 1024))
    size = ctx.params.get("size", "base")
    attention = ctx.params.get("attention", "auto")
    moe_every = int(ctx.params.get("moe_every", 0))
    num_experts = int(ctx.params.get("num_experts", 8))
    fused_xent = ctx.params.get("fused_xent", "0") in ("1", "true")
    devs = _devices(ctx)
    with jax.default_device(devs[0]):
        mesh = _mesh(ctx, devs)
        maker = GPTConfig.tiny if size == "tiny" else GPTConfig
        cfg = maker(
            max_len=seq_len, attention_impl=attention,
            moe_every=moe_every, num_experts=num_experts,
            return_hidden=fused_xent,
            **_gqa_rope_kwargs(ctx),
        )
        model = GPT(cfg, mesh=mesh)
        params = _jit_init(
            model, jax.random.PRNGKey(0), _zeros((1, seq_len), dtype="int32")
        )
        if fused_xent:
            from cron_operator_tpu.ops.xent import chunked_cross_entropy

            def loss_fn(out, y):
                # return_hidden mode: the model hands back (hidden,
                # tied table) itself — no param-path coupling here.
                hidden, table = out
                return chunked_cross_entropy(hidden, table, y)
        else:
            from cron_operator_tpu.workloads.train import cross_entropy_loss
            loss_fn = cross_entropy_loss

        def apply_fn(p, x):
            return model.apply({"params": p}, x)
        trainer = Trainer(
            apply_fn, params, mesh,
            TrainConfig(**_train_kwargs(
                ctx, steps,
                remat=ctx.params.get("remat", "0") in ("1", "true"),
                seq_dim_in_batch=1,
                labels_follow_seq=True,
                aux_loss_in_output=True,
            )),
            loss_fn=loss_fn,
            checkpoint=_checkpoint_store(ctx),
            sample_fn=(
                datasets.causal_token_sample(
                    batch_size, seq_len, cfg.vocab_size
                )
                if _fused(ctx) else None
            ),
        )
        _run(
            ctx, trainer,
            _batches(
                ctx, trainer,
                lambda: datasets.causal_token_batches(
                    batch_size, seq_len, cfg.vocab_size
                ),
                lambda shardings: datasets.device_causal_token_batches(
                    batch_size, seq_len, cfg.vocab_size, shardings=shardings
                ),
            ),
            steps,
            tokens_per_step=batch_size * seq_len,
        )


@register_entrypoint("vit")
def vit(ctx: JobContext) -> None:
    """ViT classification on synthetic ImageNet — attention on images.

    Params: steps(=10), batch_size(=64), image_size(=224), size(=base|tiny),
    remat(=0), kv_heads(=0: MHA), rope(=0|1: rotary over the flattened
    patch index, replacing the learned table). Attention is XLA dense —
    the (size/patch)²+1 token count is never 128-aligned, so the
    flash/sequence-parallel paths don't apply (see models/vit.py).
    """
    steps = int(ctx.params.get("steps", 10))
    batch_size = int(ctx.params.get("batch_size", 64))
    size = ctx.params.get("size", "base")
    maker = ViTConfig.tiny if size == "tiny" else ViTConfig.base
    # attention stays "auto"→xla (see docstring); GQA/RoPE ride the
    # shared encoder projection.
    cfg = maker(
        **_gqa_rope_kwargs(ctx),
    )
    image_size = int(ctx.params.get("image_size", cfg.image_size))
    if image_size != cfg.image_size:
        from dataclasses import replace

        cfg = replace(cfg, image_size=image_size)
    devs = _devices(ctx)
    with jax.default_device(devs[0]):
        mesh = _mesh(ctx, devs)
        model = ViT(cfg, mesh=mesh)
        params = _jit_init(
            model, jax.random.PRNGKey(0),
            _zeros((1, cfg.image_size, cfg.image_size, 3)),
        )
        trainer = Trainer(
            lambda p, x: model.apply({"params": p}, x), params, mesh,
            TrainConfig(**_train_kwargs(
                ctx, steps,
                remat=ctx.params.get("remat", "0") in ("1", "true"),
            )),
            checkpoint=_checkpoint_store(ctx),
            sample_fn=(
                datasets.imagenet_sample(
                    batch_size, cfg.image_size, cfg.num_classes
                )
                if _fused(ctx) else None
            ),
        )
        _run(
            ctx, trainer,
            _batches(
                ctx, trainer,
                lambda: datasets.imagenet_batches(
                    batch_size, cfg.image_size,
                    num_classes=cfg.num_classes,
                ),
                lambda shardings: datasets.device_imagenet_batches(
                    batch_size, cfg.image_size,
                    num_classes=cfg.num_classes, shardings=shardings,
                ),
            ),
            steps,
        )


@register_entrypoint("generate")
def generate_job(ctx: JobContext) -> None:
    """Scheduled batch inference: GPT KV-cache generation as a Cron
    workload (nightly eval/sampling jobs — the serving-side counterpart
    of the training entrypoints). Each round generates a batch of
    continuations from synthetic prompts; progress reports rounds and
    sustained tokens/s.

    Params: rounds(=1), batch_size(=8), prompt_len(=32), max_new(=128),
    temperature(=0 → greedy), size(=base|tiny),
    seq_len(=prompt_len+max_new: the model max_len — set it to the
    TRAINING job's seq_len when serving a checkpoint), kv_heads(=0: MHA;
    grouped-query shrinks the KV cache), rope(=0|1),
    checkpoint_from(=unset: random weights; a job/family name loads the
    latest params that training lineage checkpointed — the train-nightly
    → serve-nightly pairing; the GPTConfig params must match the
    training job's), checkpoint_dir(=the store root).
    """
    from cron_operator_tpu.workloads.generate import generate

    rounds = int(ctx.params.get("rounds", 1))
    batch_size = int(ctx.params.get("batch_size", 8))
    prompt_len = int(ctx.params.get("prompt_len", 32))
    max_new = int(ctx.params.get("max_new", 128))
    temperature = float(ctx.params.get("temperature", 0))
    size = ctx.params.get("size", "base")
    devs = _devices(ctx)
    with jax.default_device(devs[0]):
        maker = GPTConfig.tiny if size == "tiny" else GPTConfig
        # seq_len (the same param the gpt TRAINING entrypoint uses) pins
        # max_len — it must match the training config when serving a
        # checkpoint, or the pos_emb table shapes disagree at restore.
        cfg = maker(
            max_len=int(ctx.params.get("seq_len", prompt_len + max_new)),
            **_gqa_rope_kwargs(ctx),
            # Must mirror the training config when serving an MoE
            # checkpoint — a dense serve model can't hold 'moe' subtrees.
            moe_every=int(ctx.params.get("moe_every", 0)),
            num_experts=int(ctx.params.get("num_experts", 8)),
        )
        model = GPT(cfg)
        ckpt_from = ctx.params.get("checkpoint_from")
        if ckpt_from:
            # Restored weights replace init entirely — compiling and
            # materializing a random init just to discard it would waste
            # the serve tick's startup budget.
            from cron_operator_tpu.workloads.checkpoint import (
                CheckpointStore,
            )

            store = CheckpointStore(
                ctx.namespace or "default", ckpt_from,
                root=ctx.params.get("checkpoint_dir"),
                create=False,  # read-only: a typo'd name must raise
            )
            try:
                # Pin the step BEFORE restoring: a concurrent training
                # tick can save a newer step mid-restore, and reporting
                # that one would misattribute the served weights. A None
                # pin must raise here — restore_params(None) would
                # re-query and could succeed against a just-landed save
                # while we report restored_from_step=None.
                step = store.latest_step()
                if step is None:
                    raise FileNotFoundError(
                        f"lineage {ckpt_from!r} has no completed "
                        "checkpoint yet"
                    )
                params = store.restore_params(step)
                ctx.progress["restored_from_step"] = step
            finally:
                store.close()
        else:
            params = _jit_init(
                model, jax.random.PRNGKey(0),
                _zeros((1, prompt_len), dtype="int32"),
            )
        # Decode is HBM-bandwidth-bound: each decode step re-reads the
        # (bf16-cast, scan-hoisted) parameters once for the whole batch
        # plus every item's full static KV cache ([b, max_len, kv_h, d]
        # K and V per layer — masked, not length-truncated). Publish the
        # read-bytes model so consumers (bench.py) can place measured
        # tokens/s against the chip's HBM roofline.
        import jax.numpy as jnp

        n_params = sum(
            int(a.size) for a in jax.tree_util.tree_leaves(params)
        )
        kv_heads = cfg.num_kv_heads or cfg.num_heads
        head_dim = cfg.hidden_size // cfg.num_heads
        dsize = jnp.dtype(cfg.dtype).itemsize
        ctx.progress["n_params"] = n_params
        ctx.progress["decode_read_bytes_per_step"] = (
            n_params * dsize
            + 2 * cfg.num_layers * batch_size * cfg.max_len
            * kv_heads * head_dim * dsize
        )
        key = jax.random.PRNGKey(int(ctx.params.get("seed", 0)))
        ctx.progress["started_at"] = time.time()
        started_mono = time.monotonic()
        total_tokens = 0
        steady_t0 = None
        for r in range(rounds):
            if ctx.should_stop is not None and ctx.should_stop():
                break
            kp, ks = jax.random.split(jax.random.fold_in(key, r))
            prompt = jax.random.randint(
                kp, (batch_size, prompt_len), 0, cfg.vocab_size,
                dtype=jax.numpy.int32,
            )
            out = generate(
                cfg, params, prompt, max_new,
                temperature=temperature,
                rng=ks if temperature > 0 else None,
            )
            int(out[0, -1])  # value fetch = true device sync
            now = time.time()
            if r == 0:
                # Round 0 carries the compile; steady throughput starts
                # after it (mirrors the trainers' first-step convention).
                ctx.progress["first_step_at"] = now
                ctx.progress["first_step_latency_s"] = round(
                    time.monotonic() - started_mono, 6
                )
                steady_t0 = now
            else:
                total_tokens += batch_size * max_new
                elapsed = now - steady_t0
                if elapsed > 0:
                    ctx.progress["tokens_per_s"] = round(
                        total_tokens / elapsed, 1
                    )
            ctx.progress["steps_done"] = r + 1
            ctx.progress["tokens_generated"] = (
                (r + 1) * batch_size * max_new
            )
            if ctx.publish is not None:
                ctx.publish()


def _zeros(shape, dtype: Optional[str] = None):
    import jax.numpy as jnp

    return jnp.zeros(shape, dtype or jnp.float32)


__all__ = ["mnist", "resnet50", "bert", "gpt", "vit", "generate_job"]
