"""End-to-end tracing + flight recording for the control plane.

One trace id is minted when the cron controller fires a tick and rides
the workload object (annotation) and the runner env (``TPU_TRACE_ID``)
through every layer, so the operator can decompose the BASELINE north
star — ``cron_tick_to_first_step_seconds`` — into reconcile / submit /
queue / compile / first-step spans on ``/debug/traces``. Elastic resume
attempts inherit the ROOT attempt's trace id, so one preempt→resume
chain renders as a single tree with per-attempt productive vs. wasted
steps.

The :mod:`~cron_operator_tpu.telemetry.audit` journal is the discrete
counterpart: every committed store verb, controller decision, and
cluster event as one typed record, cross-checkable against the WAL
(invariant I9) and served from ``/debug/audit``.
"""

from cron_operator_tpu.telemetry.audit import (
    AUDIT_KINDS,
    AuditJournal,
    AuditRecord,
)
from cron_operator_tpu.telemetry.observatory import FleetObservatory
from cron_operator_tpu.telemetry.timeseries import (
    DEFAULT_HISTORY_FAMILIES,
    TIMESERIES_APPEND_GATE_US,
    TimeSeriesStore,
)
from cron_operator_tpu.telemetry.trace import (
    ANNOTATION_TRACE_ID,
    CRITICAL_PATH_HOPS,
    ENV_TRACE_ID,
    TRACEPARENT_HEADER,
    Span,
    TraceContext,
    Tracer,
    critical_path,
    current_trace,
    current_trace_id,
    format_traceparent,
    new_span_id,
    new_trace_id,
    parse_traceparent,
    reset_current_trace,
    set_current_trace,
    stitch_trace,
)

__all__ = [
    "ANNOTATION_TRACE_ID",
    "AUDIT_KINDS",
    "AuditJournal",
    "AuditRecord",
    "CRITICAL_PATH_HOPS",
    "DEFAULT_HISTORY_FAMILIES",
    "ENV_TRACE_ID",
    "FleetObservatory",
    "Span",
    "TIMESERIES_APPEND_GATE_US",
    "TRACEPARENT_HEADER",
    "TimeSeriesStore",
    "TraceContext",
    "Tracer",
    "critical_path",
    "current_trace",
    "current_trace_id",
    "format_traceparent",
    "new_span_id",
    "new_trace_id",
    "parse_traceparent",
    "reset_current_trace",
    "set_current_trace",
    "stitch_trace",
]
