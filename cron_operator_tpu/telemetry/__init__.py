"""End-to-end tracing for the tick→first-step path.

One trace id is minted when the cron controller fires a tick and rides
the workload object (annotation) and the runner env (``TPU_TRACE_ID``)
through every layer, so the operator can decompose the BASELINE north
star — ``cron_tick_to_first_step_seconds`` — into reconcile / submit /
queue / compile / first-step spans on ``/debug/traces``.
"""

from cron_operator_tpu.telemetry.trace import (
    ANNOTATION_TRACE_ID,
    ENV_TRACE_ID,
    Span,
    Tracer,
    new_span_id,
    new_trace_id,
)

__all__ = [
    "ANNOTATION_TRACE_ID",
    "ENV_TRACE_ID",
    "Span",
    "Tracer",
    "new_span_id",
    "new_trace_id",
]
