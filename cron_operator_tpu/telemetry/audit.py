"""Structured audit journal — the control-plane flight recorder.

Every *committed* store verb, controller decision, and cluster event is
appended as one typed :class:`AuditRecord` carrying the trace id, shard
index, and WAL position it happened under. Records land in a bounded
in-process ring (evictions are counted, never silent) plus an optional
JSONL sink, and are served from ``/debug/audit`` with filter params.

The journal is *cross-checkable against the WAL*: store-verb records are
emitted immediately after the WAL append, under the same store lock, so
their ``wal_pos`` sequence per shard must be exactly ``1..N`` with
``N == Persistence.records_appended`` — a gap means a durable write the
audit missed, a duplicate or overshoot means an audited write that never
reached the WAL. :meth:`AuditJournal.wal_check` asserts both directions
from O(1) aggregates (maintained outside the ring, so eviction cannot
blind the check); the chaos soak promotes it to invariant I9.

Record kinds:

- ``store``    — a committed API-server verb (create, update,
  patch_status, delete, cascade_delete). Semantic no-op status patches
  are elided by the store *before* the WAL and before this journal, so
  a steady-state sweep audits nothing — by design.
- ``decision`` — a controller choice: tick_fired, tick_skipped (+reason),
  submit, submit_retries_exhausted, resume, replace_delete, gc_delete,
  preempt.
- ``cluster``  — control-plane lifecycle: lease_acquired, lease_revoked,
  watch_resync, shard_failover, crash_recovery.

Everything is stdlib-only and thread-safe; :meth:`AuditJournal.record`
is a few dict ops under a lock (gated ≤ 5 µs/verb by
``hack/controlplane_bench.py``) so it can sit on the commit hot path.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

#: Record kinds the journal accepts (see module docstring).
AUDIT_KINDS = ("store", "decision", "cluster")

#: Default bound on the in-process ring. 4096 records ≈ several hundred
#: ticks of history; older records are evicted FIFO (and counted).
DEFAULT_MAX_RECORDS = 4096

# Pre-formatted metric series per kind: record() sits on the store
# commit path, so it must not pay an f-string per call.
_KIND_SERIES = {
    k: f'audit_records_total{{kind="{k}"}}' for k in AUDIT_KINDS
}


@dataclass
class AuditRecord:
    """One audited fact. ``ts`` is wall-clock epoch seconds
    (``time.time`` domain, same as trace spans, so audit records and
    spans from different components line up on one timeline)."""

    seq: int
    ts: float
    kind: str                     # store | decision | cluster
    event: str                    # verb / decision / lifecycle event
    key: str = ""                 # "apiVersion/Kind/ns/name" or ""
    trace_id: Optional[str] = None
    shard: Optional[int] = None
    wal_pos: Optional[int] = None  # records_appended after the append
    rv: Optional[int] = None       # committed resourceVersion
    reason: Optional[str] = None
    attrs: Dict[str, Any] = field(default_factory=dict)

    def to_dict(self) -> Dict[str, Any]:
        d = {
            "seq": self.seq,
            "ts": self.ts,
            "kind": self.kind,
            "event": self.event,
            "key": self.key,
            "trace_id": self.trace_id,
            "shard": self.shard,
            "wal_pos": self.wal_pos,
            "rv": self.rv,
            "reason": self.reason,
        }
        if self.attrs:
            d["attrs"] = dict(self.attrs)
        return d


def object_key(obj: Dict[str, Any]) -> str:
    """Canonical audit key for a store object."""
    meta = obj.get("metadata") or {}
    return (
        f"{obj.get('apiVersion', '')}/{obj.get('kind', '')}/"
        f"{meta.get('namespace', '')}/{meta.get('name', '')}"
    )


class AuditJournal:
    """Thread-safe bounded audit ring with WAL cross-check aggregates.

    ``sink_path`` (optional) appends every record as one JSON line — the
    durable flight-recorder tape for post-mortems; the ring alone serves
    ``/debug/audit``. ``shard`` is a default stamped on records that do
    not carry their own (a sharded plane passes per-store views via
    :meth:`shard_view`).
    """

    def __init__(
        self,
        max_records: int = DEFAULT_MAX_RECORDS,
        sink_path: Optional[str] = None,
        shard: Optional[int] = None,
        metrics=None,
    ):
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=max_records)
        self.max_records = max_records
        self.shard = shard
        self._seq = 0
        self.records_dropped = 0
        self._metrics = metrics
        # Per-(shard, kind) totals survive ring eviction — counts stay
        # exact however small the ring is.
        self._kind_totals: Dict[str, int] = {}
        # Per-shard WAL continuity aggregate: first/last position seen,
        # count, and whether every step was +1 (see wal_check).
        self._wal: Dict[Optional[int], Dict[str, Any]] = {}
        self._sink = open(sink_path, "a", encoding="utf-8") \
            if sink_path else None
        self.sink_path = sink_path
        # Optional live subscriber (telemetry/observatory.py): called
        # with every record, OUTSIDE the journal lock. One attribute
        # check on the hot path when unattached.
        self._observer = None

    # ---- recording --------------------------------------------------------

    def instrument(self, metrics) -> None:
        """Count records (and ring evictions) into a metrics registry."""
        self._metrics = metrics

    def attach_observer(self, fn) -> None:
        """Stream every record to ``fn(record)`` as it lands — the
        observatory's event intake. The callback runs outside the
        journal lock on the recording thread, so it must be fast and
        must never raise (exceptions are swallowed: accounting must not
        fail the audited operation)."""
        self._observer = fn

    def _count(self, series: str) -> None:
        if self._metrics is not None:
            self._metrics.inc(series)

    def record(
        self,
        kind: str,
        event: str,
        *,
        key: str = "",
        trace_id: Optional[str] = None,
        shard: Optional[int] = None,
        wal_pos: Optional[int] = None,
        rv: Optional[int] = None,
        reason: Optional[str] = None,
        **attrs: Any,
    ) -> AuditRecord:
        """Append one record. Hot path: called under the store lock for
        every committed verb, so it stays allocation-light."""
        if shard is None:
            shard = self.shard
        with self._lock:
            self._seq += 1
            rec = AuditRecord(
                seq=self._seq, ts=time.time(), kind=kind, event=event,
                key=key, trace_id=trace_id, shard=shard, wal_pos=wal_pos,
                rv=rv, reason=reason, attrs=attrs,
            )
            if len(self._ring) == self.max_records:
                self.records_dropped += 1
                self._count("audit_records_dropped_total")
            self._ring.append(rec)
            self._kind_totals[kind] = self._kind_totals.get(kind, 0) + 1
            if wal_pos is not None:
                w = self._wal.get(shard)
                if w is None:
                    self._wal[shard] = {
                        "first_pos": wal_pos, "last_pos": wal_pos,
                        "count": 1, "contiguous": True,
                    }
                else:
                    if wal_pos != w["last_pos"] + 1:
                        w["contiguous"] = False
                    w["last_pos"] = wal_pos
                    w["count"] += 1
            if self._sink is not None:
                self._sink.write(
                    json.dumps(rec.to_dict(), default=str) + "\n"
                )
        if self._metrics is not None:
            series = _KIND_SERIES.get(kind)
            if series is not None:
                self._metrics.inc(series)
            else:  # unknown kind — format off the hot path
                self._metrics.inc(f'audit_records_total{{kind="{kind}"}}')
            if kind == "cluster":
                # Cluster lifecycle events are rare (failover, breaker
                # flips, grow/shrink) — a per-event f-string is fine
                # off the store hot path.
                self._metrics.inc(
                    f'cluster_events_total{{event="{event}"}}'
                )
        if self._observer is not None:
            try:
                self._observer(rec)
            except Exception:  # noqa: BLE001 — see attach_observer
                pass
        return rec

    def shard_view(self, shard: int) -> "_ShardAuditView":
        """A view stamping ``shard`` on every record (one journal shared
        by a sharded plane, mirroring the ``ShardMetrics`` idiom)."""
        return _ShardAuditView(self, shard)

    # ---- reading ----------------------------------------------------------

    @property
    def total(self) -> int:
        """Records ever written (ring + evicted)."""
        with self._lock:
            return self._seq

    def kind_totals(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._kind_totals)

    def records(
        self,
        kind: Optional[str] = None,
        event: Optional[str] = None,
        trace_id: Optional[str] = None,
        shard: Optional[int] = None,
        key_contains: Optional[str] = None,
        limit: Optional[int] = None,
    ) -> List[Dict[str, Any]]:
        """Filtered view of the ring, oldest first. ``limit`` keeps the
        NEWEST matches (the useful tail of a flight recorder)."""
        with self._lock:
            out = [r.to_dict() for r in self._ring]
        if kind is not None:
            out = [r for r in out if r["kind"] == kind]
        if event is not None:
            out = [r for r in out if r["event"] == event]
        if trace_id is not None:
            out = [r for r in out if r["trace_id"] == trace_id]
        if shard is not None:
            out = [r for r in out if r["shard"] == shard]
        if key_contains is not None:
            out = [r for r in out if key_contains in r["key"]]
        if limit is not None and limit >= 0:
            out = out[-limit:]
        return out

    def render_json(self, params: Optional[Dict[str, List[str]]] = None) -> str:
        """JSON body for ``/debug/audit``. ``params`` is a parsed query
        string (``urllib.parse.parse_qs`` shape): ``kind``, ``event``,
        ``trace``, ``shard``, ``key``, ``limit`` (default 256, bounding
        the response body)."""
        params = params or {}

        def one(name: str) -> Optional[str]:
            vals = params.get(name)
            return vals[0] if vals else None

        shard: Optional[int] = None
        raw_shard = one("shard")
        if raw_shard is not None:
            try:
                shard = int(raw_shard)
            except ValueError:
                shard = None
        try:
            limit = int(one("limit") or 256)
        except ValueError:
            limit = 256
        recs = self.records(
            kind=one("kind"), event=one("event"), trace_id=one("trace"),
            shard=shard, key_contains=one("key"), limit=limit,
        )
        return json.dumps(
            {
                "total": self.total,
                "dropped": self.records_dropped,
                "kind_totals": self.kind_totals(),
                "matched": len(recs),
                "records": recs,
            },
            indent=2, default=str,
        )

    # ---- WAL cross-check (invariant I9's store leg) ------------------------

    def wal_summary(self, shard: Optional[int] = None) -> Dict[str, Any]:
        with self._lock:
            w = self._wal.get(shard if shard is not None else self.shard)
            if w is None and shard is None and len(self._wal) == 1:
                w = next(iter(self._wal.values()))
            return dict(w) if w else {
                "first_pos": None, "last_pos": None,
                "count": 0, "contiguous": True,
            }

    def reset_wal(self, shard: Optional[int] = None) -> None:
        """Forget the WAL-continuity aggregate for ``shard``.

        A failover (or crash-restart with a fresh journal-less restart)
        replaces the shard's ``Persistence``, whose position counter
        restarts at 1 — judge continuity against the NEW WAL from here.
        Callers wanting the old WAL's verdict take :meth:`wal_check`
        first; the chaos soak does exactly that at every promotion.
        """
        with self._lock:
            self._wal.pop(shard if shard is not None else self.shard, None)

    def wal_check(
        self,
        records_appended: int,
        shard: Optional[int] = None,
        crash_tail: int = 0,
    ) -> Dict[str, Any]:
        """Audit ≡ WAL, record for record, for one store's WAL.

        Passes iff the audited ``wal_pos`` stream for ``shard`` is
        exactly contiguous ``1..K`` and ``K == records_appended`` — every
        durable record was audited and every audited verb was durable.
        ``crash_tail`` tolerates up to that many WAL records *beyond* the
        audit (a kill fired between the WAL append and the commit: the
        record is on disk but the verb never committed, so the journal —
        which audits only *committed* verbs — rightly lacks it).
        """
        w = self.wal_summary(shard)
        count = w["count"]
        gap = records_appended - (w["last_pos"] or 0)
        ok = (
            w["contiguous"]
            and (count == 0 or w["first_pos"] == 1)
            and (count == 0 or w["last_pos"] == count)
            and 0 <= gap <= crash_tail
        )
        return {
            "ok": ok,
            "audited_records": count,
            "wal_records_appended": records_appended,
            "contiguous": w["contiguous"],
            "first_pos": w["first_pos"],
            "last_pos": w["last_pos"],
            "unaudited_tail": max(gap, 0),
            "crash_tail_allowed": crash_tail,
        }

    def close(self) -> None:
        with self._lock:
            if self._sink is not None:
                self._sink.close()
                self._sink = None


class _ShardAuditView:
    """Stamps a shard index on every record routed through it (the
    audit analog of ``ShardMetrics``); everything else delegates."""

    def __init__(self, journal: AuditJournal, shard: int):
        self._journal = journal
        self.shard = shard

    def record(self, kind: str, event: str, **kw: Any) -> AuditRecord:
        kw.setdefault("shard", self.shard)
        return self._journal.record(kind, event, **kw)

    def __getattr__(self, name: str):
        return getattr(self._journal, name)


__all__ = [
    "AUDIT_KINDS",
    "AuditJournal",
    "AuditRecord",
    "DEFAULT_MAX_RECORDS",
    "object_key",
]
