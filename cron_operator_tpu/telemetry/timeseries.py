"""Bounded in-process time-series store — the observatory's history layer.

Point-in-time telemetry (gauges, the 4096-record audit ring) answers
"what is happening"; nothing answered "what was v5e utilization over the
last hour". This store keeps a *bounded* history per metric series as a
ring of fixed-width buckets at several resolutions simultaneously
(multi-resolution rollup): every appended sample lands in the 1 s, 10 s
and 60 s rings at once, each ring holding (count, sum, min, max) per
bucket. Memory is O(series × Σ slots) and fixed at construction; a slot
whose wall-clock bucket has aged past the ring's horizon is overwritten
in place on the next append that maps to it — eviction IS the append,
so there is no compaction pass and no allocation on the hot path beyond
the sample's float box.

``append`` is the hot path: it runs inside :meth:`Metrics.inc` /
``set`` / ``observe`` for every family that opted into history
(``Metrics.instrument``), so it is a handful of list index ops under
one lock — gated ≤ ``TIMESERIES_APPEND_GATE_US`` by
``hack/controlplane_bench.py`` and the ``timeline`` leg of
``hack/obs_report.py``, the same discipline as the PR 8 audit-record
gate.

Snapshots are served from ``/debug/timeline?family=&series=&res=``
(:meth:`TimeSeriesStore.render_json`, the ``/debug/audit`` param
idiom). The store performs zero store/WAL I/O by construction — it
never sees the API server at all.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

#: Hot-path budget for one append across all resolutions, microseconds.
#: Mirrors hack/controlplane_bench.py's AUDIT_RECORD_GATE_US: history
#: rides the Metrics hot path, so it must stay this cheap.
TIMESERIES_APPEND_GATE_US = 5.0

#: (bucket width seconds, slot count) per resolution — finest first.
#: 1 s × 300 = 5 min of fine detail, 10 s × 360 = 1 h, 60 s × 240 = 4 h.
DEFAULT_RESOLUTIONS: Tuple[Tuple[float, int], ...] = (
    (1.0, 300),
    (10.0, 360),
    (60.0, 240),
)

#: Families the embedded operator mirrors into history by default
#: (cli cmd_start). Curated: history costs one append per sample, so
#: only the series a fleet dashboard actually plots ride along.
DEFAULT_HISTORY_FAMILIES: Tuple[str, ...] = (
    "cron_ticks_fired_total",
    "cron_missed_runs_total",
    "cron_jobs_pending",
    "cron_deadline_hits_total",
    "cron_deadline_misses_total",
    "workload_tokens_per_s",
    "workload_last_step_seconds",
    "workload_mfu",
    "fleet_utilization",
    "fleet_placements_total",
    "fleet_preemptions_total",
    "fleet_rejections_total",
    "fleet_backfills_total",
    "fleet_grows_total",
    "fleet_shrinks_total",
)

#: Default cap on distinct series — history memory must stay bounded
#: even if a caller opts a high-cardinality family in.
DEFAULT_MAX_SERIES = 256


def _res_name(width: float) -> str:
    return f"{width:g}s"


class TimeSeriesStore:
    """Thread-safe bounded multi-resolution ring store.

    One entry per series; per resolution, five parallel fixed-length
    lists (bucket index, count, sum, min, max). ``idx[slot] == -1``
    marks a never-written slot; a written slot whose stored bucket
    index differs from the incoming sample's is *stale* (its wall-clock
    window scrolled off the ring) and is reset in place — the rollup /
    eviction mechanic.
    """

    def __init__(
        self,
        resolutions: Tuple[Tuple[float, int], ...] = DEFAULT_RESOLUTIONS,
        max_series: int = DEFAULT_MAX_SERIES,
    ):
        if not resolutions:
            raise ValueError("need at least one (width, slots) resolution")
        for width, slots in resolutions:
            if width <= 0 or slots <= 0:
                raise ValueError(
                    f"invalid resolution ({width}, {slots}): width and "
                    "slot count must be positive"
                )
        self.resolutions = tuple(
            (float(w), int(n)) for w, n in sorted(resolutions)
        )
        self.max_series = max_series
        self._lock = threading.Lock()
        # series → list per resolution of [idx, count, sum, min, max]
        # parallel lists (allocated once, on first sight of the series).
        self._series: Dict[str, List[List[list]]] = {}
        self.points_total = 0
        #: Appends refused because max_series was reached (never silent).
        self.series_dropped = 0

    # ---- hot path ---------------------------------------------------------

    def append(
        self, series: str, value: float, ts: Optional[float] = None
    ) -> bool:
        """Record one sample into every resolution ring. O(1): a few
        list index ops per resolution under the lock. Returns False iff
        the series was refused (max_series cap)."""
        if ts is None:
            ts = time.time()
        v = float(value)
        with self._lock:
            rings = self._series.get(series)
            if rings is None:
                if len(self._series) >= self.max_series:
                    self.series_dropped += 1
                    return False
                rings = [
                    [[-1] * n, [0] * n, [0.0] * n, [0.0] * n, [0.0] * n]
                    for _w, n in self.resolutions
                ]
                self._series[series] = rings
            for (width, slots), (idx, cnt, tot, lo, hi) in zip(
                self.resolutions, rings
            ):
                b = int(ts // width)
                s = b % slots
                if idx[s] != b:
                    # New (or scrolled-past) bucket: overwrite in place.
                    idx[s] = b
                    cnt[s] = 1
                    tot[s] = v
                    lo[s] = v
                    hi[s] = v
                else:
                    cnt[s] += 1
                    tot[s] += v
                    if v < lo[s]:
                        lo[s] = v
                    if v > hi[s]:
                        hi[s] = v
            self.points_total += 1
        return True

    # ---- reading ----------------------------------------------------------

    def series_names(self) -> List[str]:
        with self._lock:
            return sorted(self._series)

    def families(self) -> List[str]:
        with self._lock:
            return sorted({s.split("{", 1)[0] for s in self._series})

    def resolution_names(self) -> List[str]:
        return [_res_name(w) for w, _n in self.resolutions]

    def _resolve_res(self, res: Optional[str]) -> Tuple[float, int]:
        if res is None:
            return self.resolutions[0]
        wanted = res.strip().lower().rstrip("s")
        for width, slots in self.resolutions:
            if f"{width:g}" == wanted or _res_name(width) == res:
                return (width, slots)
        raise KeyError(
            f"unknown resolution {res!r}; have "
            f"{', '.join(self.resolution_names())}"
        )

    def snapshot(
        self,
        series: str,
        res: Optional[str] = None,
        *,
        now: Optional[float] = None,
        limit: Optional[int] = None,
    ) -> List[Dict[str, Any]]:
        """Live buckets of one series at one resolution, oldest first.

        Each point: ``{"t": bucket start epoch s, "count", "sum",
        "min", "max", "mean"}``. Buckets older than the ring horizon
        (relative to ``now``) are excluded even if their slot has not
        been overwritten yet, so a quiet series does not resurface
        ancient data. ``limit`` keeps the newest points.
        """
        width, slots = self._resolve_res(res)
        ri = self.resolutions.index((width, slots))
        with self._lock:
            rings = self._series.get(series)
            if rings is None:
                return []
            idx, cnt, tot, lo, hi = (list(a) for a in rings[ri])
        if now is None:
            now = time.time()
        horizon = int(now // width) - slots + 1
        pts = [
            {
                "t": b * width,
                "count": cnt[s],
                "sum": round(tot[s], 6),
                "min": lo[s],
                "max": hi[s],
                "mean": round(tot[s] / cnt[s], 6) if cnt[s] else 0.0,
            }
            for s, b in enumerate(idx)
            if b >= 0 and b >= horizon
        ]
        pts.sort(key=lambda p: p["t"])
        if limit is not None and limit >= 0:
            pts = pts[-limit:]
        return pts

    def render_json(
        self, params: Optional[Dict[str, List[str]]] = None
    ) -> str:
        """JSON body for ``/debug/timeline``. ``params`` is a parsed
        query string (``urllib.parse.parse_qs`` shape): ``family``
        (every series of the family), ``series`` (one exact series),
        ``res`` (bucket width, e.g. ``10s`` — default the finest), and
        ``limit`` (newest points per series, default 256)."""
        params = params or {}

        def one(name: str) -> Optional[str]:
            vals = params.get(name)
            return vals[0] if vals else None

        try:
            limit = int(one("limit") or 256)
        except ValueError:
            limit = 256
        res = one("res")
        family = one("family")
        series = one("series")
        try:
            width, _slots = self._resolve_res(res)
        except KeyError as err:
            return json.dumps({"error": str(err)}, indent=2)
        if series is not None:
            names = [series]
        elif family is not None:
            names = [
                s for s in self.series_names()
                if s.split("{", 1)[0] == family
            ]
        else:
            names = self.series_names()
        body = {
            "resolutions": self.resolution_names(),
            "res": _res_name(width),
            "points_total": self.points_total,
            "series_count": len(self.series_names()),
            "series_dropped": self.series_dropped,
            "series": {
                name: self.snapshot(name, res, limit=limit)
                for name in names
            },
        }
        return json.dumps(body, indent=2, default=str)


__all__ = [
    "DEFAULT_HISTORY_FAMILIES",
    "DEFAULT_MAX_SERIES",
    "DEFAULT_RESOLUTIONS",
    "TIMESERIES_APPEND_GATE_US",
    "TimeSeriesStore",
]
