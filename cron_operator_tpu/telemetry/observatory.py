"""Fleet observatory — derived accounting over fleet/audit/watch events.

The fleet scheduler, cron controller and executor already *emit*
everything a capacity review needs (audit decision records, lineage
traces, pool bookkeeping); this module is the layer that *derives* the
answers from those streams without touching the store:

- **Utilization** per slice type: busy-chip-seconds ÷
  capacity-chip-seconds, integrated from periodic fleet samples so
  capacity flaps (``fleet_flap``/``fleet_restore``) shrink the
  denominator instead of hiding in it.
- **Deadline SLO** per Cron: hit-rate of firing within
  ``startingDeadlineSeconds``, fed by ``tick_fired`` lateness attrs and
  charged misses for StartingDeadline skips and fleet queue sheds.
- **Queue-wait distributions** per priority class, from
  ``fleet_dispatch`` records.
- **Goodput vs wasted work** per tenant, from the PR 8 lineage spans
  (``wasted_steps`` of preempted attempts).

Intake is :meth:`FleetObservatory.on_record` registered via
``AuditJournal.attach_observer`` — a pure in-memory fold over records
already being written, so the observatory adds **zero store/WAL
writes** on the steady-state path (rv-bracket asserted by
``hack/obs_report.py`` and tests). The derived report is served from
``/debug/fleet`` (:meth:`render_json`) and persisted as periodic JSONL
rollups into ``--data-dir`` so history survives restarts.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

#: Reverse map of runtime/fleet.PRIORITY_CLASSES for display buckets.
#: "batch" and "low" share a priority value; the first name wins.
_PRIORITY_NAMES = {100: "system", 50: "high", 0: "normal", -50: "batch"}

#: Audit events the observatory folds; everything else is skipped with
#: one dict lookup (the intake rides the audit hot path).
_HANDLED_EVENTS = frozenset((
    "tick_fired", "tick_skipped", "tick_shed",
    "fleet_place", "fleet_dispatch",
    "fleet_grow", "fleet_shrink",
))


def _priority_name(priority: Any) -> str:
    try:
        return _PRIORITY_NAMES.get(int(priority), str(int(priority)))
    except (TypeError, ValueError):
        return "normal"


def _quantile(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
    return sorted_vals[i]


class _DeadlineSLO:
    """Per-Cron hit/miss bookkeeping against startingDeadlineSeconds."""

    __slots__ = ("hits", "misses", "lateness")

    def __init__(self, max_samples: int):
        self.hits = 0
        self.misses = 0
        self.lateness: deque = deque(maxlen=max_samples)

    def to_dict(self) -> Dict[str, Any]:
        total = self.hits + self.misses
        late = sorted(self.lateness)
        return {
            "hits": self.hits,
            "misses": self.misses,
            "hit_rate": round(self.hits / total, 4) if total else 1.0,
            "lateness_p50_s": round(_quantile(late, 0.50), 3),
            "lateness_p99_s": round(_quantile(late, 0.99), 3),
        }


class FleetObservatory:
    """Derived fleet accounting: fold audit records, sample the fleet,
    read lineage traces — never write the store.

    All intake paths take the observatory's own lock only; rollups and
    reports are computed from the folded state plus read-only calls
    into the fleet (``stats()``/``pool``) and tracer (``traces()``).
    """

    def __init__(
        self,
        *,
        metrics: Optional[Any] = None,
        tracer: Optional[Any] = None,
        data_dir: Optional[str] = None,
        rollup_interval_s: float = 60.0,
        sample_interval_s: float = 1.0,
        max_samples: int = 512,
        max_crons: int = 4096,
    ):
        self.metrics = metrics
        self.tracer = tracer
        self.data_dir = data_dir
        self.rollup_interval_s = rollup_interval_s
        self.sample_interval_s = sample_interval_s
        self.max_samples = max_samples
        self.max_crons = max_crons

        self._lock = threading.Lock()
        self._fleet: Optional[Any] = None
        # cron "ns/name" → deadline bookkeeping (bounded: max_crons).
        self._slo: Dict[str, _DeadlineSLO] = {}
        self._slo_dropped = 0
        # priority class name → queue-wait reservoir (seconds).
        self._queue_wait: Dict[str, deque] = {}
        # workload key → tenant, for attributing lineage waste. Bounded
        # like the SLO table; dispatch refreshes recency implicitly.
        self._tenant_of: Dict[str, str] = {}
        # slice type → integrated chip-seconds since start.
        self._busy_chip_s: Dict[str, float] = {}
        self._cap_chip_s: Dict[str, float] = {}
        # Bidirectional elasticity: grow/shrink decision counts folded
        # from the audit stream, plus idle chip-seconds RECLAIMED —
        # integrated from the fleet's running grown-gang bookkeeping
        # (stats()["grown"]: extra chips each grown gang holds beyond
        # its original width).
        self._grows_seen = 0
        self._shrinks_seen = 0
        self._reclaimed_chip_s = 0.0
        self._last_sample_mono: Optional[float] = None
        self.records_seen = 0
        self.rollups_total = 0

        self._rollup_hooks: List[Callable[[], None]] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ---- wiring -----------------------------------------------------------

    def attach_fleet(self, fleet: Any) -> None:
        """Point utilization sampling at a ``FleetScheduler`` (reads
        ``pool`` and ``stats()`` only)."""
        with self._lock:
            self._fleet = fleet
            self._last_sample_mono = None

    def add_rollup_hook(self, fn: Callable[[], None]) -> None:
        """Run ``fn`` after each rollup line lands (e.g. the cli's
        throughput-matrix sidecar save). Exceptions are swallowed —
        a broken hook must not stop accounting."""
        self._rollup_hooks.append(fn)

    # ---- audit intake (hot path) ------------------------------------------

    def on_record(self, rec: Any) -> None:
        """AuditJournal observer: fold one record. Non-decision kinds
        and unhandled events return after one set lookup."""
        if rec.kind != "decision" or rec.event not in _HANDLED_EVENTS:
            return
        event = rec.event
        attrs = rec.attrs
        with self._lock:
            self.records_seen += 1
            if event == "tick_fired":
                self._fold_tick(
                    attrs.get("cron") or self._cron_from_key(rec.key),
                    attrs.get("lateness_s"), attrs.get("deadline_s"),
                )
            elif event == "tick_skipped":
                # Only deadline-driven skips are SLO misses; Forbid /
                # Replace skips are policy working as configured.
                if rec.reason == "StartingDeadline":
                    self._fold_miss(
                        attrs.get("cron") or self._cron_from_key(rec.key),
                        attrs.get("lateness_s"),
                    )
            elif event == "tick_shed":
                # Fleet queue shed: the tick will never run — a
                # deadline miss whatever the configured deadline was.
                self._fold_miss(
                    attrs.get("cron") or self._cron_from_key(rec.key),
                    attrs.get("lateness_s"),
                )
            elif event == "fleet_grow":
                self._grows_seen += 1
            elif event == "fleet_shrink":
                self._shrinks_seen += 1
            elif event == "fleet_place":
                self._remember_tenant(rec.key, attrs.get("tenant"))
            elif event == "fleet_dispatch":
                self._remember_tenant(rec.key, attrs.get("tenant"))
                wait = attrs.get("queue_wait_s")
                if wait is not None:
                    cls = _priority_name(attrs.get("priority", 0))
                    res = self._queue_wait.get(cls)
                    if res is None:
                        res = self._queue_wait[cls] = deque(
                            maxlen=self.max_samples
                        )
                    try:
                        res.append(float(wait))
                    except (TypeError, ValueError):
                        pass

    @staticmethod
    def _cron_from_key(key: str) -> str:
        # "apiVersion/Kind/ns/name" → "ns/name"; tolerate bare "ns/name".
        parts = key.rsplit("/", 2)
        return "/".join(parts[-2:]) if len(parts) >= 2 else key

    def _slo_for(self, cron: str) -> Optional[_DeadlineSLO]:
        slo = self._slo.get(cron)
        if slo is None:
            if len(self._slo) >= self.max_crons:
                self._slo_dropped += 1
                return None
            slo = self._slo[cron] = _DeadlineSLO(self.max_samples)
        return slo

    def _fold_tick(
        self, cron: str, lateness_s: Any, deadline_s: Any
    ) -> None:
        slo = self._slo_for(cron)
        if slo is None:
            return
        try:
            late = max(0.0, float(lateness_s))
        except (TypeError, ValueError):
            late = 0.0
        slo.lateness.append(late)
        hit = deadline_s is None or late <= float(deadline_s)
        if hit:
            slo.hits += 1
        else:
            slo.misses += 1
        self._count(
            "cron_deadline_hits_total" if hit
            else "cron_deadline_misses_total"
        )

    def _fold_miss(self, cron: str, lateness_s: Any) -> None:
        slo = self._slo_for(cron)
        if slo is None:
            return
        try:
            slo.lateness.append(max(0.0, float(lateness_s)))
        except (TypeError, ValueError):
            pass
        slo.misses += 1
        self._count("cron_deadline_misses_total")

    def _remember_tenant(self, key: str, tenant: Any) -> None:
        if not tenant:
            return
        if len(self._tenant_of) >= self.max_crons:
            # Evict the oldest insertion — dict order is insertion
            # order, and placement order approximates recency here.
            self._tenant_of.pop(next(iter(self._tenant_of)))
        self._tenant_of[self._cron_from_key(key)] = str(tenant)

    def _count(self, series: str) -> None:
        if self.metrics is not None:
            self.metrics.inc(series)

    # ---- utilization sampling ---------------------------------------------

    def sample_fleet(self, now_mono: Optional[float] = None) -> None:
        """Integrate busy/capacity chip-seconds from the fleet's current
        bookkeeping. Called ~every second by the observatory thread (or
        explicitly with a synthetic clock in benches/tests)."""
        if now_mono is None:
            now_mono = time.monotonic()
        with self._lock:
            fleet = self._fleet
            if fleet is None:
                return
            last = self._last_sample_mono
            self._last_sample_mono = now_mono
            stats = fleet.stats()
            free = stats.get("free", {})
            lost = stats.get("lost", {})
            if last is not None and now_mono > last:
                extra = sum((stats.get("grown") or {}).values())
                if extra:
                    self._reclaimed_chip_s += extra * (now_mono - last)
            for name, st in fleet.pool.items():
                cap = max(0, st.count - int(lost.get(name, 0)))
                busy = max(0, cap - int(free.get(name, 0)))
                util = busy / cap if cap else 0.0
                if self.metrics is not None:
                    self.metrics.set(
                        f'fleet_utilization{{slice_type="{name}"}}', util
                    )
                if last is not None and now_mono > last:
                    dt = now_mono - last
                    chips = st.chips
                    self._busy_chip_s[name] = (
                        self._busy_chip_s.get(name, 0.0) + busy * chips * dt
                    )
                    self._cap_chip_s[name] = (
                        self._cap_chip_s.get(name, 0.0) + cap * chips * dt
                    )

    # ---- reporting --------------------------------------------------------

    def report(self) -> Dict[str, Any]:
        """The derived accounting snapshot (the ``/debug/fleet`` body's
        ``observatory`` section and the rollup line's payload)."""
        with self._lock:
            util = {
                name: {
                    "busy_chip_s": round(self._busy_chip_s.get(name, 0.0), 3),
                    "capacity_chip_s": round(cap_s, 3),
                    "utilization": round(
                        self._busy_chip_s.get(name, 0.0) / cap_s, 4
                    ) if cap_s else 0.0,
                }
                for name, cap_s in sorted(self._cap_chip_s.items())
            }
            slo = {c: s.to_dict() for c, s in sorted(self._slo.items())}
            waits = {
                cls: self._wait_summary(res)
                for cls, res in sorted(self._queue_wait.items())
            }
            tenants = dict(self._tenant_of)
            elasticity = {
                "grows": self._grows_seen,
                "shrinks": self._shrinks_seen,
                "reclaimed_idle_chip_s": round(self._reclaimed_chip_s, 3),
            }
            records_seen = self.records_seen
            rollups = self.rollups_total
        hits = sum(s["hits"] for s in slo.values())
        misses = sum(s["misses"] for s in slo.values())
        return {
            "utilization": util,
            "deadline_slo": {
                "per_cron": slo,
                "hits": hits,
                "misses": misses,
                "hit_rate": round(hits / (hits + misses), 4)
                if (hits + misses) else 1.0,
            },
            "queue_wait_s": waits,
            "goodput": self._goodput(tenants),
            "elasticity": elasticity,
            "records_seen": records_seen,
            "rollups_total": rollups,
        }

    @staticmethod
    def _wait_summary(res: deque) -> Dict[str, Any]:
        vals = sorted(res)
        return {
            "count": len(vals),
            "p50_s": round(_quantile(vals, 0.50), 4),
            "p99_s": round(_quantile(vals, 0.99), 4),
            "max_s": round(vals[-1], 4) if vals else 0.0,
        }

    def _goodput(self, tenants: Dict[str, str]) -> Dict[str, Any]:
        """Per-tenant productive vs wasted steps from lineage traces.
        A resume chain's workload names carry ``-rN`` suffixes; the
        tenant map is keyed on placement-time names, so strip the
        suffix when attributing."""
        out: Dict[str, Dict[str, float]] = {}
        total_wasted = 0
        if self.tracer is None:
            return {"per_tenant": out, "wasted_steps": 0}
        for trace in self.tracer.traces():
            lineage = trace.get("lineage")
            if not lineage:
                continue
            wasted = int(lineage.get("wasted_steps", 0))
            total_wasted += wasted
            wl = ""
            for hop in lineage.get("resumes", []):
                wl = hop.get("workload") or wl
                if wl:
                    break
            base = wl.split("-r", 1)[0] if wl else ""
            tenant = tenants.get(base, tenants.get(wl, "unknown"))
            row = out.setdefault(
                tenant, {"wasted_steps": 0, "resume_chains": 0}
            )
            row["wasted_steps"] += wasted
            row["resume_chains"] += 1
        return {"per_tenant": out, "wasted_steps": total_wasted}

    def render_json(
        self, params: Optional[Dict[str, List[str]]] = None
    ) -> str:
        """JSON body for ``/debug/fleet``: the derived report plus the
        fleet's own live bookkeeping (stats + throughput matrix)."""
        del params  # reserved; route dispatch is param-aware
        body: Dict[str, Any] = {"observatory": self.report()}
        fleet = self._fleet
        if fleet is not None:
            body["fleet"] = fleet.stats()
            body["throughput_matrix"] = fleet.matrix.snapshot()
            body["pool"] = {
                name: {"count": st.count, "chips": st.chips}
                for name, st in sorted(fleet.pool.items())
            }
        return json.dumps(body, indent=2, default=str)

    # ---- rollups ----------------------------------------------------------

    @property
    def rollup_path(self) -> Optional[str]:
        if not self.data_dir:
            return None
        return os.path.join(self.data_dir, "observatory.jsonl")

    def rollup(self, now: Optional[float] = None) -> Optional[str]:
        """Append one report line to ``--data-dir/observatory.jsonl``
        (history that survives restarts), bump the counter, run hooks.
        Returns the path written, or None when no data dir is set."""
        path = self.rollup_path
        line = dict(self.report(), ts=now if now is not None else time.time())
        if path is not None:
            try:
                with open(path, "a", encoding="utf-8") as f:
                    f.write(json.dumps(line, default=str) + "\n")
            except OSError:
                path = None
        with self._lock:
            self.rollups_total += 1
        self._count("observatory_rollups_total")
        for hook in self._rollup_hooks:
            try:
                hook()
            except Exception:  # noqa: BLE001 — see add_rollup_hook
                pass
        return path

    # ---- lifecycle --------------------------------------------------------

    def start(self) -> None:
        """Own light thread: sample the fleet every
        ``sample_interval_s``, roll up every ``rollup_interval_s``."""
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="observatory", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None

    def _loop(self) -> None:
        next_rollup = time.monotonic() + self.rollup_interval_s
        while not self._stop.wait(self.sample_interval_s):
            try:
                self.sample_fleet()
                if time.monotonic() >= next_rollup:
                    self.rollup()
                    next_rollup = time.monotonic() + self.rollup_interval_s
            except Exception:  # noqa: BLE001 — accounting never crashes
                pass


__all__ = ["FleetObservatory"]
