"""Span-based tracer for the cron tick → first train step path.

The design mirrors the shape (not the wire format) of OpenTelemetry:
a *trace* is a set of spans sharing one ``trace_id``; each span has a
name, wall-clock start/end, an optional parent, and free-form string
attributes. Spans are tiny dicts-on-export, stored in a bounded
in-process deque and served as JSON from ``/debug/traces`` — enough to
answer "where did the 90 seconds go?" without any external collector.

Propagation uses the two channels the operator already has:

- ``tpu.kubedl.io/trace-id`` annotation on the workload object, stamped
  by the cron controller when the tick fires and read back by backends.
- ``TPU_TRACE_ID`` env var, rendered into the runner environment by
  ``backends.tpu.render_job_env`` so subprocess / pod runners inherit it.

Everything here is stdlib-only and thread-safe; recording is a few dict
ops under a lock, cheap enough for the reconcile hot path.
"""

from __future__ import annotations

import json
import random
import threading
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional

# Annotation on workload objects carrying the tick's trace id.
ANNOTATION_TRACE_ID = "tpu.kubedl.io/trace-id"
# Env var carrying the trace id into runner subprocesses / pods.
ENV_TRACE_ID = "TPU_TRACE_ID"

# Default bound on the finished-span store. 512 spans ≈ 100+ ticks of
# history at ~4 spans per tick; old spans are evicted FIFO.
DEFAULT_MAX_SPANS = 512


# Seeded once from the OS at import; ``getrandbits`` is a single C call
# (atomic under the GIL) and ~30× cheaper than uuid4's per-call
# ``os.urandom`` syscall — ids are minted on the reconcile hot path.
_rng = random.Random()


def new_trace_id() -> str:
    """Mint a 16-hex-char trace id (64 random bits, plenty of entropy)."""
    return f"{_rng.getrandbits(64):016x}"


def new_span_id() -> str:
    return f"{_rng.getrandbits(32):08x}"


@dataclass
class Span:
    """One timed operation inside a trace.

    ``start_s`` / ``end_s`` are wall-clock epoch seconds (``time.time``
    domain) so spans recorded in different processes line up.
    """

    name: str
    trace_id: str
    span_id: str = field(default_factory=new_span_id)
    parent_id: Optional[str] = None
    start_s: float = 0.0
    end_s: Optional[float] = None
    attrs: Dict[str, Any] = field(default_factory=dict)

    @property
    def duration_s(self) -> Optional[float]:
        if self.end_s is None:
            return None
        return max(0.0, self.end_s - self.start_s)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "start_s": self.start_s,
            "end_s": self.end_s,
            "duration_s": self.duration_s,
            "attrs": dict(self.attrs),
        }


class Tracer:
    """Thread-safe bounded store of finished spans.

    Spans only become visible (and evictable) once finished — either via
    :meth:`finish`, the :meth:`span` context manager, or :meth:`record`
    for after-the-fact spans reconstructed from timestamps the workload
    progress stream already carries (``started_at``, ``first_step_at``).
    """

    def __init__(self, max_spans: int = DEFAULT_MAX_SPANS, metrics=None):
        self._lock = threading.Lock()
        self._spans: deque = deque(maxlen=max_spans)
        self.max_spans = max_spans
        # FIFO eviction is visible, never silent: the counter (and the
        # trace_spans_dropped_total family when instrumented) says how
        # much history the bounded store has already shed.
        self.spans_dropped = 0
        self._metrics = metrics

    def instrument(self, metrics) -> None:
        """Count evictions into a metrics registry
        (``trace_spans_dropped_total``)."""
        self._metrics = metrics

    def start_span(
        self,
        name: str,
        trace_id: str,
        start_s: float,
        parent_id: Optional[str] = None,
        attrs: Optional[Dict[str, Any]] = None,
    ) -> Span:
        return Span(
            name=name,
            trace_id=trace_id,
            parent_id=parent_id,
            start_s=start_s,
            attrs=dict(attrs or {}),
        )

    def finish(self, span: Span, end_s: float) -> Span:
        span.end_s = end_s
        dropped = False
        with self._lock:
            if len(self._spans) == self.max_spans:
                self.spans_dropped += 1
                dropped = True
            self._spans.append(span)
        if dropped and self._metrics is not None:
            self._metrics.inc("trace_spans_dropped_total")
        return span

    def record(
        self,
        name: str,
        trace_id: str,
        start_s: float,
        end_s: float,
        parent_id: Optional[str] = None,
        attrs: Optional[Dict[str, Any]] = None,
    ) -> Span:
        """Record a completed span directly from two timestamps."""
        span = self.start_span(name, trace_id, start_s,
                               parent_id=parent_id, attrs=attrs)
        return self.finish(span, end_s)

    @contextmanager
    def span(
        self,
        name: str,
        trace_id: str,
        start_s: float,
        end_s_fn,
        parent_id: Optional[str] = None,
        attrs: Optional[Dict[str, Any]] = None,
    ) -> Iterator[Span]:
        """Context manager recording ``name`` around the block.

        ``end_s_fn`` is called on exit to stamp the end time, keeping the
        tracer agnostic of the caller's clock.
        """
        s = self.start_span(name, trace_id, start_s, parent_id=parent_id, attrs=attrs)
        try:
            yield s
        finally:
            self.finish(s, end_s_fn())

    def spans(self, trace_id: Optional[str] = None) -> List[Dict[str, Any]]:
        with self._lock:
            out = [s.to_dict() for s in self._spans]
        if trace_id is not None:
            out = [s for s in out if s["trace_id"] == trace_id]
        return out

    def traces(self) -> List[Dict[str, Any]]:
        """Finished spans grouped by trace id, oldest trace first. A
        trace whose spans carry resume lineage (``attempt`` attrs from
        the elastic-resume path — the root attempt's trace id is
        propagated through every ``-rN`` successor, so one preempt→
        resume chain is one trace) additionally gets a ``lineage``
        summary with per-attempt productive vs. wasted steps."""
        grouped: Dict[str, List[Dict[str, Any]]] = {}
        for s in self.spans():
            grouped.setdefault(s["trace_id"], []).append(s)
        out = []
        for tid, spans in grouped.items():
            entry: Dict[str, Any] = {
                "trace_id": tid,
                "spans": sorted(spans, key=lambda s: s["start_s"]),
            }
            lineage = _lineage(spans)
            if lineage is not None:
                entry["lineage"] = lineage
            out.append(entry)
        return out

    def render_json(self) -> str:
        """JSON body for the ``/debug/traces`` route."""
        return json.dumps(
            {"traces": self.traces(), "spans_dropped": self.spans_dropped},
            indent=2, sort_keys=False,
        )


def _lineage(spans: List[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    """Attempt-chain summary for one trace, built from ``resume`` spans.

    Each resume span is stamped by the controller with the successor's
    ``attempt`` number, the checkpoint step it resumed from, and the
    preempted predecessor's last step — so ``wasted_steps`` (steps the
    predecessor trained past its last durable checkpoint) falls straight
    out, and the goodput report can read the whole chain from one trace.
    """
    resumes = [s for s in spans if s["name"] == "resume"]
    if not resumes:
        return None
    chain = []
    for s in sorted(resumes, key=lambda s: s["attrs"].get("attempt", 0)):
        a = s["attrs"]
        try:
            pre = int(a.get("pre_steps") or 0)
            start = int(a.get("resumed_from_step") or 0)
        except (TypeError, ValueError):
            pre = start = 0
        chain.append({
            "attempt": a.get("attempt"),
            "workload": a.get("workload"),
            "resumed_from_step": start,
            "pre_steps": pre,
            "wasted_steps": max(0, pre - start),
        })
    return {
        "attempts": len(resumes) + 1,
        "resumes": chain,
        "wasted_steps": sum(c["wasted_steps"] for c in chain),
    }
