"""Span-based tracer for the cron tick → first train step path.

The design mirrors the shape (not the wire format) of OpenTelemetry:
a *trace* is a set of spans sharing one ``trace_id``; each span has a
name, wall-clock start/end, an optional parent, and free-form string
attributes. Spans are tiny dicts-on-export, stored in a bounded
in-process deque and served as JSON from ``/debug/traces`` — enough to
answer "where did the 90 seconds go?" without any external collector.

Propagation uses the two channels the operator already has:

- ``tpu.kubedl.io/trace-id`` annotation on the workload object, stamped
  by the cron controller when the tick fires and read back by backends.
- ``TPU_TRACE_ID`` env var, rendered into the runner environment by
  ``backends.tpu.render_job_env`` so subprocess / pod runners inherit it.

Everything here is stdlib-only and thread-safe; recording is a few dict
ops under a lock, cheap enough for the reconcile hot path.
"""

from __future__ import annotations

import contextvars
import json
import os
import random
import re
import threading
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, NamedTuple, Optional, Tuple

# Annotation on workload objects carrying the tick's trace id.
ANNOTATION_TRACE_ID = "tpu.kubedl.io/trace-id"
# Env var carrying the trace id into runner subprocesses / pods.
ENV_TRACE_ID = "TPU_TRACE_ID"

# HTTP header carrying the trace context between control-plane
# processes (router → shard leader). The format follows the W3C Trace
# Context ``traceparent`` shape — ``00-<32hex trace>-<16hex span>-01`` —
# with our native 64-bit trace / 32-bit span ids left-zero-padded into
# the W3C field widths on the wire and stripped back on parse.
TRACEPARENT_HEADER = "traceparent"

# Hard bound on header length before any parsing happens: the real
# format is exactly 55 chars, so anything longer is garbage (or an
# attack) and is rejected without allocating per-segment substrings.
TRACEPARENT_MAX_LEN = 64

# Default bound on the finished-span store. 512 spans ≈ 100+ ticks of
# history at ~4 spans per tick; old spans are evicted FIFO.
DEFAULT_MAX_SPANS = 512


# Seeded once from the OS at import; ``getrandbits`` is a single C call
# (atomic under the GIL) and ~30× cheaper than uuid4's per-call
# ``os.urandom`` syscall — ids are minted on the reconcile hot path.
_rng = random.Random()


def new_trace_id() -> str:
    """Mint a 16-hex-char trace id (64 random bits, plenty of entropy)."""
    return f"{_rng.getrandbits(64):016x}"


def new_span_id() -> str:
    return f"{_rng.getrandbits(32):08x}"


class TraceContext(NamedTuple):
    """The two ids that cross a process boundary: which trace the
    request belongs to, and which span on the caller's side is the
    parent of whatever the callee records."""

    trace_id: str
    span_id: str


def format_traceparent(trace_id: str, span_id: str) -> str:
    """Render a context as a W3C-shaped ``traceparent`` header value.

    Native 16-hex trace ids / 8-hex span ids are left-zero-padded to
    the W3C 32/16-hex field widths; :func:`parse_traceparent` strips
    the padding back, so the round trip is identity."""
    return f"00-{trace_id:0>32}-{span_id:0>16}-01"


def _strip_pad(hexs: str, native_len: int) -> str:
    """Undo the zero-padding ``format_traceparent`` applied, without
    ever shrinking below the native width (ids that are genuinely
    32-hex — e.g. from a foreign W3C tracer — pass through intact)."""
    pad = len(hexs) - native_len
    if pad > 0 and hexs[:pad] == "0" * pad:
        return hexs[pad:]
    return hexs


_HEX = set("0123456789abcdef")

# One-pass structural check: version 00, lowercase-hex ids at exactly
# the W3C widths, 2-hex flags. Compiled once — a single fullmatch is
# ~5× cheaper than split + per-char set membership, and parse sits on
# the per-request path of every traced frame.
_TRACEPARENT_RE = re.compile(r"00-([0-9a-f]{32})-([0-9a-f]{16})-[0-9a-f]{2}")

_ZERO_TRACE = "0" * 32
_ZERO_SPAN = "0" * 16


def parse_traceparent(value: Optional[str]) -> Optional[TraceContext]:
    """Strict parse of a ``traceparent`` header value.

    Returns ``None`` — never raises — on anything malformed: wrong
    length/segment count, unknown version, non-lowercase-hex ids,
    all-zero ids, or an oversized value (> ``TRACEPARENT_MAX_LEN``).
    A malformed header must degrade to "no trace", not kill the
    connection that carried it."""
    if not value or not isinstance(value, str):
        return None
    if len(value) > TRACEPARENT_MAX_LEN:
        return None
    m = _TRACEPARENT_RE.fullmatch(value)
    if m is None:
        return None
    trace_hex, span_hex = m.group(1), m.group(2)
    if trace_hex == _ZERO_TRACE or span_hex == _ZERO_SPAN:
        return None
    return TraceContext(_strip_pad(trace_hex, 16), _strip_pad(span_hex, 8))


# ---- ambient context ------------------------------------------------------
# The front door (apiserver_http) sets the request's context here for
# the duration of the handler, so layers with no plumbing path to the
# request — the WAL append under the store lock, the outbound client in
# cluster.py — can pick it up without threading a parameter through
# every signature. contextvars (not a thread-local) so it also survives
# executor hand-offs that copy context.

_current_trace: contextvars.ContextVar[Optional[TraceContext]] = \
    contextvars.ContextVar("cron_tpu_trace", default=None)


def current_trace() -> Optional[TraceContext]:
    """The ambient trace context, if a traced request is in flight."""
    return _current_trace.get()


def current_trace_id() -> Optional[str]:
    ctx = _current_trace.get()
    return ctx.trace_id if ctx is not None else None


def set_current_trace(ctx: Optional[TraceContext]) -> contextvars.Token:
    """Install ``ctx`` as the ambient context; pair with
    :func:`reset_current_trace` in a ``finally``."""
    return _current_trace.set(ctx)


def reset_current_trace(token: contextvars.Token) -> None:
    _current_trace.reset(token)


@dataclass
class Span:
    """One timed operation inside a trace.

    ``start_s`` / ``end_s`` are wall-clock epoch seconds (``time.time``
    domain) so spans recorded in different processes line up.
    """

    name: str
    trace_id: str
    span_id: str = field(default_factory=new_span_id)
    parent_id: Optional[str] = None
    start_s: float = 0.0
    end_s: Optional[float] = None
    attrs: Dict[str, Any] = field(default_factory=dict)

    @property
    def duration_s(self) -> Optional[float]:
        if self.end_s is None:
            return None
        return max(0.0, self.end_s - self.start_s)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "start_s": self.start_s,
            "end_s": self.end_s,
            "duration_s": self.duration_s,
            "attrs": dict(self.attrs),
        }


class Tracer:
    """Thread-safe bounded store of finished spans.

    Spans only become visible (and evictable) once finished — either via
    :meth:`finish`, the :meth:`span` context manager, or :meth:`record`
    for after-the-fact spans reconstructed from timestamps the workload
    progress stream already carries (``started_at``, ``first_step_at``).
    """

    def __init__(self, max_spans: int = DEFAULT_MAX_SPANS, metrics=None):
        self._lock = threading.Lock()
        self._spans: deque = deque(maxlen=max_spans)
        self.max_spans = max_spans
        # FIFO eviction is visible, never silent: the counter (and the
        # trace_spans_dropped_total family when instrumented) says how
        # much history the bounded store has already shed.
        self.spans_dropped = 0
        self._metrics = metrics
        # Process identity stamped onto every locally finished span so
        # fan-in can count distinct processes. Opt-in (set_proc) — the
        # embedded single-process plane keeps its spans unadorned.
        self._proc: Dict[str, Any] = {}

    def instrument(self, metrics) -> None:
        """Count evictions into a metrics registry
        (``trace_spans_dropped_total``)."""
        self._metrics = metrics

    def set_proc(self, role: Optional[str] = None, **extra: Any) -> None:
        """Stamp this process's identity (``pid`` + optional ``proc``
        role) onto every span finished here — how ``/debug/trace/<id>``
        proves a trace crossed process boundaries."""
        self._proc = {"pid": os.getpid()}
        if role:
            self._proc["proc"] = role
        self._proc.update(extra)

    def start_span(
        self,
        name: str,
        trace_id: str,
        start_s: float,
        parent_id: Optional[str] = None,
        attrs: Optional[Dict[str, Any]] = None,
    ) -> Span:
        return Span(
            name=name,
            trace_id=trace_id,
            parent_id=parent_id,
            start_s=start_s,
            attrs=dict(attrs or {}),
        )

    def finish(self, span: Span, end_s: float) -> Span:
        span.end_s = end_s
        if self._proc:
            for k, v in self._proc.items():
                span.attrs.setdefault(k, v)
        dropped = False
        with self._lock:
            if len(self._spans) == self.max_spans:
                self.spans_dropped += 1
                dropped = True
            self._spans.append(span)
        if dropped and self._metrics is not None:
            self._metrics.inc("trace_spans_dropped_total")
        return span

    def ingest(self, spans: List[Dict[str, Any]]) -> int:
        """Adopt finished spans recorded by ANOTHER process (runner
        stdout frames, shard fan-in). Each entry must look like
        :meth:`Span.to_dict` output; anything that doesn't — missing or
        non-string name/ids, unfinished, non-numeric or inverted
        timestamps — is dropped and counted
        (``trace_spans_dropped_total{reason="ingest"}``), never raised:
        a corrupt frame from a peer must not take down the ingester.
        Returns the number of spans adopted."""
        adopted = 0
        bad = 0
        for d in spans or ():
            try:
                name = d["name"]
                tid = d["trace_id"]
                start_s = float(d["start_s"])
                end_s = float(d["end_s"])
                if not (isinstance(name, str) and name
                        and isinstance(tid, str) and tid):
                    raise ValueError("bad name/trace_id")
                if end_s < start_s:
                    raise ValueError("inverted span")
                parent = d.get("parent_id")
                span_id = d.get("span_id")
                attrs = d.get("attrs") or {}
                if not isinstance(attrs, dict):
                    raise ValueError("bad attrs")
                span = Span(
                    name=name, trace_id=tid,
                    span_id=span_id if isinstance(span_id, str) and span_id
                    else new_span_id(),
                    parent_id=parent if isinstance(parent, str) else None,
                    start_s=start_s, end_s=end_s, attrs=dict(attrs),
                )
            except (KeyError, TypeError, ValueError):
                bad += 1
                continue
            dropped = False
            with self._lock:
                if len(self._spans) == self.max_spans:
                    self.spans_dropped += 1
                    dropped = True
                self._spans.append(span)
            if dropped and self._metrics is not None:
                self._metrics.inc("trace_spans_dropped_total")
            adopted += 1
        if bad:
            self.spans_dropped += bad
            if self._metrics is not None:
                for _ in range(bad):
                    self._metrics.inc(
                        'trace_spans_dropped_total{reason="ingest"}'
                    )
        return adopted

    def record(
        self,
        name: str,
        trace_id: str,
        start_s: float,
        end_s: float,
        parent_id: Optional[str] = None,
        attrs: Optional[Dict[str, Any]] = None,
    ) -> Span:
        """Record a completed span directly from two timestamps."""
        span = self.start_span(name, trace_id, start_s,
                               parent_id=parent_id, attrs=attrs)
        return self.finish(span, end_s)

    @contextmanager
    def span(
        self,
        name: str,
        trace_id: str,
        start_s: float,
        end_s_fn,
        parent_id: Optional[str] = None,
        attrs: Optional[Dict[str, Any]] = None,
    ) -> Iterator[Span]:
        """Context manager recording ``name`` around the block.

        ``end_s_fn`` is called on exit to stamp the end time, keeping the
        tracer agnostic of the caller's clock.
        """
        s = self.start_span(name, trace_id, start_s, parent_id=parent_id, attrs=attrs)
        try:
            yield s
        finally:
            self.finish(s, end_s_fn())

    def spans(self, trace_id: Optional[str] = None) -> List[Dict[str, Any]]:
        with self._lock:
            out = [s.to_dict() for s in self._spans]
        if trace_id is not None:
            out = [s for s in out if s["trace_id"] == trace_id]
        return out

    def traces(self) -> List[Dict[str, Any]]:
        """Finished spans grouped by trace id, oldest trace first. A
        trace whose spans carry resume lineage (``attempt`` attrs from
        the elastic-resume path — the root attempt's trace id is
        propagated through every ``-rN`` successor, so one preempt→
        resume chain is one trace) additionally gets a ``lineage``
        summary with per-attempt productive vs. wasted steps."""
        grouped: Dict[str, List[Dict[str, Any]]] = {}
        for s in self.spans():
            grouped.setdefault(s["trace_id"], []).append(s)
        out = []
        for tid, spans in grouped.items():
            entry: Dict[str, Any] = {
                "trace_id": tid,
                "spans": sorted(spans, key=lambda s: s["start_s"]),
            }
            lineage = _lineage(spans)
            if lineage is not None:
                entry["lineage"] = lineage
            out.append(entry)
        return out

    def render_json(
        self, params: Optional[Dict[str, List[str]]] = None
    ) -> str:
        """JSON body for the ``/debug/traces`` route. ``params`` is a
        parsed query string (``urllib.parse.parse_qs`` shape, same
        contract as ``/debug/audit``): ``trace=<id>`` selects one
        trace, ``limit=<n>`` keeps the NEWEST n traces (default 256)."""
        params = params or {}

        def one(name: str) -> Optional[str]:
            vals = params.get(name)
            return vals[0] if vals else None

        trace_id = one("trace")
        try:
            limit = int(one("limit") or 256)
        except ValueError:
            limit = 256
        traces = self.traces()
        if trace_id is not None:
            traces = [t for t in traces if t["trace_id"] == trace_id]
        if limit >= 0:
            traces = traces[-limit:]
        return json.dumps(
            {"traces": traces, "spans_dropped": self.spans_dropped},
            indent=2, sort_keys=False,
        )


def _lineage(spans: List[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    """Attempt-chain summary for one trace, built from ``resume`` spans.

    Each resume span is stamped by the controller with the successor's
    ``attempt`` number, the checkpoint step it resumed from, and the
    preempted predecessor's last step — so ``wasted_steps`` (steps the
    predecessor trained past its last durable checkpoint) falls straight
    out, and the goodput report can read the whole chain from one trace.
    """
    resumes = [s for s in spans if s["name"] == "resume"]
    if not resumes:
        return None
    chain = []
    for s in sorted(resumes, key=lambda s: s["attrs"].get("attempt", 0)):
        a = s["attrs"]
        try:
            pre = int(a.get("pre_steps") or 0)
            start = int(a.get("resumed_from_step") or 0)
        except (TypeError, ValueError):
            pre = start = 0
        chain.append({
            "attempt": a.get("attempt"),
            "workload": a.get("workload"),
            "resumed_from_step": start,
            "pre_steps": pre,
            "wasted_steps": max(0, pre - start),
        })
    return {
        "attempts": len(resumes) + 1,
        "resumes": chain,
        "wasted_steps": sum(c["wasted_steps"] for c in chain),
    }


# ---- cross-process assembly -----------------------------------------------

#: Canonical hop order of one distributed cron tick, front door to
#: training loop: router route → shard admission → store commit →
#: group-commit fsync → backend submit → workload first step.
CRITICAL_PATH_HOPS: Tuple[str, ...] = (
    "route", "admit", "commit", "fsync", "submit", "first_step",
)


def stitch_trace(
    span_lists: List[List[Dict[str, Any]]], trace_id: str
) -> Dict[str, Any]:
    """Merge per-process span exports into one trace.

    Fan-in naturally returns overlapping copies (the router holds its
    own spans AND polls every shard), so spans are deduped by span id;
    parent/child links already cross process boundaries because the
    ``traceparent`` header carries the caller's span id into the
    callee. The result lists spans sorted by start time, the distinct
    processes that contributed (from ``set_proc`` attrs), and spans
    whose parent is not in the merged set (``orphans`` — a propagation
    hole worth seeing)."""
    seen: Dict[str, Dict[str, Any]] = {}
    for spans in span_lists:
        for s in spans or ():
            if s.get("trace_id") != trace_id:
                continue
            sid = s.get("span_id") or f"anon-{len(seen)}"
            seen.setdefault(sid, s)
    spans = sorted(seen.values(), key=lambda s: s.get("start_s") or 0.0)
    ids = set(seen)
    procs = []
    for s in spans:
        a = s.get("attrs") or {}
        ident = (a.get("pid"), a.get("proc"))
        if ident != (None, None) and ident not in procs:
            procs.append(ident)
    return {
        "trace_id": trace_id,
        "spans": spans,
        "processes": [
            {"pid": pid, "proc": role} for pid, role in procs
        ],
        "orphans": [
            s["span_id"] for s in spans
            if s.get("parent_id") and s["parent_id"] not in ids
        ],
    }


def critical_path(
    spans: List[Dict[str, Any]],
    hops: Tuple[str, ...] = CRITICAL_PATH_HOPS,
) -> Dict[str, Any]:
    """Decompose one trace's wall time across the named hops.

    Boundary sweep: every time slice between consecutive span edges is
    attributed to the INNERMOST active hop (latest start wins — a
    ``commit`` running inside an ``admit`` owns its slice), and slices
    no hop covers are attributed to ``(gap)`` explicitly rather than
    vanishing. The attribution partitions ``[first start, last end]``,
    so ``total_s`` reconciles with ``wall_s`` by construction up to
    float error — ``reconciles`` is True iff that holds AND every named
    hop actually appeared (a missing hop means the trace never crossed
    that layer, which is a finding, not a rounding issue)."""
    hop_spans = [
        s for s in spans
        if s.get("name") in hops and s.get("end_s") is not None
    ]
    missing = [
        h for h in hops if not any(s["name"] == h for s in hop_spans)
    ]
    if not hop_spans:
        return {
            "hops": [], "wall_s": 0.0, "total_s": 0.0,
            "missing": missing, "reconciles": False,
        }
    t0 = min(s["start_s"] for s in hop_spans)
    t1 = max(s["end_s"] for s in hop_spans)
    edges = sorted(
        {t0, t1}
        | {s["start_s"] for s in hop_spans}
        | {s["end_s"] for s in hop_spans}
    )
    attributed: Dict[str, float] = {}
    for a, b in zip(edges, edges[1:]):
        if b <= a:
            continue
        mid = (a + b) / 2.0
        active = [
            s for s in hop_spans if s["start_s"] <= mid < s["end_s"]
        ]
        if active:
            owner = max(
                active,
                key=lambda s: (s["start_s"], hops.index(s["name"])),
            )["name"]
        else:
            owner = "(gap)"
        attributed[owner] = attributed.get(owner, 0.0) + (b - a)
    wall = t1 - t0
    total = sum(attributed.values())
    ordered = [
        {"hop": h, "seconds": attributed[h]}
        for h in (*hops, "(gap)") if h in attributed
    ]
    return {
        "hops": ordered,
        "wall_s": wall,
        "total_s": total,
        "missing": missing,
        "reconciles": (
            not missing and abs(total - wall) <= max(1e-6, 1e-6 * wall)
        ),
    }
