"""Controller layer: the cron schedule engine and the Cron reconciler.

Parity targets: ``/root/reference/internal/controller/`` (reconciler, workload
helpers) and the ``robfig/cron/v3`` standard parser the reference uses at
``cron_controller.go:392``.
"""

from cron_operator_tpu.controller.schedule import (
    CronSchedule,
    EverySchedule,
    parse_standard,
    parse_standard_cached,
)
from cron_operator_tpu.controller.cron_controller import (
    CronReconciler,
    ReconcileResult,
)

__all__ = [
    "CronSchedule",
    "EverySchedule",
    "parse_standard",
    "parse_standard_cached",
    "CronReconciler",
    "ReconcileResult",
]
